"""RetryPolicy — exponential backoff, jitter, error classification.

Replaces the reference's fixed retry-count-in-a-time-window loop
(DistriOptimizer.scala:750-752, mirrored by the old ``_with_retry``):
same windowed attempt accounting, plus

* exponential backoff with deterministic jitter between attempts — an
  immediate hot retry against a struggling filesystem or a flapping
  coordinator just loses another attempt;
* retryable-vs-fatal classification — an OOM or a shape error will
  fail identically on every replay from the same checkpoint, so
  burning the retry budget on it only delays the real report.

The ``bigdl.failure.retryTimes`` / ``bigdl.failure.retryTimeInterval``
properties keep their exact meaning as compat aliases; the backoff and
jitter knobs are new (``bigdl.failure.backoffBase`` /
``backoffMax`` / ``jitter``).
"""
from __future__ import annotations

import logging
import random
import time
from typing import Callable, Optional, Sequence, Tuple, Type

log = logging.getLogger("bigdl_tpu")


class FatalTrainingError(Exception):
    """Raise (or wrap) to mark an error as not-retryable regardless of
    the policy's type lists."""


class LossSpikeError(RuntimeError):
    """Training loss diverged (K consecutive spikes).  Retryable: the
    retry loop answers it by restoring the last good checkpoint."""


# Errors that will reproduce identically on a replay from the same
# checkpoint — retrying them burns the budget without new information.
DEFAULT_FATAL_TYPES: Tuple[Type[BaseException], ...] = (
    FatalTrainingError, MemoryError, NotImplementedError, SyntaxError,
)


def classify_error(exc: BaseException,
                   fatal_types: Sequence[Type[BaseException]]
                   = DEFAULT_FATAL_TYPES) -> str:
    """``"fatal"`` or ``"retryable"``.

    Control-flow exceptions (KeyboardInterrupt/SystemExit) are fatal —
    the user asked to stop.  Beyond the explicit fatal list everything
    defaults to retryable, preserving the reference loop's semantics
    (it retried any Exception)."""
    if isinstance(exc, (KeyboardInterrupt, SystemExit, GeneratorExit)):
        return "fatal"
    if isinstance(exc, tuple(fatal_types)):
        return "fatal"
    return "retryable"


class RetryPolicy:
    """Windowed retry with exponential backoff + jitter.

    ``max_retries`` attempts are allowed per ``window`` seconds (the
    reference's retryTimes-in-retryTimeInterval accounting: the counter
    resets when the window has elapsed since the last reset).  Delay
    before attempt ``i`` (1-based) is::

        min(backoff_base * 2**(i-1), backoff_max) * (1 + jitter*u)

    with ``u`` drawn uniformly from [-1, 1) by a deterministically
    seeded generator, so schedules reproduce run-to-run.
    """

    def __init__(self, max_retries: int = 5, window: float = 120.0,
                 backoff_base: float = 0.1, backoff_max: float = 30.0,
                 jitter: float = 0.1,
                 fatal_types: Sequence[Type[BaseException]]
                 = DEFAULT_FATAL_TYPES,
                 sleep: Callable[[float], None] = time.sleep,
                 seed: int = 0):
        self.max_retries = int(max_retries)
        self.window = float(window)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.jitter = float(jitter)
        self.fatal_types = tuple(fatal_types)
        self._sleep = sleep
        self._seed = seed
        self._rng = random.Random(seed)

    @classmethod
    def from_properties(cls, prefix: str = "bigdl.failure",
                        **overrides) -> "RetryPolicy":
        """Build from ``<prefix>.*`` properties (compat aliases
        ``retryTimes``/``retryTimeInterval`` plus the new backoff
        knobs); explicit ``overrides`` win.  The training loop reads
        ``bigdl.failure.*``; the serving path passes
        ``prefix="bigdl.serving"`` so its classification/backoff knobs
        tune independently of the trainer's."""
        from ..utils.engine import get_property

        kw = dict(
            max_retries=int(get_property(f"{prefix}.retryTimes", 5)),
            window=float(get_property(f"{prefix}.retryTimeInterval",
                                      120)),
            backoff_base=float(get_property(f"{prefix}.backoffBase",
                                            0.1)),
            backoff_max=float(get_property(f"{prefix}.backoffMax", 30)),
            jitter=float(get_property(f"{prefix}.jitter", 0.1)),
        )
        kw.update(overrides)
        return cls(**kw)

    # ------------------------------------------------------------------
    def classify(self, exc: BaseException) -> str:
        return classify_error(exc, self.fatal_types)

    def delay(self, attempt: int) -> float:
        """Jittered backoff before retry ``attempt`` (1-based).
        Consumes the policy's deterministic jitter stream."""
        base = min(self.backoff_base * (2.0 ** (attempt - 1)),
                   self.backoff_max)
        return max(0.0, base * (1.0 + self.jitter
                                * (2.0 * self._rng.random() - 1.0)))

    def schedule(self, n: int) -> list:
        """The first ``n`` delays a fresh copy of this policy would
        sleep (does not consume this policy's jitter stream)."""
        twin = RetryPolicy(self.max_retries, self.window,
                           self.backoff_base, self.backoff_max,
                           self.jitter, self.fatal_types, self._sleep,
                           seed=self._seed)
        return [twin.delay(i) for i in range(1, n + 1)]

    # ------------------------------------------------------------------
    def run(self, fn: Callable, on_retry: Optional[Callable] = None):
        """Call ``fn()`` until it returns; on a retryable error sleep
        the backoff, call ``on_retry(exc, attempt)`` (the restore hook),
        and try again.  Fatal errors and exhausted budgets re-raise."""
        attempts = 0
        window_start = time.time()
        while True:
            try:
                return fn()
            except BaseException as e:
                if self.classify(e) == "fatal":
                    raise
                if time.time() - window_start > self.window:
                    attempts = 0
                    window_start = time.time()
                attempts += 1
                if attempts > self.max_retries:
                    raise
                from ..telemetry.registry import default_registry

                default_registry().counter(
                    "bigdl_retry_attempts_total",
                    "retryable failures answered with a backoff "
                    "retry").inc()
                d = self.delay(attempts)
                log.warning(
                    "Error during training: %s — retry %d/%d after %.2fs "
                    "backoff", e, attempts, self.max_retries, d)
                if d > 0:
                    self._sleep(d)
                if on_retry is not None:
                    on_retry(e, attempts)
