"""Step fingerprints + silent-data-corruption (SDC) defense.

The reference framework's recovery story is deterministic,
coarse-grained recomputation (BigDL, arXiv:1804.05839): any lost work
can be replayed bit-for-bit from the last checkpoint.  That property
makes a second, harder failure mode tractable — a host that computes
*plausible but wrong* numbers (a flaky DIMM, a marginal MXU, a cosmic
ray): because every step is a pure function of checkpointable state,
"is this number right?" has a ground truth.  This module owns the two
mechanisms built on that:

* **Flight recorder** — :class:`FlightRecorder` keeps an append-only
  JSONL journal of cheap per-step fingerprints: the loss's exact bit
  pattern, the global gradient norm's bit pattern, a crc32c of the
  batch bytes, and (at a configurable cadence) a crc32c of the full
  parameter tree.  ~100 bytes/step of evidence; :mod:`.replay`
  re-executes from a checkpoint and diffs journals to localize the
  first divergent step.
* **Cross-host integrity votes** — at a configurable cadence every
  host publishes its parameter/gradient checksum through the elastic
  KV transport (``sdc/<step>/<host>``); in synchronous SPMD training
  every healthy host holds bit-identical post-all-gather parameters,
  so a strict majority defines truth.  A minority host is flagged as
  silently corrupting and escalated to the existing eviction +
  verified-restore path (:class:`MembershipChangedError`); when no
  strict majority exists the run stops with the fatal
  :class:`IntegrityError` — continuing without a ground truth would
  train on unknown-quality numbers.

The deterministic fault injectors driving the tests live in
:mod:`.faults` (``corrupt_gradient`` / ``flip_param_bits``); the full
protocol and cadence/overhead guidance are in ``docs/determinism.md``.
"""
from __future__ import annotations

import json
import os
import struct
from typing import Any, List, Optional

from .retry import FatalTrainingError


class IntegrityError(FatalTrainingError):
    """The cross-host integrity vote found no strict majority — there
    is no quorum to define which parameters are correct, so neither
    continuing nor evicting is sound.  Fatal: the run stops and the
    operator decides (the journals + checkpoints hold the evidence)."""


class SilentDataCorruptionError(RuntimeError):
    """THIS host's parameter checksum was flagged by a healthy-host
    majority: our own numbers are the wrong ones.  Retryable (``code``
    ``"UNAVAILABLE"``): the retry loop restores the last verified
    checkpoint, replacing the corrupt state with known-good bytes."""

    code = "UNAVAILABLE"


# ---------------------------------------------------------------------------
# fingerprint primitives
# ---------------------------------------------------------------------------

def _crc_fn():
    from .checkpoint import _native_crc

    return _native_crc()


def float_bits(x: Optional[float]) -> Optional[str]:
    """The exact IEEE-754 float64 bit pattern of ``x`` as hex —
    "equal" fingerprints mean bitwise-equal values, which is the whole
    point: an SDC that perturbs the 20th mantissa bit still diverges."""
    if x is None:
        return None
    return struct.pack("<d", float(x)).hex()


def checksum_tree(tree: Any) -> str:
    """crc32c over every leaf's raw bytes (deterministic pytree order)
    — the cheap "are these parameters/gradients bit-identical?"
    digest behind both the journal's param fingerprint and the
    cross-host vote.  Device arrays are fetched to host; call at a
    cadence, not every step, on large models."""
    import jax
    import numpy as np

    crc_fn = _crc_fn()
    crc = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        a = np.asarray(leaf)
        if not a.flags["C_CONTIGUOUS"]:
            a = np.ascontiguousarray(a)
        crc = crc_fn(a.tobytes(), crc)
    return f"{crc:08x}"


#: the batch-bytes digest is the same computation — a distinct name at
#: call sites so journals read unambiguously
batch_fingerprint = checksum_tree


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Append-only JSONL journal of per-step fingerprints.

    Two record kinds, aligned by ``step`` (= the iteration's ``neval``):

    * ``{"kind": "step", "step", "epoch", "loss", "loss_bits",
      "grad_norm", "grad_norm_bits", "batch_id", "skipped"}``
    * ``{"kind": "param", "step", "param_crc"}`` — emitted every
      ``param_crc_every`` steps (0 = only when the driver checkpoints).

    Every line is flushed as written: after a crash the journal is
    complete up to the last finished step (a torn trailing line is
    skipped by :func:`bigdl_tpu.resilience.replay.load_journal`).
    """

    def __init__(self, path: str, param_crc_every: int = 0):
        self.path = str(path)
        self.param_crc_every = int(param_crc_every)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(self.path, "a")
        self.steps_recorded = 0

    # ------------------------------------------------------------------
    def record_step(self, step: int, epoch: int, loss: float,
                    grad_norm: Optional[float] = None,
                    batch_id: Optional[str] = None,
                    skipped: bool = False):
        self._write({
            "kind": "step", "step": int(step), "epoch": int(epoch),
            "loss": float(loss), "loss_bits": float_bits(loss),
            "grad_norm": None if grad_norm is None else float(grad_norm),
            "grad_norm_bits": float_bits(grad_norm),
            "batch_id": batch_id, "skipped": bool(skipped)})
        self.steps_recorded += 1

    def wants_param_crc(self, step: int) -> bool:
        return self.param_crc_every > 0 and \
            int(step) % self.param_crc_every == 0

    def record_param(self, step: int, param_crc: str):
        self._write({"kind": "param", "step": int(step),
                     "param_crc": str(param_crc)})

    # ------------------------------------------------------------------
    def _write(self, rec: dict):
        if self._f is None:
            raise ValueError(f"record on closed FlightRecorder "
                             f"({self.path})")
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def majority_vote(votes: dict, members: List[str]):
    """Strict-majority vote over ``{host: checksum}``.

    Returns ``(truth_checksum, corrupt_hosts)`` where ``corrupt_hosts``
    are publishers disagreeing with the majority value.  Raises
    :class:`IntegrityError` when no checksum is held by a strict
    majority of ``members`` (silent hosts count against quorum — a
    2-2 split or a gang too partitioned to vote has no ground truth).
    """
    from collections import Counter

    counted = Counter(v for v in votes.values() if v is not None)
    if not counted:
        raise IntegrityError(
            f"integrity vote received no checksums from {members}")
    top, n_top = counted.most_common(1)[0]
    if 2 * n_top <= len(members):
        raise IntegrityError(
            f"no integrity quorum: {dict(counted)} across "
            f"{len(members)} member(s) — cannot decide which "
            "parameters are correct")
    corrupt = sorted(h for h, v in votes.items() if v != top)
    return top, corrupt
