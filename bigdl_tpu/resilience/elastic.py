"""Elastic multi-host training: heartbeats, membership, shrink-to-survivors.

The reference framework ships a straggler-drop knob
(``Optimizer.setDropModuleProperty``, Optimizer.scala:229-243) because
its synchronous parameter manager stalls the whole gang on one slow or
dead worker (BigDL, arXiv:1804.05839 §4; SparkNet, arXiv:1511.06051
makes tolerating slow/failed workers the key to practical cluster
training).  Spark gave it task re-execution for free; a TPU-native
trainer has no such substrate, so this module owns the cluster-level
story end to end:

* **Heartbeats + membership** — every host publishes liveness and its
  recent step time through a pluggable :class:`KVTransport`
  (:class:`InMemoryKV` for tests/benches, :class:`FileKV` over a shared
  directory so CPU CI exercises the real read/write paths;
  ``jax.distributed``'s KV store carries the same protocol on a real
  pod).  Membership is versioned by a monotonically increasing
  **incarnation** number: incarnation *n* names an exact member set,
  and every reconfiguration — shrink, eviction, regrow — is a bump to
  *n+1* that all survivors rendezvous on.
* **Shrink-to-survivors** — on a membership change every survivor
  restores the last verified checkpoint
  (:func:`~bigdl_tpu.resilience.checkpoint.verified_load` walk-back),
  rebuilds the mesh at the **largest valid shard count** for the new
  member set (:func:`largest_valid_shards`), re-shards, and resumes.
  A departed host that comes back publishes a ``rejoin`` beat and is
  re-admitted at the next incarnation boundary (**regrow**).
* **Straggler policy** — per-host step-time skew (vs the cluster
  median) is tracked from the heartbeats; chronic stragglers are warned
  about and, within the reference drop knobs' budget, voted out at an
  incarnation boundary (:class:`StragglerPolicy`).
* **Hung-collective watchdog** — :mod:`.watchdog` bounds each step so a
  dead peer mid-collective surfaces as a retryable
  ``HungCollectiveError`` instead of an eternal block.

:class:`ElasticContext` packages all of it behind the three hooks the
training drivers call (``begin_attempt`` / ``on_step_start`` /
``run_step``); ``Optimizer.set_elastic`` wires it into every mesh path.
"""
from __future__ import annotations

import json
import logging
import os
import statistics
import threading
import time
import urllib.parse
from typing import (Callable, Dict, List, Optional, Sequence, Set,
                    Tuple)

from ..telemetry.events import record_change as _record_change
from ..telemetry.registry import default_registry
from .watchdog import CollectiveWatchdog, HungCollectiveError

log = logging.getLogger("bigdl_tpu")


def _count(name: str, help: str, n: float = 1.0):
    """Bump a process-wide counter (the telemetry default registry) —
    cluster events must land in the one scrapeable snapshot whether or
    not a Telemetry bundle is attached."""
    default_registry().counter(name, help).inc(n)

__all__ = [
    "ElasticContext", "ElasticCoordinator", "FileKV", "InMemoryKV",
    "KVTransport", "MembershipChangedError", "SimulatedHost",
    "StragglerPolicy", "largest_valid_shards",
]


class MembershipChangedError(RuntimeError):
    """The cluster reconfigured (host death, eviction, or rejoin) — the
    current attempt's mesh no longer matches the membership.  Retryable
    (``code`` ``"UNAVAILABLE"``): the driver restores the last verified
    checkpoint and re-enters with the new incarnation's mesh."""

    code = "UNAVAILABLE"

    def __init__(self, message: str, incarnation: Optional[int] = None,
                 members: Sequence[str] = ()):
        super().__init__(message)
        self.incarnation = incarnation
        self.members = tuple(members)


# ---------------------------------------------------------------------------
# KV transports
# ---------------------------------------------------------------------------

class KVTransport:
    """Minimal shared-KV contract the membership protocol needs.  Real
    deployments back this with ``jax.distributed``'s coordination
    service; CI uses the two implementations below."""

    def put(self, key: str, value: str) -> None:
        raise NotImplementedError

    def get(self, key: str) -> Optional[str]:
        raise NotImplementedError

    def keys(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError


class InMemoryKV(KVTransport):
    """Dict-backed transport for single-process simulations (tests,
    the ``bench.py --elastic`` leg)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: Dict[str, str] = {}

    def put(self, key, value):
        with self._lock:
            self._data[str(key)] = str(value)

    def get(self, key):
        with self._lock:
            return self._data.get(str(key))

    def keys(self, prefix=""):
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))

    def delete(self, key):
        with self._lock:
            self._data.pop(str(key), None)


class FileKV(KVTransport):
    """Directory-backed transport: one file per key (name = the
    URL-quoted key), writes atomic via tmp + rename — the same
    discipline as the checkpoint layer, so a reader never sees a torn
    value.  Works over any shared filesystem, which is exactly what a
    multi-process CPU CI (or an NFS-backed dev pod) has."""

    def __init__(self, directory: str):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.directory,
                            urllib.parse.quote(str(key), safe=""))

    def put(self, key, value):
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            f.write(str(value))
        os.replace(tmp, path)

    def get(self, key):
        try:
            with open(self._path(key)) as f:
                return f.read()
        except OSError:
            return None

    def keys(self, prefix=""):
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if ".tmp." in name:
                continue
            key = urllib.parse.unquote(name)
            if key.startswith(prefix):
                out.append(key)
        return sorted(out)

    def delete(self, key):
        try:
            os.remove(self._path(key))
        except OSError:
            pass


# ---------------------------------------------------------------------------
# coordinator: heartbeats + incarnation-numbered membership
# ---------------------------------------------------------------------------

_HB = "hb/"
_INC = "inc"
_ACK = "ack/"
_EVICTED = "evicted/"
_SDC = "sdc/"


class BoundedLog(list):
    """A list that keeps only its newest ``maxlen`` items — the
    bounded-memory event log (keeps plain-list semantics: slicing,
    equality with lists, `json`-serializable) for accumulators that
    would otherwise grow for the life of a long run."""

    def __init__(self, maxlen: int, iterable=()):
        super().__init__(iterable)
        self.maxlen = int(maxlen)
        self._trim()

    def _trim(self):
        if len(self) > self.maxlen:
            del self[:len(self) - self.maxlen]

    def append(self, item):
        super().append(item)
        self._trim()

    def extend(self, items):
        super().extend(items)
        self._trim()


class ElasticCoordinator:
    """One host's handle on the cluster membership protocol.

    Keys (all JSON strings through the transport):

    * ``hb/<host>``      — ``{step, step_time, ts, rejoin}`` liveness beat
    * ``inc``            — ``{n, members, reason, by}`` current incarnation
    * ``ack/<n>/<host>`` — host has adopted incarnation ``n``
    * ``evicted/<host>`` — straggler eviction marker (cleared on readmit)

    ``ts`` uses this coordinator's ``clock`` — injectable so liveness
    tests need no real waiting.
    """

    def __init__(self, host: str, transport: KVTransport,
                 heartbeat_timeout: float = 2.0,
                 clock: Callable[[], float] = time.monotonic):
        self.host = str(host)
        self.transport = transport
        self.heartbeat_timeout = float(heartbeat_timeout)
        self._clock = clock

    # -- liveness -------------------------------------------------------
    def heartbeat(self, step: int = 0, step_time: Optional[float] = None,
                  rejoin: bool = False):
        self.transport.put(_HB + self.host, json.dumps({
            "host": self.host, "step": int(step),
            "step_time": step_time, "ts": self._clock(),
            "rejoin": bool(rejoin)}))

    def beats(self) -> Dict[str, dict]:
        out = {}
        for key in self.transport.keys(_HB):
            raw = self.transport.get(key)
            if raw is None:
                continue
            try:
                b = json.loads(raw)
            except ValueError:
                continue
            out[key[len(_HB):]] = b
        return out

    def alive(self, beats: Optional[Dict[str, dict]] = None) -> Set[str]:
        now = self._clock()
        beats = self.beats() if beats is None else beats
        return {h for h, b in beats.items()
                if now - float(b.get("ts", -1e18)) <= self.heartbeat_timeout}

    def leader_step(self, leader: str) -> int:
        """Published step counter of ``leader`` (0 when absent) — the
        shared clock the deterministic fault schedules key off."""
        raw = self.transport.get(_HB + leader)
        if raw is None:
            return 0
        try:
            return int(json.loads(raw).get("step", 0))
        except ValueError:
            return 0

    # -- membership -----------------------------------------------------
    def bootstrap(self, members: Sequence[str]):
        """Write incarnation 0 with the initial gang (idempotent: a
        pre-existing incarnation wins)."""
        if self.transport.get(_INC) is None:
            self.transport.put(_INC, json.dumps({
                "n": 0, "members": sorted(members),
                "reason": "bootstrap", "by": self.host}))

    def membership(self) -> Tuple[int, Tuple[str, ...]]:
        raw = self.transport.get(_INC)
        if raw is None:
            return 0, (self.host,)
        rec = json.loads(raw)
        return int(rec["n"]), tuple(rec["members"])

    def propose(self, members: Sequence[str], reason: str,
                expect: Optional[int] = None) -> Optional[int]:
        """Publish incarnation ``current+1`` with ``members``.  With
        ``expect``, only when the current incarnation still matches
        (losing a race means someone else reconfigured first — adopt
        theirs instead).  Returns the new incarnation, or None."""
        cur, _ = self.membership()
        if expect is not None and cur != expect:
            return None
        n = cur + 1
        self.transport.put(_INC, json.dumps({
            "n": n, "members": sorted(set(members)), "reason": str(reason),
            "by": self.host}))
        log.warning("elastic: proposed incarnation %d (%s) members=%s",
                    n, reason, sorted(set(members)))
        _record_change("membership_change",
                       f"incarnation={n} reason={reason} "
                       f"members={len(set(members))}",
                       source="resilience.elastic", host=self.host)
        self.ack(n)
        return n

    def ack(self, n: int):
        self.transport.put(f"{_ACK}{int(n)}/{self.host}", "1")

    def acked(self, n: int) -> Set[str]:
        prefix = f"{_ACK}{int(n)}/"
        return {k[len(prefix):] for k in self.transport.keys(prefix)}

    def rendezvous(self, n: int, members: Sequence[str],
                   timeout: float = 5.0, poll: float = 0.01,
                   sleep: Callable[[float], None] = time.sleep) -> Set[str]:
        """Wait (bounded) until every member has acked incarnation
        ``n``; returns the acked set — callers drop the laggards and
        re-propose rather than blocking forever."""
        deadline = self._clock() + float(timeout)
        want = set(members)
        while True:
            got = self.acked(n)
            if want <= got or self._clock() >= deadline:
                return got
            sleep(poll)

    # -- eviction markers ----------------------------------------------
    def evict(self, host: str, reason: str):
        self.transport.put(_EVICTED + str(host), json.dumps(
            {"reason": str(reason), "by": self.host}))
        _record_change("membership_evict", str(reason),
                       source="resilience.elastic", host=host)

    def evicted(self) -> Set[str]:
        return {k[len(_EVICTED):] for k in self.transport.keys(_EVICTED)}

    def readmit(self, host: str):
        self.transport.delete(_EVICTED + str(host))
        _record_change("membership_readmit",
                       source="resilience.elastic", host=host)


# ---------------------------------------------------------------------------
# straggler policy
# ---------------------------------------------------------------------------

class StragglerPolicy:
    """Step-time skew tracking + bounded eviction votes.

    A host is *warned* about when its published step time exceeds
    ``skew_threshold`` × the cluster median, and becomes an eviction
    *victim* after ``patience`` consecutive over-threshold observations
    — provided the ``eviction_budget`` (total evictions allowed for the
    run) is not spent.  The reference drop knobs map onto this via
    :meth:`from_drop_knobs`.

    ``relax_before_evict`` interposes the relaxed-synchrony escape
    hatch (docs/elastic.md): the first ``max_relax_rounds`` times a
    host qualifies for eviction, the policy instead WIDENS the
    effective local-SGD averaging period (:attr:`period_factor`
    multiplies each ``periodic(k)`` rule's cadence — local steps keep
    landing while the straggler lags, and the averaging collective
    that would stall on it fires less often) and gives the host a
    fresh patience window.  Only when the skew sustains past every
    relax round does :meth:`victim` fall through to the eviction vote
    — eviction becomes the last resort, not the first response.  A
    round where every relaxed host is back under threshold resets the
    factor to 1 (the schedule tightens back once the straggler
    recovers).
    """

    def __init__(self, skew_threshold: float = 3.0, patience: int = 3,
                 eviction_budget: int = 1, sustain: float = 0.0,
                 relax_before_evict: bool = False,
                 relax_factor: float = 2.0, max_relax_rounds: int = 2,
                 clock: Callable[[], float] = time.monotonic):
        if skew_threshold <= 1.0:
            raise ValueError("skew_threshold must be > 1")
        self.skew_threshold = float(skew_threshold)
        self.patience = max(1, int(patience))
        self.eviction_budget = max(0, int(eviction_budget))
        # sustain: seconds a host must STAY over threshold before it can
        # be voted out.  Observation cadence is the driver's step rate,
        # which can be far faster than peers refresh their beats — a
        # count alone would let one stale spike read as a chronic
        # straggler within milliseconds.
        self.sustain = float(sustain)
        self.relax_before_evict = bool(relax_before_evict)
        if relax_factor <= 1.0:
            raise ValueError("relax_factor must be > 1")
        self.relax_factor = float(relax_factor)
        self.max_relax_rounds = max(0, int(max_relax_rounds))
        self.relax_rounds = 0
        self.relaxed_hosts: Dict[str, int] = {}
        self._clock = clock
        self.evicted_count = 0
        self._streak: Dict[str, int] = {}
        self._since: Dict[str, float] = {}
        self.warnings: Dict[str, float] = {}

    @property
    def period_factor(self) -> float:
        """Multiplier for every ``periodic(k)`` rule's effective
        averaging period (1.0 = the configured cadence)."""
        if not self.relax_before_evict or self.relax_rounds <= 0:
            return 1.0
        return self.relax_factor ** self.relax_rounds

    @classmethod
    def from_drop_knobs(cls, drop_percentage: float,
                        max_drop_percentage: float, n_hosts: int,
                        warmup_iteration: int = 200,
                        sustain: float = 0.0
                        ) -> Optional["StragglerPolicy"]:
        """Map the reference ``setDropModuleProperty`` knobs
        (Optimizer.scala:229-243) onto the policy: ``drop_percentage``
        sets the sensitivity (skew threshold ``max(1.5,
        1/drop_percentage)`` — the larger the fraction you were willing
        to drop per sync, the lower the skew a host may sustain),
        ``max_drop_percentage`` caps the eviction budget as a fraction
        of the gang, and ``warmup_iteration`` scales the patience
        (observations before a vote, ``warmup/100``).  ``0`` disables
        (returns None), matching the reference default."""
        drop = float(drop_percentage)
        if drop <= 0:
            return None
        budget = max(1, int(round(float(max_drop_percentage or drop)
                                  * max(1, int(n_hosts)))))
        return cls(
            skew_threshold=max(1.5, 1.0 / max(drop, 0.1)),
            patience=max(1, int(warmup_iteration) // 100),
            eviction_budget=budget, sustain=sustain)

    def observe(self, step_times: Dict[str, float]) -> Dict[str, float]:
        """Feed one round of per-host step times; returns the hosts
        currently over threshold with their skew."""
        times = {h: float(t) for h, t in step_times.items()
                 if t is not None and t > 0}
        if len(times) < 2:
            return {}
        med = statistics.median(times.values())
        if med <= 0:
            return {}
        warn = {}
        now = self._clock()
        for h, t in times.items():
            skew = t / med
            if skew >= self.skew_threshold:
                if self._streak.get(h, 0) == 0:
                    self._since[h] = now
                self._streak[h] = self._streak.get(h, 0) + 1
                warn[h] = skew
            else:
                self._streak[h] = 0
                self._since.pop(h, None)
        self.warnings = warn
        if self.relaxed_hosts and not any(h in warn
                                          for h in self.relaxed_hosts):
            # every relaxed host is back under threshold: tighten the
            # averaging schedule back to its configured cadence
            self.relax_rounds = 0
            self.relaxed_hosts.clear()
        return warn

    def victim(self, exclude: Sequence[str] = ()) -> Optional[str]:
        """The host to vote out at the next incarnation boundary, or
        None (nobody chronic, budget spent, or — under
        ``relax_before_evict`` — a relax round absorbed the skew
        instead).  Chronic = over threshold for ``patience``
        consecutive observations AND ``sustain`` seconds of wall
        clock."""
        if self.evicted_count >= self.eviction_budget:
            return None
        now = self._clock()
        over = sorted(
            ((s, h) for h, s in self._streak.items()
             if s >= self.patience and h not in exclude
             and now - self._since.get(h, now) >= self.sustain),
            reverse=True)
        if not over:
            return None
        host = over[0][1]
        if self.relax_before_evict \
                and self.relax_rounds < self.max_relax_rounds:
            # widen the effective averaging period instead of voting:
            # the straggler gets a fresh patience window to catch up
            # under the cheaper schedule
            self.relax_rounds += 1
            self.relaxed_hosts[host] = self.relaxed_hosts.get(host,
                                                              0) + 1
            self._streak[host] = 0
            self._since.pop(host, None)
            return None
        return host

    def record_eviction(self, host: str):
        self.evicted_count += 1
        self._streak.pop(host, None)
        self._since.pop(host, None)


# ---------------------------------------------------------------------------
# shard-count math
# ---------------------------------------------------------------------------

def largest_valid_shards(n_hosts: int, batch_size: Optional[int] = None,
                         n_devices: Optional[int] = None) -> int:
    """Largest data-shard count a surviving gang can run: at most one
    shard per member (and per device), shrunk until it divides the
    global batch — the shrink-to-survivors mesh is always valid for the
    existing batch pipeline, never a remainder-shard special case."""
    k = max(1, int(n_hosts))
    if n_devices is not None:
        k = min(k, max(1, int(n_devices)))
    if batch_size is not None:
        while k > 1 and int(batch_size) % k != 0:
            k -= 1
    return k


# ---------------------------------------------------------------------------
# the driver-facing context
# ---------------------------------------------------------------------------

class ElasticContext:
    """Everything ``Optimizer.set_elastic`` needs, behind three hooks:

    * :meth:`begin_attempt` — start of every optimize attempt: adopt the
      current incarnation (rendezvousing with the other members when it
      changed), reset the step-time estimator, rebuild the straggler
      policy for the member set.
    * :meth:`on_step_start` — once per iteration before the batch:
      heartbeat, detect dead members / a newer incarnation / chronic
      stragglers / rejoiners, and raise
      :class:`MembershipChangedError` when the gang must reconfigure.
    * :meth:`run_step` — run the compiled step under the watchdog
      deadline (blocking on the loss so hangs are covered), feed the
      estimator, and close out recovery timing.

    Counters (`incarnation_changes`, `evictions`, watchdog ``trips``,
    ``recoveries`` wall-clock) are exported to
    :class:`~bigdl_tpu.visualization.ElasticSummary` when one is
    attached.
    """

    def __init__(self, coordinator: ElasticCoordinator, *,
                 watchdog: Optional[CollectiveWatchdog] = None,
                 straggler: Optional[StragglerPolicy] = None,
                 summary=None, mesh_factory: Optional[Callable] = None,
                 batch_size: Optional[int] = None,
                 rendezvous_timeout: float = 5.0,
                 regrow_after_steps: int = 3,
                 integrity_cadence: int = 0,
                 integrity_timeout: float = 2.0,
                 integrity_summary=None,
                 telemetry=None, telemetry_cadence: int = 10,
                 sleep: Callable[[float], None] = time.sleep):
        self.coordinator = coordinator
        self.watchdog = watchdog or CollectiveWatchdog()
        self.straggler = straggler
        self.summary = summary
        self.batch_size = batch_size
        self.rendezvous_timeout = float(rendezvous_timeout)
        self.regrow_after_steps = max(1, int(regrow_after_steps))
        # cross-host SDC vote knobs (resilience/integrity.py): every
        # ``integrity_cadence`` steps each member publishes a param
        # checksum through the transport and the strict majority defines
        # truth; 0 disables.  ``integrity_timeout`` bounds the wait for
        # peers' checksums (a silent peer counts against quorum).
        self.integrity_cadence = max(0, int(integrity_cadence))
        self.integrity_timeout = float(integrity_timeout)
        self.integrity_summary = integrity_summary
        # cross-host telemetry (bigdl_tpu/telemetry): every
        # ``telemetry_cadence`` steps this host publishes its metric/
        # goodput snapshot under ``tm/<incarnation>/<host>`` (keyed
        # like the SDC votes, so a reconfigured cluster never reads a
        # departed membership's numbers); the leader merges the gang's
        # payloads via cluster_snapshot().  Attached by
        # Optimizer.set_telemetry; 0 disables publishing.
        self.telemetry = telemetry
        self.telemetry_cadence = max(0, int(telemetry_cadence))
        self._sleep = sleep
        self._mesh_factory = mesh_factory
        self._mesh_template = None
        self._n_devices: Optional[int] = None
        self._drop_knobs: Optional[Tuple[float, float, int]] = None
        # background publisher (telemetry/publish.py): KV-transport
        # puts for telemetry snapshots and vote checksums run off the
        # step critical path, with incarnation-keyed staleness discard.
        # Built lazily; close() joins it.
        self._publisher = None
        # parameter-server embedding legs (nn/embedding_store.py):
        # every adopted membership change re-partitions each attached
        # table over the survivors before training resumes
        self._embedding_stores: List = []
        # -- state ------------------------------------------------------
        self.incarnation: Optional[int] = None
        self.members: Tuple[str, ...] = ()
        self.current_shards: Optional[int] = None
        self._last_dt: Optional[float] = None
        self._last_step = 0
        self._steps_since_change = 0
        self._fault_at: Optional[float] = None
        # -- counters ---------------------------------------------------
        # event logs are BOUNDED (keep-newest window): a week-long run
        # retains the recent window instead of growing RSS without
        # limit (the LONGRUN leak audit — a 150-min run appended ~141k
        # step_log tuples here)
        self.incarnation_changes = 0
        self.evictions = 0
        self.evicted_hosts: List[str] = BoundedLog(1024)
        self.recoveries: List[float] = BoundedLog(1024)
        self.step_log: List[Tuple[int, int, float, float]] = \
            BoundedLog(2048)
        self.shard_history: List[int] = BoundedLog(1024)
        self.sdc_votes = 0
        self.sdc_disagreements = 0
        self.sdc_evictions = 0
        self.sdc_detected_steps: List[int] = BoundedLog(1024)
        # (step, vote wall s)
        self.vote_log: List[Tuple[int, float]] = BoundedLog(2048)

    # -- configuration --------------------------------------------------
    @property
    def host(self) -> str:
        return self.coordinator.host

    def attach(self, n_devices: Optional[int] = None,
               batch_size: Optional[int] = None, mesh_template=None):
        """Driver hook: record the local device pool, the batch size
        the shrink math must respect, and the mesh TEMPLATE whose
        non-data axes a shrink must keep (ISSUE 8: a shrink on a
        data x model [x pipe] mesh re-derives a mesh that still
        tensor/pipeline-parallelizes instead of silently degrading to
        data-only)."""
        if n_devices is not None:
            self._n_devices = int(n_devices)
        if batch_size is not None:
            self.batch_size = int(batch_size)
        if mesh_template is not None:
            self._mesh_template = mesh_template
        return self

    def configure_straggler_from_knobs(self, drop_percentage: float,
                                       max_drop_percentage: float,
                                       warmup_iteration: int = 200):
        """Install the reference drop knobs; the concrete policy is
        (re)built per incarnation so the budget scales with the live
        member count."""
        self._drop_knobs = (float(drop_percentage),
                            float(max_drop_percentage),
                            int(warmup_iteration))
        return self

    # -- parameter-server embedding legs ---------------------------------
    def attach_embedding_store(self, store):
        """Register this host's
        :class:`~bigdl_tpu.nn.embedding_store.EmbeddingStore` leg: on
        every adopted membership change the context re-partitions the
        table over the survivors (sealed, crc32c-verified shards over
        the SAME KV transport the membership protocol rides — the
        store inherits the coordinator's transport if it has none), so
        the optimize retry that follows
        :class:`MembershipChangedError` resumes against re-owned,
        verified rows — no step trains on a torn table."""
        if store.kv is None:
            store.kv = self.coordinator.transport
        self._embedding_stores.append(store)
        return self

    def _repartition_stores(self):
        for store in self._embedding_stores:
            if store.members == self.members:
                continue
            dead = set(store.members) - set(self.members)
            stats = store.repartition(self.members, dead=dead,
                                      sleep=self._sleep)
            log.warning(
                "elastic: embedding table %r re-partitioned to "
                "version %d over %d member(s) — %d block(s) in, "
                "%d out, %d row(s) moved (%d from checkpointed legs)",
                store.table, stats["version"], len(self.members),
                stats["imported_blocks"], stats["exported_blocks"],
                stats["moved_rows"], stats["recovered_from_checkpoint"])

    def counters(self) -> dict:
        return {
            "incarnation": self.incarnation,
            "members": list(self.members),
            "incarnation_changes": self.incarnation_changes,
            "evictions": self.evictions,
            "evicted_hosts": list(self.evicted_hosts),
            "watchdog_trips": self.watchdog.trips,
            "recoveries_s": list(self.recoveries),
            "shard_history": list(self.shard_history),
            "sdc_votes": self.sdc_votes,
            "sdc_disagreements": self.sdc_disagreements,
            "sdc_evictions": self.sdc_evictions,
            "sdc_detected_steps": list(self.sdc_detected_steps),
        }

    # -- mesh -----------------------------------------------------------
    def current_mesh(self):
        """The mesh this incarnation trains on: largest valid DATA
        shard count for the member set over the local device pool,
        with the attached template's non-data axes (model/seq/pipe)
        kept at full size — shrink/regrow is one mesh(+plan)
        re-derivation for ANY mesh shape (the factory defaults to
        :func:`parallel.spmd.survivor_mesh`)."""
        import jax

        n_dev = self._n_devices or len(jax.devices())
        template = self._mesh_template
        rest = 1
        if template is not None:
            for a in template.axis_names:
                if a != "data":
                    rest *= int(template.shape[a])
        k = largest_valid_shards(len(self.members) or 1,
                                 self.batch_size,
                                 max(1, n_dev // rest))
        self.current_shards = k
        self.shard_history.append(k)
        if self._mesh_factory is not None:
            return self._mesh_factory(k)
        from ..parallel.spmd import survivor_mesh

        return survivor_mesh(k, template=template)

    # -- lifecycle hooks -------------------------------------------------
    def begin_attempt(self):
        c = self.coordinator
        c.heartbeat(step=self._last_step, step_time=self._last_dt)
        n, members = c.membership()
        if self.incarnation is None:
            # first attach: adopt the bootstrap incarnation quietly
            c.ack(n)
            self._adopt(n, members, count=False)
        elif n != self.incarnation:
            c.ack(n)
            for _ in range(3):
                got = c.rendezvous(n, members,
                                   timeout=self.rendezvous_timeout,
                                   sleep=self._sleep)
                missing = set(members) - got
                if not missing:
                    break
                # laggards are suspects too: shrink past them rather
                # than blocking the survivors
                log.warning("elastic: rendezvous %d timed out waiting "
                            "for %s — proposing without them",
                            n, sorted(missing))
                survivors = [m for m in members if m not in missing]
                n2 = c.propose(survivors, "rendezvous timeout", expect=n)
                if n2 is None:
                    n, members = c.membership()
                    c.ack(n)
                else:
                    n, members = n2, tuple(sorted(survivors))
            self._adopt(n, members, count=True)
        # membership settled for this attempt
        if self._drop_knobs is not None:
            # rebuilt per incarnation so the budget scales with the live
            # gang; a vote needs skew sustained past two heartbeat
            # timeouts — one stale spike must never read as chronic
            self.straggler = StragglerPolicy.from_drop_knobs(
                self._drop_knobs[0], self._drop_knobs[1],
                n_hosts=len(self.members),
                warmup_iteration=self._drop_knobs[2],
                sustain=2.0 * self.coordinator.heartbeat_timeout)
            if self.straggler is not None:
                # the eviction budget is a RUN budget, not a
                # per-incarnation allowance — carry the spend forward
                self.straggler.evicted_count = self.evictions
        self.watchdog.estimator.reset()
        self._steps_since_change = 0

    def _adopt(self, n: int, members: Sequence[str], count: bool):
        self.incarnation = int(n)
        self.members = tuple(sorted(members))
        if count:
            self.incarnation_changes += 1
            _count("bigdl_elastic_incarnation_changes_total",
                   "cluster membership reconfigurations adopted")
        log.warning("elastic: running incarnation %d with %d member(s) %s",
                    self.incarnation, len(self.members), self.members)
        self._repartition_stores()
        self._scalar("Incarnation", self.incarnation)
        self._scalar("ClusterSize", len(self.members))

    def publisher(self):
        """The lazily-built background publisher (one per context);
        staleness is judged against this context's live incarnation."""
        from ..telemetry.publish import BackgroundPublisher

        if self._publisher is None:
            self._publisher = BackgroundPublisher(
                incarnation_of=lambda: self.incarnation or 0)
        return self._publisher

    def publish_telemetry(self, step: int):
        """Publish this host's telemetry payload for the current
        incarnation (no-op without an attached Telemetry).  The
        payload snapshot AND the transport put both run on the
        background publisher — KV I/O never blocks a step; a payload
        queued under an incarnation that has since died is discarded
        instead of published (stale snapshots must not haunt the new
        membership's view)."""
        if self.telemetry is None:
            return
        from ..telemetry.aggregate import publish_snapshot

        tm, transport, host = (self.telemetry,
                               self.coordinator.transport, self.host)
        inc = self.incarnation or 0
        tm.incarnation = inc

        def publish():
            publish_snapshot(transport, host, tm.payload(step),
                             incarnation=inc)

        if not self.publisher().submit(publish, incarnation=inc,
                                       key="tm"):
            publish()  # publisher closed: degrade to synchronous

    def cluster_snapshot(self) -> dict:
        """The leader's merged cluster telemetry view: newest payload
        per CURRENT member for the current incarnation, folded by
        :func:`~bigdl_tpu.telemetry.merge_cluster` (counters sum,
        histogram buckets add, goodput ledgers sum host-seconds)."""
        from ..telemetry.aggregate import collect_snapshots, merge_cluster

        self.publish_telemetry(self._last_step)
        if self._publisher is not None:
            # the reader's barrier: our own freshest payload must be
            # visible before the collect
            self._publisher.drain()
        payloads = collect_snapshots(
            self.coordinator.transport, self.incarnation or 0,
            members=self.members or None)
        return merge_cluster(payloads)

    def close(self):
        """Join the background publisher (flushing queued payloads).
        The context stays usable — publishing after close degrades to
        synchronous puts."""
        if self._publisher is not None:
            self._publisher.close()

    def on_step_start(self, step: int):
        c = self.coordinator
        self._last_step = int(step)
        c.heartbeat(step=step, step_time=self._last_dt)
        if self.telemetry is not None and self.telemetry_cadence > 0 \
                and step % self.telemetry_cadence == 0:
            self.publish_telemetry(step)
        n, members = c.membership()
        if self.incarnation is None:
            c.ack(n)
            self._adopt(n, members, count=False)
        elif n != self.incarnation:
            # someone else reconfigured: fall back to the retry loop,
            # which restores and re-enters through begin_attempt
            self._mark_fault()
            raise MembershipChangedError(
                f"incarnation moved {self.incarnation} -> {n}",
                incarnation=n, members=members)
        beats = c.beats()
        alive = c.alive(beats)
        dead = [m for m in self.members if m != c.host and m not in alive]
        if dead:
            survivors = [m for m in self.members if m not in dead]
            n2 = c.propose(survivors, f"hosts presumed dead: {dead}",
                           expect=n)
            self._mark_fault()
            raise MembershipChangedError(
                f"host(s) {dead} stopped heartbeating — shrinking to "
                f"{survivors}", incarnation=n2, members=survivors)
        # let the incarnation's compile transient settle before judging
        # skew — the first step of a fresh program runs seconds of XLA
        # compilation that would read as the leader straggling
        if self._steps_since_change >= 2:
            self._check_stragglers(beats, alive, n)
        self._steps_since_change += 1
        if self._steps_since_change >= self.regrow_after_steps:
            barred = c.evicted()
            rejoiners = sorted(
                h for h, b in beats.items()
                if h not in self.members and h in alive
                and b.get("rejoin") and h not in barred)
            if rejoiners:
                # an evicted straggler stays barred until something
                # clears its marker (coordinator.readmit — the host
                # itself once it has recovered, or an operator)
                grown = sorted(set(self.members) | set(rejoiners))
                n2 = c.propose(grown, f"rejoin: {rejoiners}", expect=n)
                # regrow is planned, not a fault: no recovery clock
                raise MembershipChangedError(
                    f"host(s) {rejoiners} rejoined — regrowing to {grown}",
                    incarnation=n2, members=grown)

    def _check_stragglers(self, beats: Dict[str, dict], alive: Set[str],
                          n: int):
        if self.straggler is None:
            return
        # only LIVE members are judged for skew: a freshly dead host's
        # frozen last beat is the death path's business, not a
        # straggler vote's
        times = {h: beats[h].get("step_time") for h in self.members
                 if h in beats and h in alive}
        warn = self.straggler.observe(times)
        for h, skew in warn.items():
            log.warning("elastic: straggler %s at %.1fx the cluster "
                        "median step time (threshold %.1fx)", h, skew,
                        self.straggler.skew_threshold)
            self._scalar("StragglerSkew", skew)
        before = self.sync_relax_factor()
        victim = self.straggler.victim(exclude=(self.coordinator.host,))
        if victim is None:
            after = self.sync_relax_factor()
            if after != before:
                log.warning(
                    "elastic: relax-before-evict widened the effective "
                    "sync averaging period x%.1f (round %d/%d) instead "
                    "of voting out the straggler — eviction is the "
                    "last resort", after,
                    self.straggler.relax_rounds,
                    self.straggler.max_relax_rounds)
                self._scalar("SyncRelaxFactor", after)
            return
        c = self.coordinator
        self.straggler.record_eviction(victim)
        self.evictions += 1
        _count("bigdl_elastic_evictions_total",
               "hosts voted out (stragglers + SDC minorities)")
        self.evicted_hosts.append(victim)
        c.evict(victim, "chronic straggler")
        survivors = [m for m in self.members if m != victim]
        n2 = c.propose(survivors, f"evicted straggler {victim}", expect=n)
        self._scalar("Evictions", self.evictions)
        self._mark_fault()
        raise MembershipChangedError(
            f"straggler {victim} voted out — shrinking to {survivors}",
            incarnation=n2, members=survivors)

    def sync_relax_factor(self) -> float:
        """The live relaxed-synchrony period multiplier the driver
        consults every iteration: 1.0 normally; >1 while the straggler
        policy's ``relax_before_evict`` rounds are widening the
        effective ``periodic(k)`` averaging cadence (docs/elastic.md)."""
        s = self.straggler
        return float(getattr(s, "period_factor", 1.0)) \
            if s is not None else 1.0

    def run_step(self, dispatch: Callable, step: int):
        """Run one compiled step under the watchdog.  ``dispatch`` is
        the driver's zero-arg jitted call; the worker blocks on the
        returned loss so a hang between dispatch and the value fetch is
        inside the deadline."""
        host = self.coordinator.host

        def body(cancel):
            from . import faults

            faults.check_elastic_fault(host, step, cancel)
            out = dispatch()
            import jax

            jax.block_until_ready(out[0])
            return out

        t0 = time.monotonic()
        try:
            out = self.watchdog.run(body)
        except HungCollectiveError:
            self._mark_fault()
            self._scalar("WatchdogTrips", self.watchdog.trips)
            raise
        dt = time.monotonic() - t0
        self._last_dt = dt
        self.step_log.append((self.incarnation or 0, int(step),
                              time.monotonic(), dt))
        if self._fault_at is not None:
            rec = time.monotonic() - self._fault_at
            self._fault_at = None
            self.recoveries.append(rec)
            log.warning("elastic: recovered in %.2fs (incarnation %d, "
                        "step %d)", rec, self.incarnation or 0, step)
            self._scalar("RecoverySeconds", rec)
        return out

    # -- cross-host integrity votes (resilience/integrity.py) -----------
    def integrity_vote(self, step: int, checksum: str):
        """One SDC vote round: publish this host's param checksum under
        ``sdc/<step>/<host>``, bounded-wait for the other members',
        and let the strict majority define truth
        (:func:`~bigdl_tpu.resilience.integrity.majority_vote`).

        * a corrupt PEER → evicted + membership proposal without it →
          retryable :class:`MembershipChangedError` (the survivors
          restore the verified checkpoint and shrink — the same path
          a dead host takes, because a silently-wrong host is worse
          than a dead one);
        * a corrupt SELF → retryable
          :class:`~bigdl_tpu.resilience.integrity
          .SilentDataCorruptionError` (restore replaces our bad state
          with known-good bytes);
        * no strict majority → fatal
          :class:`~bigdl_tpu.resilience.integrity.IntegrityError`.
        """
        from .integrity import SilentDataCorruptionError, majority_vote

        c = self.coordinator
        # rounds are keyed by incarnation AND step: a post-restore replay
        # of the same step is a FRESH round — peers' answers from before
        # the membership change must never count against it (the restore
        # legitimately changes the bits: fewer shards, different
        # reduction order)
        prefix = f"{_SDC}{self.incarnation}/{int(step)}/"
        # our own vote publishes through the background publisher too
        # (urgent: this round's bounded wait below is watching for it),
        # so a slow KV transport never stalls the step loop beyond the
        # vote round itself
        vote_key, vote_value = prefix + c.host, str(checksum)
        if not self.publisher().submit(
                lambda: c.transport.put(vote_key, vote_value),
                incarnation=self.incarnation, urgent=True):
            c.transport.put(vote_key, vote_value)
        want = set(self.members) or {c.host}
        t0 = time.monotonic()
        deadline = t0 + self.integrity_timeout
        while True:
            votes = {}
            for key in c.transport.keys(prefix):
                host = key[len(prefix):]
                if host in want:
                    votes[host] = c.transport.get(key)
            if want <= set(votes) or time.monotonic() >= deadline:
                break
            self._sleep(0.005)
        self.sdc_votes += 1
        _count("bigdl_integrity_votes_total",
               "cross-host SDC checksum vote rounds")
        self.vote_log.append((int(step), time.monotonic() - t0))
        self._iscalar("IntegrityVotes", self.sdc_votes, step)
        truth, corrupt = majority_vote(votes, sorted(want))
        if not corrupt:
            return
        self.sdc_disagreements += 1
        _count("bigdl_integrity_disagreements_total",
               "SDC vote rounds that flagged a minority checksum")
        self.sdc_detected_steps.append(int(step))
        self._iscalar("IntegrityDisagreements", self.sdc_disagreements,
                      step)
        log.warning("elastic: integrity vote at step %d flagged %s "
                    "(majority checksum %s, votes %s)", step, corrupt,
                    truth, votes)
        if c.host in corrupt:
            self._mark_fault()
            raise SilentDataCorruptionError(
                f"this host's parameter checksum {votes.get(c.host)} "
                f"was flagged against the {truth} majority at step "
                f"{step} — restoring the last verified checkpoint")
        for h in corrupt:
            c.evict(h, "silent data corruption")
        self.sdc_evictions += len(corrupt)
        self.evictions += len(corrupt)
        _count("bigdl_elastic_evictions_total",
               "hosts voted out (stragglers + SDC minorities)",
               len(corrupt))
        self.evicted_hosts.extend(corrupt)
        survivors = [m for m in self.members if m not in corrupt]
        n2 = c.propose(survivors, f"sdc eviction: {corrupt}",
                       expect=self.incarnation)
        self._iscalar("IntegrityEvictions", self.sdc_evictions, step)
        self._mark_fault()
        raise MembershipChangedError(
            f"host(s) {corrupt} failed the step-{step} integrity vote "
            f"(checksum minority vs {truth}) — shrinking to {survivors}",
            incarnation=n2, members=survivors)

    # -- internals -------------------------------------------------------
    def _iscalar(self, tag: str, value, step: int):
        summary = self.integrity_summary or self.summary
        if summary is not None:
            try:
                summary.add_scalar(tag, float(value), int(step))
            except Exception:
                log.exception("elastic: integrity summary write failed "
                              "for %s", tag)

    def _mark_fault(self):
        if self._fault_at is None:
            self._fault_at = time.monotonic()

    def _scalar(self, tag: str, value):
        if self.summary is not None:
            try:
                self.summary.add_scalar(tag, float(value), self._last_step)
            except Exception:
                log.exception("elastic: summary write failed for %s", tag)


# ---------------------------------------------------------------------------
# simulated cluster member (tests + bench)
# ---------------------------------------------------------------------------

class SimulatedHost:
    """A fake gang member for single-process simulations: pumps
    heartbeats, acks every incarnation that includes it, honors the
    elastic fault injectors (keyed off the *leader's* published step,
    so schedules are deterministic against the training timeline), and
    can die / rejoin / recover its speed on that schedule.

    This is what lets CPU CI drive a 4-"host" cluster through death →
    shrink → rejoin → regrow in one process: the real driver is one
    member; the rest are these.
    """

    def __init__(self, host: str, transport: KVTransport, *,
                 leader: str = "host0", interval: float = 0.02,
                 heartbeat_timeout: float = 2.0,
                 step_time: Optional[float] = None,
                 die_at_leader_step: Optional[int] = None,
                 rejoin_at_leader_step: Optional[int] = None,
                 readmit_at_leader_step: Optional[int] = None):
        self.coordinator = ElasticCoordinator(
            host, transport, heartbeat_timeout=heartbeat_timeout)
        self.host = str(host)
        self.leader = str(leader)
        self.interval = float(interval)
        # step_time=None mirrors the leader's published step time ("the
        # host keeps up with the gang"); a number simulates a fixed-rate
        # host; either is inflated by an armed delay_host fault
        self.step_time = step_time
        self.die_at_leader_step = die_at_leader_step
        self.rejoin_at_leader_step = rejoin_at_leader_step
        # a straggler that got evicted stays barred until it clears its
        # own marker; at this leader step it recovers its speed and
        # readmits itself (regrow picks it up at the next boundary)
        self.readmit_at_leader_step = readmit_at_leader_step
        self.dead = False
        self.deaths = 0
        self._acked = -1
        # every fake member carries its own telemetry bundle (private
        # registry — fake hosts must not pollute the process default)
        # and publishes payloads like a real host would, so a
        # single-process simulation exercises the leader's merge path
        from ..telemetry import MetricsRegistry, Telemetry

        self.telemetry = Telemetry(registry=MetricsRegistry(),
                                   host=str(host))
        self._tm_publish_every = 5
        self._tm_last: Optional[float] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"elastic-sim-{host}")

    def start(self):
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        self._thread.join(timeout=timeout)

    # ------------------------------------------------------------------
    def _run(self):
        from . import faults

        c = self.coordinator
        step = 0
        while not self._stop.is_set():
            leader_step = c.leader_step(self.leader)
            if self.dead:
                self._tm_last = None  # dead wall is not productive
                if (self.rejoin_at_leader_step is not None
                        and leader_step >= self.rejoin_at_leader_step):
                    self.dead = False
                    self.die_at_leader_step = None
                self._stop.wait(self.interval)
                continue
            if (self.die_at_leader_step is not None
                    and leader_step >= self.die_at_leader_step):
                self.dead = True
                self.deaths += 1
                continue
            if (self.readmit_at_leader_step is not None
                    and leader_step >= self.readmit_at_leader_step):
                self.step_time = None  # recovered: keep pace again
                self.readmit_at_leader_step = None
                c.readmit(self.host)
            step += 1
            t0 = time.monotonic()
            try:
                faults.check_elastic_fault(self.host, leader_step, None)
            except faults.HostKilledError:
                self.dead = True
                self.deaths += 1
                continue
            except HungCollectiveError:
                pass  # an uncanceled hang just delayed this fake host
            fault_dt = time.monotonic() - t0
            base = self.step_time
            if base is None:
                # keep pace with the leader's published step time, so a
                # healthy fake host never reads as a straggler relative
                # to the one member doing real compute
                raw = c.transport.get(_HB + self.leader)
                try:
                    base = json.loads(raw).get("step_time") if raw else None
                except ValueError:
                    base = None
                base = base or self.interval
            dt = max(float(base), fault_dt)
            n, members = c.membership()
            member = self.host in members
            c.heartbeat(step=step, step_time=dt, rejoin=not member)
            if member and n > self._acked:
                c.ack(n)
                self._acked = n
            if member:
                self._answer_integrity_votes(leader_step)
                self._pump_telemetry(n, step, dt)
            self._stop.wait(self.interval)

    def _pump_telemetry(self, incarnation: int, step: int, dt: float):
        """Keep the fake host's telemetry honest and published: its
        published step time feeds the step histogram (the skew view),
        while the goodput ledger is attributed real elapsed wall — a
        fake host is 'keeping pace', so its wall is productive."""
        from ..telemetry.aggregate import publish_snapshot

        tm = self.telemetry
        tm.ledger.start()
        now = time.monotonic()
        if self._tm_last is not None:
            tm.ledger.add("productive", now - self._tm_last)
        self._tm_last = now
        tm.steps.inc()
        tm.step_seconds.observe(dt)
        if step % self._tm_publish_every == 0:
            tm.incarnation = incarnation
            publish_snapshot(self.coordinator.transport, self.host,
                             tm.payload(step), incarnation=incarnation)

    def _answer_integrity_votes(self, leader_step: int):
        """Echo the leader's published integrity checksum for any open
        vote round this host has not answered — in real synchronous
        SPMD every healthy host computes the bit-identical post-gather
        parameters, so "agrees with the leader" is the faithful
        simulation of a healthy host.  An armed ``corrupt_gradient`` /
        ``flip_param_bits`` fault perturbs the answer instead,
        simulating the silently-corrupting host the vote must flag."""
        from . import faults

        t = self.coordinator.transport
        for key in t.keys(_SDC):
            parts = key[len(_SDC):].split("/")  # <inc>/<step>/<host>
            if len(parts) != 3 or parts[2] != self.leader:
                continue
            inc_s, step_s, _ = parts
            if not step_s.isdigit():
                continue
            mine = f"{_SDC}{inc_s}/{step_s}/{self.host}"
            if t.get(mine) is not None:
                continue
            value = t.get(key)
            if value is None:
                continue
            t.put(mine, faults.corrupt_checksum(self.host, int(step_s),
                                                value))
