"""Hung-collective watchdog: a deadline around the distributed step.

A synchronous all-reduce over a gang of hosts has one failure mode the
driver retry loop cannot see: a *hang*.  When a peer host dies between
heartbeats (or its NIC degrades), the surviving hosts' collective never
completes — no exception, no timeout, the dispatch thread blocks in the
runtime forever and the reference's retry-from-checkpoint loop
(DistriOptimizer.scala:750) never gets control back.

The watchdog converts that eternal block into a *typed, retryable*
error: the compiled step runs in a worker thread under a deadline
derived from a rolling estimate of recent step times
(:class:`StepTimeEstimator` — median-based, so a one-off compile does
not inflate it), and expiry raises :class:`HungCollectiveError`, which
the existing :mod:`.retry` taxonomy classifies as **retryable** (its
``code`` is ``"UNAVAILABLE"``, mirroring the serving status taxonomy:
degrade and recover, don't crash).  The elastic layer
(:mod:`.elastic`) answers it by restoring the last verified checkpoint
and re-rendezvousing the survivors.

The abandoned worker thread cannot be killed — a genuinely hung
collective only dies with the process — but the *cooperative* hang
injector (``faults.hang_collective``) honors the cancel event the
watchdog trips, so tests never leak a sleeping thread past the step
that abandoned it, and never dispatch the step from an abandoned
attempt.
"""
from __future__ import annotations

import collections
import statistics
import threading
import time
from typing import Callable, Optional

__all__ = ["CollectiveWatchdog", "HungCollectiveError", "StepTimeEstimator"]


class HungCollectiveError(RuntimeError):
    """A distributed step exceeded its watchdog deadline — a peer is
    presumed dead or unreachable mid-collective.  Retryable by the
    :mod:`.retry` taxonomy (the gang can shrink and resume); ``code``
    follows the serving status vocabulary."""

    code = "UNAVAILABLE"


class StepTimeEstimator:
    """Rolling step-time estimate → deadline.

    The deadline is ``max(floor, multiplier * median(recent))`` over a
    bounded window.  Median, not mean: the first step of every (re)build
    is a compile measured in seconds, and an EMA polluted by it would
    stretch the deadline by the multiplier — a real hang would then take
    tens of seconds to classify.  ``min_samples`` withholds any deadline
    until enough post-compile steps have landed, so a fresh incarnation
    never trips on its own compilation.
    """

    def __init__(self, window: int = 16, multiplier: float = 8.0,
                 floor: float = 0.5, min_samples: int = 3,
                 cap: Optional[float] = None,
                 warmup_deadline: Optional[float] = None):
        if min_samples < 1:
            raise ValueError("min_samples must be >= 1")
        self.window = int(window)
        self.multiplier = float(multiplier)
        self.floor = float(floor)
        self.min_samples = int(min_samples)
        self.cap = cap
        # optional generous bound for the warming steps themselves —
        # without it a hang during an incarnation's very first (compile)
        # steps has no deadline at all; set it well above the worst
        # expected compile time
        self.warmup_deadline = warmup_deadline
        self._samples: "collections.deque" = collections.deque(
            maxlen=self.window)

    def observe(self, dt: float):
        self._samples.append(float(dt))

    def deadline(self) -> Optional[float]:
        """Seconds the next step may take, or None while the estimate is
        still warming up (callers run unbounded until then)."""
        if len(self._samples) < self.min_samples:
            return self.warmup_deadline
        d = max(self.floor, self.multiplier
                * statistics.median(self._samples))
        return min(d, self.cap) if self.cap is not None else d

    def reset(self):
        """Forget the history — a new incarnation compiles a new program
        with new timings."""
        self._samples.clear()


class CollectiveWatchdog:
    """Runs a step function under the estimator's deadline.

    ``run(fn)`` calls ``fn(cancel_event)`` in a worker thread; the
    callable must block until the step's result is actually materialized
    (the elastic layer blocks on the loss), so a hang anywhere between
    dispatch and the value fetch is covered.  On expiry the cancel event
    is set (cooperative injectors honor it), ``trips`` increments, and
    :class:`HungCollectiveError` raises on the calling thread.
    """

    def __init__(self, estimator: Optional[StepTimeEstimator] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.estimator = estimator or StepTimeEstimator()
        self._clock = clock
        self.trips = 0
        self.last_deadline: Optional[float] = None

    def run(self, fn: Callable, deadline: Optional[float] = None):
        if deadline is None:
            deadline = self.estimator.deadline()
        self.last_deadline = deadline
        t0 = self._clock()
        if deadline is None:
            # warming up: run inline (no deadline to enforce yet) but
            # still feed the estimator
            out = fn(None)
            self.estimator.observe(self._clock() - t0)
            return out

        cancel = threading.Event()
        done = threading.Event()
        box: dict = {}

        def worker():
            try:
                box["out"] = fn(cancel)
            except BaseException as e:  # re-raised on the caller below
                box["exc"] = e
            finally:
                done.set()

        t = threading.Thread(target=worker, daemon=True,
                             name="bigdl-collective-watchdog")
        t.start()
        if not done.wait(deadline):
            cancel.set()
            self.trips += 1
            from ..telemetry.registry import default_registry

            default_registry().counter(
                "bigdl_watchdog_trips_total",
                "hung-collective watchdog deadline expiries").inc()
            raise HungCollectiveError(
                f"distributed step exceeded its {deadline:.2f}s watchdog "
                "deadline — presuming a dead peer in the collective "
                "(retryable: survivors shrink and resume)")
        if "exc" in box:
            raise box["exc"]
        self.estimator.observe(self._clock() - t0)
        return box["out"]
