"""Graceful preemption: checkpoint at the next step boundary, exit clean.

TPU VMs (and any spot/managed capacity) are preempted with a SIGTERM
and a short grace period; Ctrl-C during an interactive run is the same
problem.  Killing a trainer mid-step loses everything since the last
checkpoint trigger; catching the signal mid-step can't safely
checkpoint either (device arrays are in flight).  The handler here just
RECORDS the request; the training loops poll :meth:`should_stop` at
each step boundary, write one final checkpoint with the live state, and
return the model — a subsequent run with the same checkpoint path
resumes via ``resume_from_checkpoint``.

A second SIGINT while a stop is already pending raises
``KeyboardInterrupt`` immediately — an operator hammering Ctrl-C wants
out now, not after the checkpoint.
"""
from __future__ import annotations

import logging
import signal
import threading

log = logging.getLogger("bigdl_tpu")

# process-wide request flag: lets tests (and embedders without signal
# access, e.g. non-main threads) request a graceful stop directly
_GLOBAL_REQUEST = threading.Event()


def request_preemption():
    """Programmatically request a graceful stop — the same effect as
    delivering SIGTERM to the process."""
    _GLOBAL_REQUEST.set()


class PreemptionHandler:
    """Context manager installing SIGTERM/SIGINT handlers for one
    training run.  Degrades gracefully off the main thread (where
    ``signal.signal`` is unavailable): the process-wide
    :func:`request_preemption` flag still works."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 on_request=None):
        self.signals = tuple(signals)
        self._old = {}
        self._requested = False
        self.installed = False
        #: optional callback fired the instant a stop is requested
        #: (from the signal handler or request()) — lets embedders
        #: that poll between steps ALSO react immediately, e.g. the
        #: serving readiness probe flipping unready on SIGTERM before
        #: the worker reaches its next batch boundary.  Must be
        #: async-signal-safe-ish: set a flag, don't do work.
        self._on_request = on_request

    # ------------------------------------------------------------------
    def _notify(self):
        if self._on_request is None:
            return
        try:
            self._on_request()
        except Exception:  # a broken callback must not mask the signal
            log.exception("preemption on_request callback failed")

    def _on_signal(self, signum, frame):
        if self._requested and signum == signal.SIGINT:
            raise KeyboardInterrupt
        self._requested = True
        log.warning("received signal %d — will checkpoint at the next "
                    "step boundary and exit resumable", signum)
        self._notify()

    @property
    def should_stop(self) -> bool:
        return self._requested or _GLOBAL_REQUEST.is_set()

    def request(self):
        self._requested = True
        self._notify()

    # ------------------------------------------------------------------
    def __enter__(self):
        _GLOBAL_REQUEST.clear()  # a fresh run starts unpreempted
        try:
            for s in self.signals:
                self._old[s] = signal.signal(s, self._on_signal)
            self.installed = True
        except ValueError:  # not the main thread
            self._old.clear()
            self.installed = False
        return self

    def __exit__(self, *exc):
        for s, h in self._old.items():
            try:
                signal.signal(s, h)
            except ValueError:
                pass
        self._old.clear()
        self.installed = False
        return False
