"""Training resilience subsystem.

The reference framework's only fault story is a fixed-count driver
retry from the latest checkpoint (DistriOptimizer.scala:750-752),
inherited from Spark's coarse-grained task re-execution (BigDL,
arXiv:1804.05839).  A TPU-native trainer has no substrate to inherit
resilience from, so this package owns it end to end:

* :mod:`.guards`      — jit-compatible NaN/Inf gradient guard (skip the
  step, keep params/slots intact) + host-side loss-spike detector that
  triggers rollback-to-last-good-checkpoint.
* :mod:`.checkpoint`  — atomic checkpoint writes (tmp + fsync + rename)
  with crc32c sidecar checksums, verified restore, and walk-back to the
  newest checkpoint that passes verification (corrupt ones are
  quarantined, never deleted).
* :mod:`.retry`       — :class:`RetryPolicy`: exponential backoff with
  jitter and retryable-vs-fatal error classification, replacing the
  fixed ``bigdl.failure.retryTimes``/``retryTimeInterval`` window
  (kept as compat aliases) and reused by the ingest layer for
  transient I/O.
* :mod:`.preemption`  — SIGTERM/SIGINT handler that requests a
  checkpoint at the next step boundary and exits cleanly resumable.
* :mod:`.faults`      — deterministic fault-injection API (fail-at-step
  exceptions, NaN-gradient injection, checkpoint truncation/bit-flip,
  ingest I/O errors, host kill/delay/hang) driving the end-to-end
  recovery tests.
* :mod:`.elastic`     — cluster-level coordination for multi-host runs:
  heartbeat/membership with monotonically numbered incarnations over a
  pluggable KV transport, straggler skew tracking + bounded eviction,
  shrink-to-survivors recovery and regrow-on-rejoin.
* :mod:`.watchdog`    — hung-collective watchdog: the compiled
  distributed step runs under a deadline derived from a rolling
  step-time estimate; expiry raises a retryable
  :class:`HungCollectiveError` instead of blocking forever.
* :mod:`.integrity`   — silent-data-corruption defense: the
  :class:`FlightRecorder` step-fingerprint journal (loss/grad-norm bit
  patterns, batch + param checksums) and the cross-host integrity vote
  (majority checksum defines truth; a minority host is evicted, no
  quorum is the fatal :class:`IntegrityError`).
* :mod:`.async_checkpoint` — background snapshot-then-write
  checkpointing: bytes serialized synchronously at the step boundary
  (bitwise-identical to a sync write), atomic crc32c writes on a
  single writer thread with back-pressure and drain barriers at loop
  exit / restore / preemption (docs/async.md).
* :mod:`.replay`      — deterministic replay: re-execute from a
  verified checkpoint and diff fingerprint journals to localize the
  first divergent step (total train state — params, slots, RNG stream,
  pipeline cursor — makes the re-execution bit-faithful).
"""
from .async_checkpoint import AsyncCheckpointError, AsyncCheckpointWriter
from .guards import LossSpikeDetector, tree_finite, where_tree
from .retry import (FatalTrainingError, LossSpikeError, RetryPolicy,
                    classify_error)
from .preemption import PreemptionHandler, request_preemption
from .checkpoint import (CorruptCheckpointError, quarantine, verified_load,
                         verify_file, verify_and_load_latest, write_sidecar)
from .watchdog import (CollectiveWatchdog, HungCollectiveError,
                       StepTimeEstimator)
from .elastic import (ElasticContext, ElasticCoordinator, FileKV,
                      InMemoryKV, KVTransport, MembershipChangedError,
                      SimulatedHost, StragglerPolicy, largest_valid_shards)
from .faults import HostKilledError
from .integrity import (FlightRecorder, IntegrityError,
                        SilentDataCorruptionError, checksum_tree,
                        float_bits, majority_vote)
from .replay import diff_journals, load_journal, replay

__all__ = [
    "AsyncCheckpointError", "AsyncCheckpointWriter",
    "LossSpikeDetector", "tree_finite", "where_tree",
    "FatalTrainingError", "LossSpikeError", "RetryPolicy", "classify_error",
    "PreemptionHandler", "request_preemption",
    "CorruptCheckpointError", "quarantine", "verified_load", "verify_file",
    "verify_and_load_latest", "write_sidecar",
    "CollectiveWatchdog", "HungCollectiveError", "StepTimeEstimator",
    "ElasticContext", "ElasticCoordinator", "FileKV", "InMemoryKV",
    "KVTransport", "MembershipChangedError", "SimulatedHost",
    "StragglerPolicy", "largest_valid_shards", "HostKilledError",
    "FlightRecorder", "IntegrityError", "SilentDataCorruptionError",
    "checksum_tree", "float_bits", "majority_vote",
    "diff_journals", "load_journal", "replay",
]
