"""Gradient anomaly guard + loss-spike detector.

The gradient guard is a pair of jit-compatible helpers the compiled
training steps build on: :func:`tree_finite` reduces a gradient pytree
to one scalar "every leaf is finite" predicate, and :func:`where_tree`
selects between the post-update and pre-update state trees under that
predicate.  A NaN/Inf step is thereby SKIPPED — parameters, optimizer
slots and buffers come out bit-identical to their pre-step values, the
batch is dropped, and the host counts the skip in the train summary.
This is the select-not-branch idiom: under jit both sides are computed
and ``jnp.where`` picks, so the guard adds no host sync and composes
with shard_map (callers psum/pmin the predicate across shards so every
shard takes the same branch).

The loss-spike detector is HOST-side: it watches the per-iteration loss
scalar the driver already fetches, and after K consecutive spikes above
a running-mean threshold signals rollback — the driver raises
:class:`~bigdl_tpu.resilience.retry.LossSpikeError`, which the retry
loop classifies as retryable and answers by restoring the last good
checkpoint.
"""
from __future__ import annotations

import logging
import math
from typing import Optional

import jax
import jax.numpy as jnp

log = logging.getLogger("bigdl_tpu")


def tree_finite(*trees):
    """Scalar bool: every floating leaf of every given pytree is finite.

    Integer leaves pass vacuously.  jit/shard_map compatible (pure jnp,
    no host sync)."""
    ok = jnp.bool_(True)
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            if jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
                ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


def where_tree(pred, new_tree, old_tree):
    """Leaf-wise ``jnp.where(pred, new, old)`` over matching pytrees —
    the skip-step select: with ``pred`` False the old state rides
    through untouched (params/slots/buffers stay intact)."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(pred, n, o), new_tree, old_tree)


class LossSpikeDetector:
    """K-consecutive-spike trigger over the training loss stream.

    A step is a *spike* when its loss exceeds ``ratio`` times the
    exponential moving average of recent (non-spike) losses, or is
    non-finite.  ``k`` consecutive spikes trip the detector: ``update``
    returns True and the driver rolls back to the last good checkpoint.
    Isolated spikes (a hard batch) decay back into the average; genuine
    divergence — where every subsequent loss stays elevated — trips
    within ``k`` steps instead of wasting the rest of the run.

    Host-side and cheap: one float compare per iteration on the loss
    the driver already fetched.
    """

    def __init__(self, k: int = 3, ratio: float = 2.0,
                 warmup: int = 10, ema_decay: float = 0.9):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if ratio <= 1.0:
            raise ValueError(f"ratio must be > 1, got {ratio}")
        self.k = int(k)
        self.ratio = float(ratio)
        self.warmup = int(warmup)
        self.ema_decay = float(ema_decay)
        self.reset()

    def reset(self):
        """Forget history — call after a rollback so the restored run
        re-warms on its own losses."""
        self._ema: Optional[float] = None
        self._steps = 0
        self._consecutive = 0

    @property
    def consecutive_spikes(self) -> int:
        return self._consecutive

    def update(self, loss: float) -> bool:
        """Feed one iteration's loss; True means roll back now."""
        loss = float(loss)
        self._steps += 1
        finite = math.isfinite(loss)
        in_warmup = self._ema is None or self._steps <= self.warmup
        spike = not finite or (not in_warmup
                               and loss > self.ratio * self._ema)
        if spike:
            self._consecutive += 1
            log.warning("loss spike %d/%d: loss %.6g vs EMA %.6g",
                        self._consecutive, self.k, loss,
                        self._ema if self._ema is not None else float("nan"))
        else:
            self._consecutive = 0
            self._ema = (loss if self._ema is None else
                         self.ema_decay * self._ema
                         + (1.0 - self.ema_decay) * loss)
        if self._consecutive >= self.k:
            self._consecutive = 0
            return True
        return False
