"""Verified checkpoints: crc32c sidecars, walk-back restore, quarantine.

The pickle-format checkpoints (``model.N``/``optimMethod.N`` files,
reference DistriOptimizer.scala:394-416) are written atomically by
``utils.file_io.save(atomic=True, checksum=True)`` — pickle to a temp
file in the target directory, fsync, rename — with a ``<file>.crc32c``
sidecar carrying the payload's crc32c and size.  This module owns the
read side: verify a file against its sidecar, quarantine corrupt files
(rename to ``<file>.corrupt`` — never delete: the bytes are evidence),
and walk back through a checkpoint directory to the newest file that
both verifies and unpickles.

Orbax-format steps get the same treatment via per-step file manifests
in :mod:`bigdl_tpu.utils.orbax_io`.
"""
from __future__ import annotations

import logging
import os
from typing import Any, List, Optional, Tuple

from ..visualization.crc32c import crc32c

log = logging.getLogger("bigdl_tpu")

CRC_SUFFIX = ".crc32c"
QUARANTINE_SUFFIX = ".corrupt"
_CHUNK = 1 << 20


def _native_crc():
    from .. import native

    return native.crc32c if native.available() else crc32c


def stream_crc32c(path: str) -> Tuple[int, int]:
    """(crc32c, size) of a file's bytes, streamed in 1 MiB chunks
    through the native slicing-by-8 CRC when built."""
    from ..utils import file_io

    fn = _native_crc()
    crc, size = 0, 0
    with file_io.filesystem_for(path).open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            crc = fn(bytes(chunk), crc)
            size += len(chunk)
    return crc, size


def sidecar_path(path: str) -> str:
    """``<dir>/.<name>.crc32c`` — hidden, so checkpoint-directory scans
    that glob by prefix (``model.*``) never pick a sidecar up as a
    checkpoint candidate."""
    if "://" in path or "/" in path:
        sep = "/" if "://" in path else os.sep
        d, _, base = path.rpartition(sep)
        return f"{d}{sep}.{base}{CRC_SUFFIX}"
    return f".{path}{CRC_SUFFIX}"


def write_sidecar(path: str, crc: int, size: int):
    """Write ``<path>``'s sidecar = "<crc hex> <size>"."""
    from ..utils import file_io

    with file_io.filesystem_for(path).open(sidecar_path(path), "wb") as f:
        f.write(f"{crc:08x} {size}\n".encode())


def read_sidecar(path: str) -> Optional[Tuple[int, int]]:
    from ..utils import file_io

    side = sidecar_path(path)
    fs = file_io.filesystem_for(path)
    if not fs.exists(side):
        return None
    try:
        with fs.open(side, "rb") as f:
            crc_hex, size = f.read().split()
        return int(crc_hex, 16), int(size)
    except (ValueError, OSError):
        return None  # unreadable sidecar: treat the file as unverifiable


def verify_file(path: str) -> Optional[bool]:
    """True: sidecar present and crc+size match.  False: sidecar present
    and MISMATCH (the file is corrupt).  None: no (readable) sidecar —
    a legacy checkpoint; the caller decides (restore still attempts the
    unpickle, which catches gross truncation)."""
    expected = read_sidecar(path)
    if expected is None:
        return None
    try:
        actual = stream_crc32c(path)
    except OSError:
        return False
    return actual == expected


def quarantine(path: str) -> str:
    """Move a corrupt checkpoint (and its sidecar) out of the restore
    set: ``<path>`` → ``<path>.corrupt``.  Returns the new path."""
    dst = path + QUARANTINE_SUFFIX
    try:
        os.replace(path, dst)
    except OSError:
        # non-local / already-moved: fall back to best-effort removal
        # from the candidate namespace via the backend
        log.warning("could not quarantine %s in place", path)
        return path
    side = sidecar_path(path)
    if os.path.exists(side):
        try:
            os.replace(side, side + QUARANTINE_SUFFIX)
        except OSError:
            pass
    log.warning("quarantined corrupt checkpoint %s -> %s", path, dst)
    return dst


class CorruptCheckpointError(IOError):
    """A specifically-requested checkpoint failed crc32c verification
    or would not unpickle.  Unlike the walk-back restore (which falls
    back to an older file), a caller naming ONE file — e.g. the serving
    hot-swap loading candidate params — has no older file to fall back
    to, so the corruption surfaces as this typed error."""


def verified_load(path: str) -> Any:
    """Verify ``path`` against its sidecar and unpickle it — the
    single-file counterpart of :func:`verify_and_load_latest`.  A crc
    mismatch quarantines the file and raises
    :class:`CorruptCheckpointError`; a missing sidecar (legacy file)
    still attempts the unpickle, which catches gross truncation."""
    from ..utils import file_io

    if verify_file(path) is False:
        quarantine(path)
        raise CorruptCheckpointError(
            f"{path} failed crc32c verification (quarantined)")
    try:
        return file_io.load(path)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:
        raise CorruptCheckpointError(
            f"{path} failed to load ({type(e).__name__}: {e})")


# ---------------------------------------------------------------------------
# walk-back restore
# ---------------------------------------------------------------------------

def candidate_files(directory: str, prefix: str,
                    max_step: Optional[int] = None) -> List[str]:
    """All ``<prefix>``/``<prefix>.N`` files under ``directory``, newest
    step first (a bare ``<prefix>`` — the overwrite layout — sorts
    newest, matching the old ``_latest_file`` preference).  With
    ``max_step``, only steps ``<= max_step`` qualify — the replay
    entry point pins its restore to checkpoint K this way, and the
    resume path pins optimMethod/trainState to the step the model
    actually restored from (a consistent trio, never a mix)."""
    from ..utils import file_io

    if directory is None or not file_io.isdir(directory):
        return []
    steps = []
    for f in file_io.listdir(directory):
        if f == prefix:
            steps.append((float("inf"), f))
        elif f.startswith(prefix + ".") and not f.endswith(
                (CRC_SUFFIX, QUARANTINE_SUFFIX)):
            try:
                steps.append((int(f.rsplit(".", 1)[1]), f))
            except ValueError:
                continue
    if max_step is not None:
        steps = [t for t in steps if t[0] <= max_step]
    steps.sort(key=lambda t: t[0], reverse=True)
    return [file_io.join(directory, f) for _, f in steps]


def verify_and_load_latest(directory: str, prefix: str,
                           max_step: Optional[int] = None
                           ) -> Tuple[Optional[Any], Optional[str]]:
    """Walk the ``<prefix>.N`` files newest-first; return
    ``(loaded_object, path)`` for the first one that passes crc32c
    verification AND unpickles.  Corrupt candidates are quarantined and
    the walk continues — a torn newest checkpoint falls back to the
    previous good one instead of killing the resume.  ``(None, None)``
    when nothing survives."""
    from ..utils import file_io

    for path in candidate_files(directory, prefix, max_step=max_step):
        ok = verify_file(path)
        if ok is False:
            quarantine(path)
            continue
        try:
            return file_io.load(path), path
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            # sidecar absent (legacy) or matched-but-unloadable (e.g. a
            # truncated legacy file): quarantine and keep walking
            log.warning("checkpoint %s failed to load (%s: %s)",
                        path, type(e).__name__, e)
            quarantine(path)
            continue
    return None, None
