"""Background snapshot-then-write checkpointing.

A synchronous checkpoint puts the whole serialize + fsync + rename
sequence on the step critical path; at production cadence that is a
named goodput category stealing seconds from every trigger (the
`checkpoint` row of the goodput ledger).  The fix is the classic
two-phase split (the same overlap move the BigDL parameter manager
makes for gradient aggregation, arXiv:1804.05839 — hide I/O behind
compute):

* **snapshot** (synchronous, at the step boundary): pull device state
  to host and pickle it (``utils.file_io.serialize``).  After this
  instant the checkpoint's bytes are immutable — the training loop may
  donate, overwrite or shrink the live arrays without touching what
  will be written.  This is what keeps deterministic resume *bitwise*:
  an async-written checkpoint is byte-identical to the sync-written
  one, only its I/O happens later.
* **write** (asynchronous): a single daemon writer thread performs the
  atomic tmp + fsync + rename + crc32c-sidecar write
  (``utils.file_io.save_bytes``) off the critical path.

Ordering/robustness contract:

* one writer thread ⇒ jobs commit in submission order (step N's files
  can never land after step N+1's);
* the queue is bounded (default depth 1) ⇒ **back-pressure**: a new
  checkpoint triggered while the previous write is still in flight
  blocks in :meth:`~AsyncCheckpointWriter.submit`, and that blocked
  time is returned so the driver can ledger it as the only checkpoint
  seconds left on the critical path;
* a background write failure is **stored and re-raised on the training
  thread** at the next ``submit``/``drain`` — asynchrony must not turn
  a failing checkpoint path into silence (the retry loop then treats
  it exactly like a synchronous write failure);
* :meth:`~AsyncCheckpointWriter.drain` is the barrier the driver runs
  at loop exit, before any restore, and on preemption — after it
  returns, every submitted byte is committed (or its error raised).

Torn-write protection is inherited from ``save_bytes``: a writer
killed mid-write leaves only a temp file, never a torn file under the
final name, and a torn file smuggled in by a harder crash fails its
crc32c sidecar on restore and is quarantined (resilience.checkpoint).
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence, Tuple

log = logging.getLogger("bigdl_tpu")

__all__ = ["AsyncCheckpointError", "AsyncCheckpointWriter"]


class AsyncCheckpointError(IOError):
    """A background checkpoint write failed.  Raised on the *training*
    thread at the next submit/drain so the failure enters the same
    retry/rollback machinery a synchronous write failure would."""


def _count(name: str, help: str, n: float = 1.0):
    """Best-effort counter into the process default registry (the same
    pattern the elastic/retry internals use)."""
    try:
        from ..telemetry import default_registry

        default_registry().counter(name, help).inc(n)
    except Exception:
        pass


class AsyncCheckpointWriter:
    """Single background writer thread with a bounded job queue.

    A job is a sequence of ``(path, bytes)`` files (written in order
    through ``file_io.save_bytes`` — atomic + crc32c) and/or a zero-arg
    callable for writes that are not plain bytes-at-path (the orbax
    meta sidecar).  The thread starts lazily on the first submit and is
    a daemon, so an abandoned writer never blocks interpreter exit; the
    drain barrier is what guarantees durability at the points that need
    it.
    """

    def __init__(self, queue_depth: int = 1, name: str = "bigdl-ckpt-writer"):
        self.queue_depth = max(1, int(queue_depth))
        self._name = name
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._jobs: deque = deque()
        self._pending = 0          # queued + in-flight jobs
        self._error: Optional[BaseException] = None
        self._error_step: Optional[int] = None
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # -- counters (observability; also exported to the default
        #    registry as bigdl_checkpoint_async_* metrics) -------------
        self.writes = 0            # jobs fully committed
        self.write_seconds = 0.0   # background wall spent writing
        self.blocked_seconds = 0.0  # cumulative submit back-pressure

    # -- lifecycle -------------------------------------------------------
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name=self._name)
            self._thread.start()

    def _run(self):
        while True:
            with self._cv:
                while not self._jobs and not self._stop:
                    self._cv.wait()
                if self._stop and not self._jobs:
                    return
                step, files, fn = self._jobs.popleft()
                self._cv.notify_all()  # wake a submit blocked on depth
            t0 = time.monotonic()
            try:
                self._write(files, fn)
                with self._cv:
                    self.writes += 1
                    self.write_seconds += time.monotonic() - t0
                _count("bigdl_checkpoint_async_writes_total",
                       "checkpoint jobs committed by the background "
                       "writer")
                _count("bigdl_checkpoint_async_write_seconds_total",
                       "background wall seconds spent writing "
                       "checkpoints (off the step critical path)",
                       time.monotonic() - t0)
            except BaseException as e:  # noqa: BLE001 — re-raised on the
                #                         training thread via _raise_pending
                log.error("async checkpoint write for step %s failed: "
                          "%s: %s", step, type(e).__name__, e)
                with self._cv:
                    if self._error is None:
                        self._error, self._error_step = e, step
            finally:
                with self._cv:
                    self._pending -= 1
                    self._cv.notify_all()

    @staticmethod
    def _write(files: Sequence[Tuple[str, bytes]],
               fn: Optional[Callable[[], None]]):
        from ..utils import file_io

        for path, data in files or ():
            file_io.save_bytes(data, path, atomic=True, checksum=True)
        if fn is not None:
            fn()

    # -- training-thread API --------------------------------------------
    def _raise_pending(self):
        with self._cv:
            err, step = self._error, self._error_step
            self._error = self._error_step = None
        if err is not None:
            raise AsyncCheckpointError(
                f"background checkpoint write for step {step} failed: "
                f"{type(err).__name__}: {err}") from err

    def submit(self, step: int,
               files: Sequence[Tuple[str, bytes]] = (),
               fn: Optional[Callable[[], None]] = None) -> float:
        """Queue one checkpoint's committed bytes for background write.

        Blocks while the queue is at depth (back-pressure: checkpoints
        must not pile up faster than storage absorbs them) and returns
        the seconds blocked — the only checkpoint-write time left on
        the caller's critical path.  Raises :class:`AsyncCheckpointError`
        first if a previous background write failed."""
        self._raise_pending()
        self._ensure_thread()
        t0 = time.monotonic()
        with self._cv:
            while self._pending >= self.queue_depth and not self._stop:
                self._cv.wait(0.05)
            self._jobs.append((int(step), tuple(files or ()), fn))
            self._pending += 1
            self._cv.notify_all()
        blocked = time.monotonic() - t0
        with self._cv:
            self.blocked_seconds += blocked
        return blocked

    def drain(self, timeout: Optional[float] = None,
              raise_errors: bool = True) -> bool:
        """Barrier: block until every submitted job has committed (or
        failed).  Returns False on timeout.  With ``raise_errors`` a
        stored background failure surfaces here — the drain points
        (loop exit, pre-restore, preemption) are exactly where a lost
        checkpoint must not go unnoticed."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._pending > 0:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining if remaining is not None else 0.5)
        if raise_errors:
            self._raise_pending()
        return True

    def close(self, timeout: float = 30.0):
        """Drain and stop the writer thread (idempotent).  Errors from
        in-flight writes still raise — closing must not eat them."""
        drained = self.drain(timeout=timeout, raise_errors=False)
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        self._thread = None
        if not drained:
            log.warning("async checkpoint writer closed with writes "
                        "still pending after %.0fs", timeout)
        self._raise_pending()

    @property
    def pending(self) -> int:
        with self._cv:
            return self._pending

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
