"""Deterministic fault injection for recovery tests.

Generalizes the old ``tests/_fault.py`` ExceptionTransformer (reference
ExceptionTest module, SURVEY §4.5) into a first-class API: every
injector fires at an explicit, deterministic point (record index, byte
offset, open count) and records that it fired, so recovery tests can
assert both that the fault happened AND that training rode through it.

Under XLA a module can only throw at trace time, so the host-visible
fault surface is the input pipeline — data-plane transformers inject
driver exceptions (:class:`ExceptionTransformer`), NaN gradients
(:class:`NaNInjector` — a NaN feature makes every downstream gradient
NaN), and loss spikes (:class:`ScaleInjector`).  File-level helpers
(:func:`bit_flip`, :func:`truncate`) corrupt checkpoints on disk, and
the :func:`io_faults` context injects transient errors into the ingest
layer's shard opens.  Cluster-level chaos (:func:`kill_host`,
:func:`delay_host`, :func:`hang_collective`) is keyed off the leader's
published step so schedules stay deterministic against the training
timeline — see the registry table in ``docs/resilience.md``.
"""
from __future__ import annotations

import contextlib
import os
import threading
from typing import Callable, Optional

import numpy as np

from ..dataset.sample import Sample
from ..dataset.transformer import Transformer
from .retry import FatalTrainingError


# ---------------------------------------------------------------------------
# data-plane injectors (Transformer stages)
# ---------------------------------------------------------------------------

class ExceptionTransformer(Transformer):
    """Raises once when the ``fail_at``-th record passes through;
    ``fired`` records that the fault actually triggered."""

    def __init__(self, fail_at: int,
                 exc: Callable[[], BaseException] = None):
        self.fail_at = fail_at
        self.count = 0
        self.fired = False
        self._exc = exc or (lambda: RuntimeError("injected failure"))

    def apply(self, it):
        for item in it:
            self.count += 1
            if self.count == self.fail_at and not self.fired:
                self.fired = True
                raise self._exc()
            yield item


class NaNInjector(Transformer):
    """Replaces the features of records [``at``, ``at + n``) with NaN —
    once per run — so the step's gradients (and loss) go NaN and the
    gradient guard's skip path is exercised end to end."""

    def __init__(self, at: int, n: int = 1):
        self.at = at
        self.n = n
        self.count = 0
        self.fired = 0

    def apply(self, it):
        for item in it:
            self.count += 1
            if (self.at <= self.count < self.at + self.n
                    and self.fired < self.n):
                self.fired += 1
                f = np.full_like(np.asarray(item.feature, np.float32),
                                 np.nan)
                item = Sample(f, item.label)
            yield item


class ScaleInjector(Transformer):
    """Scales the features of records [``at``, ``at + n``) by ``scale``
    — once per run — driving the loss far above its running average to
    exercise the loss-spike rollback path."""

    def __init__(self, at: int, n: int, scale: float):
        self.at = at
        self.n = n
        self.scale = float(scale)
        self.count = 0
        self.fired = 0

    def apply(self, it):
        for item in it:
            self.count += 1
            if (self.at <= self.count < self.at + self.n
                    and self.fired < self.n):
                self.fired += 1
                f = np.asarray(item.feature, np.float32) * self.scale
                item = Sample(f, item.label)
            yield item


class PreemptTransformer(Transformer):
    """Requests a graceful preemption (the SIGTERM path, minus the
    signal) when the ``at``-th record passes through."""

    def __init__(self, at: int):
        self.at = at
        self.count = 0
        self.fired = False

    def apply(self, it):
        from .preemption import request_preemption

        for item in it:
            self.count += 1
            if self.count == self.at and not self.fired:
                self.fired = True
                request_preemption()
            yield item


# ---------------------------------------------------------------------------
# checkpoint corruption (file-level)
# ---------------------------------------------------------------------------

def bit_flip(path: str, offset: Optional[int] = None, seed: int = 0):
    """Flip one byte's bits at ``offset`` (deterministically mid-file by
    default) — the classic silent-corruption case crc32c must catch."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path} is empty — nothing to flip")
    if offset is None:
        offset = (size // 2 + seed) % size
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))
    return offset


def truncate(path: str, keep_fraction: float = 0.5):
    """Truncate a file to ``keep_fraction`` of its size — the torn-write
    / out-of-disk case."""
    size = os.path.getsize(path)
    keep = int(size * keep_fraction)
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep



def _journal_chaos(event: str, entry: dict):
    """Record a chaos injector's arm/disarm into the change journal,
    tagged ``ground_truth=True`` — the scoreable cause benches judge
    blame rankings against (docs/observability.md "Incidents").  Lazy
    import keeps faults importable independent of telemetry."""
    from ..telemetry.events import record_change

    detail = " ".join(
        f"{k}={entry[k]}" for k in ("kind", "substr", "seconds",
                                    "rps", "at_step", "scale")
        if entry.get(k) is not None)
    record_change(event, detail, ground_truth=True,
                  source="resilience.faults",
                  host=entry.get("host"),
                  replica=entry.get("replica") or entry.get("server"),
                  tenant=entry.get("tenant"),
                  model=entry.get("model"),
                  table=entry.get("table"))


# ---------------------------------------------------------------------------
# ingest I/O faults
# ---------------------------------------------------------------------------

_IO_LOCK = threading.Lock()
_IO_FAULTS: list = []  # [dict(substr, remaining, exc_type)]


def check_io_fault(path: str):
    """Called by the ingest layer at each shard open; raises the
    injected transient error while its budget lasts.  No-op (and free)
    when nothing is registered."""
    if not _IO_FAULTS:
        return
    with _IO_LOCK:
        for f in _IO_FAULTS:
            if f["substr"] in path and f["remaining"] > 0:
                f["remaining"] -= 1
                raise f["exc_type"](
                    f"injected transient I/O error on {path} "
                    f"({f['remaining']} left)")


@contextlib.contextmanager
def io_faults(substr: str, times: int = 1, exc_type=OSError):
    """Inject ``times`` transient ``exc_type`` failures into ingest
    opens of any shard path containing ``substr``."""
    entry = {"substr": substr, "remaining": int(times),
             "exc_type": exc_type}
    with _IO_LOCK:
        _IO_FAULTS.append(entry)
    _journal_chaos("chaos_inject", entry)
    try:
        yield entry
    finally:
        with _IO_LOCK:
            _IO_FAULTS.remove(entry)
        _journal_chaos("chaos_clear", entry)


# ---------------------------------------------------------------------------
# serving faults
# ---------------------------------------------------------------------------
# The serving worker calls check_serving_fault() immediately before each
# compiled step, so every breaker / shedding / drain behavior is
# deterministically testable on the CPU backend — the serving analogue
# of the data-plane injectors above (under XLA the step itself can only
# throw at trace time, so the injection point is the host-side dispatch).

_SERVING_LOCK = threading.Lock()
_SERVING_FAULTS: list = []  # [dict(kind, remaining, exc_type|seconds, fired)]


def check_serving_fault(server: Optional[str] = None):
    """Called by the serving worker before each batch step (and by the
    hot-swap canary) with the server's replica name: applies the
    injected latency, then raises the injected failure while its budget
    lasts.  An entry carrying a ``server`` name only fires for that
    replica; unscoped entries fire for every server.  No-op (and free)
    when nothing is registered."""
    if not _SERVING_FAULTS:
        return
    delay = 0.0
    boom = None
    with _SERVING_LOCK:
        for f in _SERVING_FAULTS:
            if f["remaining"] <= 0:
                continue
            if f.get("server") is not None and f["server"] != server:
                continue
            if f["kind"] == "latency":
                f["remaining"] -= 1
                f["fired"] += 1
                delay += f["seconds"]
            elif boom is None:
                f["remaining"] -= 1
                f["fired"] += 1
                boom = f["exc_type"](
                    f"injected serving step failure "
                    f"({f['remaining']} left)")
    if delay > 0:
        import time

        time.sleep(delay)
    if boom is not None:
        raise boom


@contextlib.contextmanager
def serving_step_failures(times: int = 1, exc_type=RuntimeError,
                          server: Optional[str] = None):
    """Fail the next ``times`` serving batch steps with ``exc_type``
    (classified by the server's RetryPolicy: a retryable type counts
    toward the breaker threshold, a fatal one trips it immediately).
    ``server`` scopes the fault to one named replica."""
    entry = {"kind": "fail", "remaining": int(times),
             "exc_type": exc_type, "fired": 0,
             "server": None if server is None else str(server)}
    with _SERVING_LOCK:
        _SERVING_FAULTS.append(entry)
    _journal_chaos("chaos_inject", entry)
    try:
        yield entry
    finally:
        with _SERVING_LOCK:
            _SERVING_FAULTS.remove(entry)
        _journal_chaos("chaos_clear", entry)


@contextlib.contextmanager
def serving_step_latency(seconds: float, times: int = 1 << 30,
                         server: Optional[str] = None):
    """Add ``seconds`` of host-side latency to the next ``times``
    serving batch steps — drives deadline-expiry and queue-depth
    behaviors without a slow model.  ``server`` scopes the fault to one
    named replica."""
    entry = {"kind": "latency", "remaining": int(times),
             "seconds": float(seconds), "fired": 0,
             "server": None if server is None else str(server)}
    with _SERVING_LOCK:
        _SERVING_FAULTS.append(entry)
    _journal_chaos("chaos_inject", entry)
    try:
        yield entry
    finally:
        with _SERVING_LOCK:
            _SERVING_FAULTS.remove(entry)
        _journal_chaos("chaos_clear", entry)


# ---------------------------------------------------------------------------
# fleet (replica-membership) faults
# ---------------------------------------------------------------------------
# The serving-fleet layer (serving/fleet.py) gives inference the same
# cluster fault surface training got: each ReplicaAgent consults
# check_fleet_fault(replica) once per heartbeat pump, so replica death
# and KV partitions are scheduled deterministically against the
# heartbeat timeline.  ``delay_replica`` rides the per-server scoping
# of the serving injectors above (the slow path is the compiled step,
# not the heartbeat).

_FLEET_LOCK = threading.Lock()
_FLEET_FAULTS: list = []  # [dict(kind, replica, remaining, fired)]


def check_fleet_fault(replica: str) -> Optional[str]:
    """Called once per heartbeat pump by each ReplicaAgent.  Returns
    the armed fault kind for this replica (``"kill"`` consumes one
    budget unit; ``"partition"`` reports while armed without consuming
    — a partition lasts as long as its context), or None."""
    if not _FLEET_FAULTS:
        return None
    with _FLEET_LOCK:
        for f in _FLEET_FAULTS:
            if f["replica"] != replica or f["remaining"] <= 0:
                continue
            if f["kind"] == "kill":
                f["remaining"] -= 1
            f["fired"] += 1
            return f["kind"]
    return None


@contextlib.contextmanager
def _fleet_fault(entry):
    with _FLEET_LOCK:
        _FLEET_FAULTS.append(entry)
    _journal_chaos("chaos_inject", entry)
    try:
        yield entry
    finally:
        with _FLEET_LOCK:
            _FLEET_FAULTS.remove(entry)
        _journal_chaos("chaos_clear", entry)


def kill_replica(replica: str):
    """Kill serving replica ``replica`` at its next heartbeat pump: its
    server hard-stops (in-flight requests resolve typed, queued ones
    CANCELLED) and its heartbeats cease — the router's missed-heartbeat
    ejection and failover-retry paths are exercised end to end."""
    return _fleet_fault({"kind": "kill", "replica": str(replica),
                         "remaining": 1, "fired": 0})


def delay_replica(replica: str, seconds: float, times: int = 1 << 30):
    """Slow ``replica``'s serving steps by ``seconds`` each — its
    queue grows and its published p99 inflates, driving the router's
    least-loaded routing away from it (the serving analogue of
    :func:`delay_host`)."""
    return serving_step_latency(seconds, times=times, server=replica)


def partition_kv(replica: str):
    """Partition ``replica`` from the fleet KV transport while the
    context is active: its heartbeats and health snapshots stop
    landing, so the router presumes it dead and ejects it; on heal
    (context exit) its beats resume and it is re-admitted — the
    asymmetric-partition case where the replica itself is healthy but
    invisible."""
    return _fleet_fault({"kind": "partition", "replica": str(replica),
                         "remaining": 1 << 30, "fired": 0})


# ---------------------------------------------------------------------------
# continuous-learning loop faults
# ---------------------------------------------------------------------------
# The ContinuousLoop (loop/continuous.py) consults check_loop_fault()
# at two deterministic points: once per deploy attempt with
# kind="poison_candidate" (the captured candidate tree is NaN-poisoned
# before it reaches the fleet — the poisoned-artifact case every
# replica canary must refuse), and once per ingest interval with
# kind="diverge" (that interval's fresh samples are feature-scaled, so
# the next training slice's loss spikes and the TrainingHealthMonitor's
# divergence rule must gate the following deploy).

_LOOP_LOCK = threading.Lock()
_LOOP_FAULTS: list = []  # [dict(kind, remaining, fired, scale?)]


def check_loop_fault(kind: str) -> Optional[dict]:
    """Called by the ContinuousLoop at the injection point named by
    ``kind``; consumes one budget unit and returns a copy of the armed
    entry (carrying e.g. ``scale``), or None.  No-op (and free) when
    nothing is registered."""
    if not _LOOP_FAULTS:
        return None
    with _LOOP_LOCK:
        for f in _LOOP_FAULTS:
            if f["kind"] != kind or f["remaining"] <= 0:
                continue
            f["remaining"] -= 1
            f["fired"] += 1
            return dict(f)
    return None


@contextlib.contextmanager
def _loop_fault(entry):
    with _LOOP_LOCK:
        _LOOP_FAULTS.append(entry)
    _journal_chaos("chaos_inject", entry)
    try:
        yield entry
    finally:
        with _LOOP_LOCK:
            _LOOP_FAULTS.remove(entry)
        _journal_chaos("chaos_clear", entry)


def poison_candidate(times: int = 1):
    """NaN-poison the next ``times`` deploy candidates the
    ContinuousLoop captures (via :func:`poison_params`) — the
    poisoned-artifact deploy: every replica's canary must reject it
    and the fleet must roll back, never serving a bad param."""
    return _loop_fault({"kind": "poison_candidate",
                        "remaining": int(times), "fired": 0})


def loop_loss_divergence(times: int = 1, scale: float = 3.0):
    """Feature-scale the next ``times`` ingest intervals' fresh
    samples by ``scale`` — the loop's training loss spikes well above
    its window minimum, the divergence SLO rule fires, and the deploy
    gate must refuse to roll the damaged candidate until the loss
    recovers (the scaled samples wash out of the bounded streaming
    window)."""
    return _loop_fault({"kind": "diverge", "remaining": int(times),
                        "fired": 0, "scale": float(scale)})


# ---------------------------------------------------------------------------
# elastic (multi-host) faults
# ---------------------------------------------------------------------------
# The elastic step runner (resilience.elastic.ElasticContext.run_step)
# and every SimulatedHost call check_elastic_fault() once per step with
# the host's name and the global (leader-published) step number, so
# cluster chaos — a host dying, a host slowing down, a collective
# hanging — is scheduled deterministically against the training
# timeline, not wall clock.

_ELASTIC_LOCK = threading.Lock()
_ELASTIC_FAULTS: list = []  # [dict(kind, host, at_step, remaining, ...)]


class HostKilledError(FatalTrainingError):
    """Injected host death.  Fatal *for the killed host* — a dead host
    does not retry; its survivors detect the missing heartbeat and
    shrink without it."""


def check_elastic_fault(host: str, step: int, cancel_event=None):
    """Called once per step by each (real or simulated) cluster member.
    Applies the first matching armed fault: ``kill`` raises
    :class:`HostKilledError`, ``delay`` sleeps (making the host a
    straggler), ``hang`` blocks for ``seconds`` — cooperatively: when
    the watchdog trips it sets ``cancel_event`` and the hang re-raises
    as ``HungCollectiveError`` inside the abandoned worker, so the
    compiled step is never dispatched from an abandoned attempt.  No-op
    (and free) when nothing is registered."""
    if not _ELASTIC_FAULTS:
        return
    fault = None
    with _ELASTIC_LOCK:
        for f in _ELASTIC_FAULTS:
            if (f["host"] == host and f["remaining"] > 0
                    and step >= f["at_step"]):
                f["remaining"] -= 1
                f["fired"] += 1
                fault = dict(f)
                break
    if fault is None:
        return
    if fault["kind"] == "kill":
        raise HostKilledError(
            f"injected kill of {host} at step {step}")
    if fault["kind"] == "delay":
        import time

        time.sleep(fault["seconds"])
        return
    # hang: block like a dead collective would, but honor the
    # watchdog's cancel so the abandoned worker exits promptly
    from .watchdog import HungCollectiveError

    if cancel_event is not None:
        if cancel_event.wait(fault["seconds"]):
            raise HungCollectiveError(
                f"injected hang on {host} at step {step} canceled by "
                "the watchdog")
    else:
        import time

        time.sleep(fault["seconds"])


@contextlib.contextmanager
def _elastic_fault(entry):
    with _ELASTIC_LOCK:
        _ELASTIC_FAULTS.append(entry)
    _journal_chaos("chaos_inject", entry)
    try:
        yield entry
    finally:
        with _ELASTIC_LOCK:
            _ELASTIC_FAULTS.remove(entry)
        _journal_chaos("chaos_clear", entry)


def kill_host(host: str, at_step: int):
    """Kill ``host`` when the global step reaches ``at_step``: its step
    raises :class:`HostKilledError` and it stops heartbeating — the
    survivors' death detection and shrink path is exercised end to
    end."""
    return _elastic_fault({"kind": "kill", "host": str(host),
                           "at_step": int(at_step), "remaining": 1,
                           "fired": 0})


def delay_host(host: str, seconds: float, at_step: int = 0,
               times: int = 1 << 30):
    """Slow ``host`` by ``seconds`` per step from ``at_step`` for
    ``times`` steps — its published step time inflates and the
    straggler policy's warn/evict path is exercised."""
    return _elastic_fault({"kind": "delay", "host": str(host),
                           "at_step": int(at_step),
                           "remaining": int(times), "fired": 0,
                           "seconds": float(seconds)})


def hang_collective(host: str, at_step: int, seconds: float = 60.0):
    """Hang ``host``'s next step at ``at_step`` for up to ``seconds``
    (or until the watchdog trips and cancels) — the
    dead-peer-mid-collective case the watchdog deadline must convert
    into a retryable error instead of an eternal block."""
    return _elastic_fault({"kind": "hang", "host": str(host),
                           "at_step": int(at_step), "remaining": 1,
                           "fired": 0, "seconds": float(seconds)})


# ---------------------------------------------------------------------------
# integrity (silent-data-corruption) faults
# ---------------------------------------------------------------------------
# Two deterministic SDC injectors keyed, like the elastic faults, off a
# host name and the global step.  ``corrupt_gradient`` perturbs the
# checksum a (simulated) host publishes into the integrity vote — the
# "this host's compute is silently wrong" case the cross-host majority
# must localize.  ``flip_param_bits`` is consumed by the real driver:
# when armed for its host it flips one mantissa bit in the live
# parameter tree right after the step — plausible-but-wrong numbers the
# NaN guard can never see, which the fingerprint journal + replay must
# localize.

_INTEGRITY_LOCK = threading.Lock()
_INTEGRITY_FAULTS: list = []  # [dict(kind, host, at_step, remaining, fired)]


def corrupt_gradient(host: str, at_step: int, times: int = 1 << 30):
    """From global step ``at_step``, ``host``'s published
    gradient/param checksums are deterministically perturbed for
    ``times`` votes — simulating a host whose compute went silently
    wrong.  The integrity vote's majority must flag and evict it."""
    return _elastic_fault_entry(_INTEGRITY_LOCK, _INTEGRITY_FAULTS, {
        "kind": "checksum", "host": str(host), "at_step": int(at_step),
        "remaining": int(times), "fired": 0})


def flip_param_bits(host: str, at_step: int, times: int = 1):
    """Flip one mantissa bit in ``host``'s live parameter tree at
    global step ``at_step`` (the driver applies :func:`flip_tree_bits`
    when it sees this armed) — the classic SDC case: every value stays
    finite and plausible, only the fingerprints can tell."""
    return _elastic_fault_entry(_INTEGRITY_LOCK, _INTEGRITY_FAULTS, {
        "kind": "flip", "host": str(host), "at_step": int(at_step),
        "remaining": int(times), "fired": 0})


@contextlib.contextmanager
def _elastic_fault_entry(lock, registry, entry):
    with lock:
        registry.append(entry)
    _journal_chaos("chaos_inject", entry)
    try:
        yield entry
    finally:
        with lock:
            registry.remove(entry)
        _journal_chaos("chaos_clear", entry)


def corrupt_checksum(host: str, step: int, value: str) -> str:
    """Called by simulated hosts before publishing an integrity-vote
    checksum: returns a deterministically perturbed value while a
    matching ``corrupt_gradient``/``flip_param_bits`` fault is armed,
    ``value`` unchanged otherwise."""
    if not _INTEGRITY_FAULTS:
        return value
    with _INTEGRITY_LOCK:
        for f in _INTEGRITY_FAULTS:
            if (f["host"] == host and f["remaining"] > 0
                    and step >= f["at_step"]):
                f["remaining"] -= 1
                f["fired"] += 1
                try:
                    return f"{int(value, 16) ^ 0x5DC0FFEE:08x}"
                except ValueError:
                    return value[::-1] + "!"
    return value


def check_param_corruption(host: str, step: int) -> bool:
    """Called by the real driver once per step: True when an armed
    ``flip_param_bits`` fault fires for this host at this step (the
    caller then applies :func:`flip_tree_bits` to its live params).
    No-op (and free) when nothing is registered."""
    if not _INTEGRITY_FAULTS:
        return False
    with _INTEGRITY_LOCK:
        for f in _INTEGRITY_FAULTS:
            if (f["kind"] == "flip" and f["host"] == host
                    and f["remaining"] > 0 and step >= f["at_step"]):
                f["remaining"] -= 1
                f["fired"] += 1
                return True
    return False


def flip_tree_bits(tree, seed: int = 0):
    """A copy of ``tree`` with ONE mantissa bit flipped in its largest
    leaf — values stay finite and plausibly sized (the bit is in the
    middle of the mantissa, a ~2^-9 relative nudge), so NaN/Inf guards
    ride straight past it: only fingerprints catch it."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    float_ix = [i for i, l in enumerate(leaves)
                if np.issubdtype(np.asarray(l).dtype, np.floating)]
    if not float_ix:
        return tree
    idx = max(float_ix, key=lambda i: np.asarray(leaves[i]).size)
    a = np.array(leaves[idx])  # host copy, contiguous
    flat = a.view(np.uint8).reshape(-1)
    off = (flat.size // 2 + seed * a.itemsize) % flat.size
    off -= off % a.itemsize  # leaf-element start (little-endian)
    flat[off + 1] ^= 0x80    # mid-mantissa bit: finite, plausible, wrong
    leaves[idx] = jnp.asarray(a)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def poison_params(tree):
    """A NaN-poisoned copy of a param tree (every float leaf) — the
    hot-swap canary must reject it and roll back."""
    import jax
    import jax.numpy as jnp

    def _poison(leaf):
        a = jnp.asarray(leaf)
        if jnp.issubdtype(a.dtype, jnp.floating):
            return jnp.full_like(a, jnp.nan)
        return a

    return jax.tree_util.tree_map(_poison, tree)

# ---------------------------------------------------------------------------
# migration (embedding row re-partition) faults
# ---------------------------------------------------------------------------
# Two deterministic injectors for the EmbeddingStore's live
# shrink/regrow path (nn/embedding_store.py).  ``corrupt_migration_
# shard`` flips one payload bit in a sealed row shard AFTER its crc32c
# is computed — to the importer this is exactly a torn write, and the
# verify-on-import must convert it into the typed ``MigrationCorrupt``
# + a re-request from the owner's checkpointed leg, never a
# zero-filled row.  ``kill_host_mid_repartition`` kills a host in the
# narrow window between ownership re-derivation and import-ack — the
# survivors must re-derive without it and source its blocks from its
# checkpointed leg.

_MIGRATION_LOCK = threading.Lock()
_MIGRATION_FAULTS: list = []  # [dict(kind, host|table, remaining, fired)]


def check_migration_fault(kind: str, host: Optional[str] = None,
                          table: Optional[str] = None,
                          block: Optional[int] = None) -> bool:
    """Consulted by the store at its two deterministic injection
    points: ``"corrupt_shard"`` while sealing a shard for the KV
    transport (returns True when the armed fault consumed this shard
    — the caller flips a payload bit), ``"kill"`` between ownership
    re-derivation and import-ack (raises :class:`HostKilledError` for
    the armed host).  No-op (and free) when nothing is armed."""
    if not _MIGRATION_FAULTS:
        return False
    fault = None
    with _MIGRATION_LOCK:
        for f in _MIGRATION_FAULTS:
            if f["kind"] != kind or f["remaining"] <= 0:
                continue
            if kind == "kill" and f["host"] != host:
                continue
            if (kind == "corrupt_shard" and f["table"] is not None
                    and f["table"] != table):
                continue
            f["remaining"] -= 1
            f["fired"] += 1
            fault = dict(f)
            break
    if fault is None:
        return False
    if kind == "kill":
        raise HostKilledError(
            f"injected kill of {host} mid-repartition (between "
            "ownership re-derivation and import-ack)")
    return True


def corrupt_migration_shard(table: Optional[str] = None,
                            times: int = 1):
    """Bit-flip ``times`` sealed row shards in flight (any table when
    ``table`` is None).  The flip lands after the crc32c is sealed, so
    verify-on-import MUST fail — the typed ``MigrationCorrupt`` +
    checkpointed-leg re-request path is exercised end to end."""
    return _elastic_fault_entry(_MIGRATION_LOCK, _MIGRATION_FAULTS, {
        "kind": "corrupt_shard",
        "table": None if table is None else str(table),
        "remaining": int(times), "fired": 0})


def kill_host_mid_repartition(host: str):
    """Kill ``host`` inside its next repartition, between ownership
    re-derivation and import-ack: it has acked nothing, so survivors
    re-derive without it and its blocks come from its checkpointed
    leg."""
    return _elastic_fault_entry(_MIGRATION_LOCK, _MIGRATION_FAULTS, {
        "kind": "kill", "host": str(host), "remaining": 1,
        "fired": 0})


# ---------------------------------------------------------------------------
# multi-tenant (registry + admission) faults
# ---------------------------------------------------------------------------
# Two deterministic injectors for the multi-tenant serving layer
# (serving/registry.py).  ``tenant_flood`` is an open-loop overload on
# one tenant: the AdmissionController consults check_tenant_flood() at
# every admission decision and counts the armed phantom inflight units
# against that tenant's quota — the flooding tenant hits its weighted
# budget and sheds typed OVERLOADED while every other tenant's budget
# is untouched, with no wall-clock race.  ``unregister_model_mid_
# flight`` vanishes a registry entry at the model's next lookup (one
# consume), so requests already queued for it must resolve typed
# NOT_FOUND with their admission slots and KV pages released.

_TENANT_LOCK = threading.Lock()
_TENANT_FAULTS: list = []  # [dict(kind, tenant|model, remaining, fired, rps?)]


def check_tenant_flood(tenant: str) -> int:
    """Called by the AdmissionController at each admission decision:
    returns the phantom inflight units armed against ``tenant`` (the
    simulated open-loop flood, counted against its quota), consuming
    one budget unit per call.  0 (and free) when nothing is armed."""
    if not _TENANT_FAULTS:
        return 0
    with _TENANT_LOCK:
        for f in _TENANT_FAULTS:
            if (f["kind"] == "flood" and f["tenant"] == tenant
                    and f["remaining"] > 0):
                f["remaining"] -= 1
                f["fired"] += 1
                return int(f["rps"])
    return 0


def check_registry_fault(model: str) -> bool:
    """Called by the ModelRegistry at each lookup: True when an armed
    ``unregister_model_mid_flight`` fault fires for ``model`` (the
    registry then drops the entry — requests queued for it resolve
    typed NOT_FOUND).  No-op (and free) when nothing is armed."""
    if not _TENANT_FAULTS:
        return False
    with _TENANT_LOCK:
        for f in _TENANT_FAULTS:
            if (f["kind"] == "unregister" and f["model"] == model
                    and f["remaining"] > 0):
                f["remaining"] -= 1
                f["fired"] += 1
                return True
    return False


def tenant_flood(tenant: str, rps: int, times: int = 1 << 30):
    """Open-loop overload on ``tenant``: the next ``times`` admission
    decisions see ``rps`` phantom inflight requests charged against its
    quota, so the flooding tenant saturates its weighted budget and
    sheds typed OVERLOADED while under-quota tenants keep their full
    budget — the noisy-neighbor case admission control must contain."""
    return _elastic_fault_entry(_TENANT_LOCK, _TENANT_FAULTS, {
        "kind": "flood", "tenant": str(tenant), "rps": int(rps),
        "remaining": int(times), "fired": 0})


def unregister_model_mid_flight(model: str):
    """Vanish ``model``'s registry entry at its next lookup, with
    requests still queued for it: every queued request must resolve
    typed NOT_FOUND (never INTERNAL_ERROR), its admission slot released
    and its KV pages returned to the pool."""
    return _elastic_fault_entry(_TENANT_LOCK, _TENANT_FAULTS, {
        "kind": "unregister", "model": str(model), "remaining": 1,
        "fired": 0})
