"""Deterministic replay: localize the first divergent step.

Because training state is *total* — parameters, optimizer slots, the
host RNG stream, and the input pipeline's order/cursor all live inside
the verified checkpoint (docs/determinism.md) — re-executing from
checkpoint K is bit-faithful: a healthy machine reproduces the flight
recorder's journal exactly.  So when a run's numbers are suspect (an
integrity vote fired, a loss curve bent oddly, a repro request), replay
is the microscope: restore checkpoint K, re-run to step N with a fresh
recorder, and diff the two journals.  The first fingerprint that
differs names the first divergent step AND the field that diverged —
``batch_id`` (the input pipeline fed different bytes), ``loss_bits`` /
``grad_norm_bits`` (the compute produced different numbers from the
same input), or ``param_crc`` (the state itself was perturbed between
steps).

This is the per-host complement of the cross-host vote in
:mod:`.integrity`: votes localize *which host* corrupts in a gang;
replay localizes *which step* (and which stage) on one host.
"""
from __future__ import annotations

import json
import logging
from typing import Callable, List, Optional

log = logging.getLogger("bigdl_tpu")

#: journal fields compared, in blame order: a batch_id mismatch
#: explains every later mismatch, so it is reported first
DIFF_FIELDS = ("batch_id", "loss_bits", "grad_norm_bits", "param_crc")


def load_journal(path: str) -> List[dict]:
    """Parse a flight-recorder JSONL journal; a torn trailing line
    (crash mid-write) is skipped, matching the append+flush contract."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                log.warning("journal %s: skipping torn line %r",
                            path, line[:80])
    return out


def diff_journals(expected: List[dict], actual: List[dict]
                  ) -> Optional[dict]:
    """First fingerprint divergence between two journals, or None.

    Records align on ``(kind, step)``; only steps present in BOTH
    journals are compared (replay starts mid-journal), scanned in step
    order so the returned divergence is the *first* one.  Returns
    ``{"step", "kind", "field", "expected", "actual"}``.
    """
    index = {(r.get("kind", "step"), r["step"]): r for r in actual}
    for rec in sorted(expected, key=lambda r: (r["step"],
                                               r.get("kind", "step"))):
        other = index.get((rec.get("kind", "step"), rec["step"]))
        if other is None:
            continue
        for field in DIFF_FIELDS:
            a, b = rec.get(field), other.get(field)
            if a is None or b is None:
                continue
            if a != b:
                return {"step": int(rec["step"]),
                        "kind": rec.get("kind", "step"),
                        "field": field, "expected": a, "actual": b}
    return None


def replay(make_optimizer: Callable, checkpoint_dir: str,
           journal_path: str, from_step: Optional[int] = None,
           end_step: Optional[int] = None,
           replay_journal: Optional[str] = None,
           param_crc_every: int = 0) -> dict:
    """Re-execute training from a checkpoint and localize divergence.

    ``make_optimizer`` must return a freshly configured optimizer
    (model, dataset, criterion, optim method — the same recipe as the
    original run); replay then

    1. restores the newest checkpoint at or below ``from_step`` from
       ``checkpoint_dir`` (verified walk-back; params, slots, RNG and
       pipeline cursor all come back),
    2. re-runs to ``end_step`` (default: the original journal's last
       step) with a fresh :class:`~.integrity.FlightRecorder` —
       checkpoint WRITES are disabled so the evidence directory is
       never touched,
    3. diffs the replayed journal against the original.

    Returns ``{"from_step", "end_step", "steps_compared",
    "divergence", "replay_journal"}`` where ``divergence`` is
    :func:`diff_journals`' verdict (None = the original run verifies
    bit-for-bit over the replayed window).
    """
    from ..optim.trigger import max_iteration
    from .integrity import FlightRecorder

    original = load_journal(journal_path)
    if not original:
        raise ValueError(f"journal {journal_path} is empty — nothing "
                         "to replay against")
    last = max(r["step"] for r in original)
    end_step = int(end_step or last)

    opt = make_optimizer()
    opt.checkpoint_path = str(checkpoint_dir)
    if not opt.resume_from_checkpoint(step=from_step):
        raise ValueError(
            f"no restorable checkpoint at or below step {from_step} "
            f"in {checkpoint_dir}")
    # replay is read-only on the evidence: never write new checkpoints
    # (or train state) into the directory under investigation
    opt.checkpoint_path = None
    opt.checkpoint_trigger = None

    rec_path = replay_journal or f"{journal_path}.replay"
    recorder = FlightRecorder(rec_path, param_crc_every=param_crc_every)
    opt.set_flight_recorder(recorder)
    opt.set_end_when(max_iteration(end_step))
    try:
        opt.optimize()
    finally:
        recorder.close()

    replayed = load_journal(rec_path)
    steps = {r["step"] for r in replayed}
    window = [r for r in original if r["step"] in steps]
    divergence = diff_journals(window, replayed)
    report = {
        "from_step": from_step, "end_step": end_step,
        "steps_compared": len({r["step"] for r in window}),
        "divergence": divergence, "replay_journal": rec_path,
    }
    if divergence is None:
        log.info("replay: %d step(s) reproduced bit-for-bit — no "
                 "divergence", report["steps_compared"])
    else:
        log.warning("replay: first divergence at step %d (%s: %s -> %s)",
                    divergence["step"], divergence["field"],
                    divergence["expected"], divergence["actual"])
    return report
