"""Bounded prefetch-to-device infeed — overlap batch N+1's host prep
with the compiled step on batch N.

Every optimizer mesh path used to either fetch synchronously (the
step waited on ``next(data_iter)`` + ``device_put`` every iteration)
or carry its own ad-hoc one-deep ``prefetch()`` closure inside the
driver loop.  This module is the one generalization: a
:class:`DevicePrefetcher` runs the fetch + host→device transfer on a
background producer thread into a bounded queue (default depth 2 —
double buffering), and the driver's ``get()`` measures *actual* stall
time — the seconds it really blocked on an empty buffer — which is the
only time the telemetry spine should ledger as ``data_stall``.
DeepSpark (arXiv:1602.08191) makes the same argument for overlapping
data movement with computation; INFEED_REHEARSAL.json measured the
decode pipeline at ~3x the consumption rate, so with any buffering the
steady-state stall is zero unless the pipeline is genuinely
data-bound.

Epoch semantics are preserved exactly: the producer stops once it has
fetched the epoch's record budget (never consuming past the epoch, so
rollover/shuffle/resume-cursor behavior is unchanged — the underlying
iterators shuffle from a clone, docs/determinism.md), and the driver
``reset()``-s the feed with the fresh iterator AFTER the shuffle — the one producer thread persists across epochs
(epochs can be two steps long; a thread spawn/join per epoch would be
its own stall).  By the time the driver reaches the rollover the
producer has exhausted its budget and is parked on the epoch
condition, so a fetch can never race the shuffle's index permutation.

Exceptions from the data pipeline (fault injectors, corrupt records,
``StopIteration`` from a finite iterator) are re-raised on the
training thread from ``get()``, exactly where a synchronous ``next``
would have raised them.

:class:`InlineFeed` is the same API without the thread (prefetch depth
0) — one driver code path serves both modes.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator, Optional

__all__ = ["DevicePrefetcher", "InlineFeed", "make_feed"]

_DONE = object()


def _count(name: str, help: str, n: float = 1.0):
    try:
        from ..telemetry import default_registry

        default_registry().counter(name, help).inc(n)
    except Exception:
        pass


class _Failure:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class InlineFeed:
    """Depth-0 feed: synchronous fetch with the same ``get()`` API —
    the whole fetch time is a real stall, reported as such."""

    def __init__(self, data_iter: Iterator,
                 transform: Optional[Callable] = None):
        self._it = data_iter
        self._transform = transform

    def get(self):
        t0 = time.perf_counter()
        batch = next(self._it)
        item = ((batch, *self._transform(batch)) if self._transform
                else (batch,))
        return item, time.perf_counter() - t0

    def reset(self, data_iter: Iterator, epoch_size=None,
              start_records: int = 0):
        self._it = data_iter
        return self

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


class DevicePrefetcher:
    """Background producer filling a bounded queue of device-ready
    batches.

    ``transform(batch)`` runs on the producer thread and returns the
    device-resident tuple (typically ``(x, y)`` via ``jnp.asarray`` —
    ``device_put`` dispatches asynchronously, so the transfer itself
    overlaps the running step too).  ``epoch_size``/``start_records``
    bound the producer to the current epoch: it stops *before*
    consuming a record past the budget, so an infinite epoch iterator
    is never over-read and the driver's rollover arithmetic is
    untouched.  One producer thread serves the feed's whole life;
    :meth:`reset` hands it the next epoch's iterator."""

    def __init__(self, data_iter: Iterator, *,
                 epoch_size: Optional[int] = None,
                 start_records: int = 0, depth: int = 2,
                 transform: Optional[Callable] = None,
                 name: str = "bigdl-infeed"):
        self.depth = max(1, int(depth))
        self._transform = transform
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._epoch = (data_iter, epoch_size, int(start_records))
        self._epoch_id = 0
        self.hits = 0     # get() served without blocking
        self.misses = 0   # get() blocked on an empty buffer (real stall)
        self.produced = 0
        self.epochs_fed = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._thread.start()

    # -- producer --------------------------------------------------------
    def _run(self):
        served = -1
        while True:
            with self._cv:
                while self._epoch_id == served and not self._stop.is_set():
                    self._cv.wait()
                if self._stop.is_set():
                    return
                served = self._epoch_id
                it, budget, fetched = self._epoch
            self.epochs_fed += 1
            while not self._stop.is_set():
                if budget is not None and fetched >= budget:
                    break  # epoch budget met: park until reset
                try:
                    batch = next(it)
                except BaseException as e:  # noqa: BLE001 — re-raised
                    # in get() on the training thread (StopIteration
                    # included: a finite iterator ending early surfaces
                    # exactly where a synchronous next() would have)
                    self._put(_Failure(e))
                    break
                try:
                    item = ((batch, *self._transform(batch))
                            if self._transform else (batch,))
                except BaseException as e:  # noqa: BLE001
                    self._put(_Failure(e))
                    break
                size = getattr(batch, "size", None)
                if callable(size):
                    try:
                        fetched += int(size())
                    except TypeError:
                        fetched += 1
                else:
                    fetched += 1
                self.produced += 1
                if not self._put(item):
                    break

    def _put(self, item) -> bool:
        """Bounded put that stays responsive to close(): returns False
        when the feed was closed while waiting for queue room."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer --------------------------------------------------------
    def get(self):
        """Next ``(item, stall_seconds)``.  ``stall_seconds`` > 0 only
        when the buffer was actually empty — the honest ``data_stall``
        figure.  Re-raises any producer-side exception here, on the
        training thread."""
        t0 = time.perf_counter()
        try:
            item = self._q.get_nowait()
            stall = 0.0
            self.hits += 1
            _count("bigdl_infeed_buffer_hits_total",
                   "infeed get() served from a non-empty buffer")
        except queue.Empty:
            item = self._q.get()
            stall = time.perf_counter() - t0
            self.misses += 1
            _count("bigdl_infeed_buffer_misses_total",
                   "infeed get() blocked on an empty buffer "
                   "(real data stall)")
        if isinstance(item, _Failure):
            raise item.exc
        return item, stall

    def reset(self, data_iter: Iterator,
              epoch_size: Optional[int] = None,
              start_records: int = 0):
        """Point the (persistent) producer at the next epoch's
        iterator.  The driver calls this AFTER consuming the previous
        epoch and AFTER the shuffle — at that point the producer has
        met its budget and is parked, so no fetch races the
        permutation."""
        with self._cv:
            self._epoch = (data_iter, epoch_size, int(start_records))
            self._epoch_id += 1
            self._cv.notify_all()
        return self

    def close(self, timeout: float = 10.0):
        """Stop the producer and join it — the barrier the driver runs
        at loop exit (and whenever the epoch contract below cannot be
        kept).  Idempotent."""
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        # unblock a producer stuck on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


def make_feed(data_iter: Iterator, *, epoch_size: Optional[int] = None,
              start_records: int = 0, depth: int = 2,
              transform: Optional[Callable] = None):
    """Feed factory the drivers use: ``depth >= 1`` builds the
    background :class:`DevicePrefetcher`; ``depth == 0`` the
    synchronous :class:`InlineFeed` (prefetch disabled)."""
    if int(depth) <= 0:
        return InlineFeed(data_iter, transform=transform)
    return DevicePrefetcher(data_iter, epoch_size=epoch_size,
                            start_records=start_records, depth=depth,
                            transform=transform)
