"""Synthetic Zipf clickstream — the DLRM workload's data source.

Real clickstreams are heavily skewed: a handful of hot users/items
absorb most lookups (rank-frequency follows a Zipf law; the bench uses
exponent 1.1 — the shape Parallax measures sparse-gradient wins on).
This generator reproduces that skew deterministically:

* per categorical table, row ids are drawn ``p(rank) proportional to
  (rank + 1) ** -exponent`` and mapped through a seeded permutation, so
  the hot rows are scattered across the table (a contiguous hot prefix
  would make row sharding trivially imbalanced in a way real tables
  are not);
* dense features are standard normals;
* the click label is Bernoulli of a sigmoid-scored hidden linear model
  over the dense features plus one hidden weight per (table, row) — so
  the stream is *learnable* and a descending loss means the model
  found the planted structure.

Built on :class:`~bigdl_tpu.dataset.dataset.LocalArrayDataSet`, so the
epoch order, shuffle state and record cursor ride the same
``state_dict`` machinery as every other dataset — checkpoint/resume
stays bitwise (docs/determinism.md).  Samples are
``Sample([dense, indices], label)`` with ``indices`` float 1-based
(``models.dlrm.DLRM``'s input layout).
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from ..utils.rng import np_stream
from .dataset import LocalArrayDataSet
from .sample import Sample


def zipf_probs(vocab: int, exponent: float = 1.1) -> np.ndarray:
    """Normalized Zipf rank probabilities over ``vocab`` ranks."""
    ranks = np.arange(1, vocab + 1, dtype=np.float64)
    p = ranks ** -float(exponent)
    return p / p.sum()


class ZipfClickstream(LocalArrayDataSet):
    """Seeded synthetic clickstream for ``table_sizes`` categorical
    tables plus ``dense_dim`` dense features.

    ``exponent`` is the Zipf rank exponent (1.1 default — the bench's
    skew).  ``seed`` routes through ``utils.rng.derive_seed`` so
    ``set_global_seed`` governs it like every other generator."""

    def __init__(self, n_records: int, table_sizes: Sequence[int],
                 dense_dim: int = 4, exponent: float = 1.1,
                 seed: int = 20):
        self.table_sizes = tuple(int(v) for v in table_sizes)
        self.dense_dim = int(dense_dim)
        self.exponent = float(exponent)
        rng = np_stream(seed)
        n = int(n_records)
        dense = rng.randn(n, self.dense_dim).astype(np.float32)
        idx = np.empty((n, len(self.table_sizes)), np.float32)
        score = dense @ rng.randn(self.dense_dim).astype(np.float32) * 0.5
        for t, vocab in enumerate(self.table_sizes):
            perm = rng.permutation(vocab)
            ranks = rng.choice(vocab, size=n,
                               p=zipf_probs(vocab, self.exponent))
            rows = perm[ranks]
            idx[:, t] = rows.astype(np.float32) + 1.0  # 1-based
            row_w = rng.randn(vocab).astype(np.float32)
            score = score + 0.5 * row_w[rows]
        prob = 1.0 / (1.0 + np.exp(-score))
        clicks = (rng.rand(n) < prob).astype(np.float32)
        super().__init__([
            Sample([dense[i], idx[i]], np.array([clicks[i]], np.float32))
            for i in range(n)])
