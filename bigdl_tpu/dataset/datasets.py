"""Dataset loaders (reference pyspark/bigdl/dataset/mnist.py & the
Scala load helpers in models/*/Utils).

Real IDX/CIFAR-binary files are parsed when present under ``data_dir``;
otherwise a deterministic synthetic set with learnable structure is
generated (class-dependent means) so examples/tests/benchmarks run
hermetically in this zero-egress environment.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

TRAIN_MEAN = 0.13066047740239506 * 255
TRAIN_STD = 0.3081078 * 255
TEST_MEAN = 0.13251460696903547 * 255
TEST_STD = 0.31048024 * 255

CIFAR_MEAN = (125.3, 123.0, 113.9)
CIFAR_STD = (63.0, 62.1, 66.7)


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        raw = f.read()
    magic, = struct.unpack(">i", raw[:4])
    ndim = magic % 256
    dims = struct.unpack(">" + "i" * ndim, raw[4:4 + 4 * ndim])
    return np.frombuffer(raw, np.uint8, offset=4 + 4 * ndim).reshape(dims)


def _synthetic_images(n: int, shape, n_classes: int, seed: int,
                      proto_seed: int = 1234):
    """Class-conditional gaussian blobs — learnable by small nets.

    ``proto_seed`` fixes the class prototypes across train/test splits
    (only labels+noise vary with ``seed``) so a trained model generalizes.
    """
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, n)
    protos = np.random.RandomState(proto_seed).rand(n_classes, *shape) * 255
    imgs = protos[labels] + rng.randn(n, *shape) * 25
    return np.clip(imgs, 0, 255).astype(np.uint8), (labels + 1).astype(np.float32)


def load_mnist(data_dir: Optional[str] = None, train: bool = True,
               synthetic_size: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (images (N, 28, 28) uint8, labels (N,) float 1-based)."""
    if data_dir:
        prefix = "train" if train else "t10k"
        for ext in ("", ".gz"):
            ip = os.path.join(data_dir, f"{prefix}-images-idx3-ubyte{ext}")
            lp = os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte{ext}")
            if os.path.exists(ip) and os.path.exists(lp):
                return _read_idx(ip), _read_idx(lp).astype(np.float32) + 1
    n = synthetic_size if train else synthetic_size // 4
    return _synthetic_images(n, (28, 28), 10, seed=0 if train else 1)


def load_cifar10(data_dir: Optional[str] = None, train: bool = True,
                 synthetic_size: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (images (N, 32, 32, 3) uint8 BGR, labels 1-based float)."""
    if data_dir:
        files = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
                 else ["test_batch.bin"])
        paths = [os.path.join(data_dir, f) for f in files]
        if all(os.path.exists(p) for p in paths):
            imgs, labels = [], []
            for p in paths:
                raw = np.fromfile(p, np.uint8).reshape(-1, 3073)
                labels.append(raw[:, 0])
                chw = raw[:, 1:].reshape(-1, 3, 32, 32)
                imgs.append(chw.transpose(0, 2, 3, 1)[..., ::-1])  # RGB→BGR HWC
            return (np.concatenate(imgs),
                    np.concatenate(labels).astype(np.float32) + 1)
    n = synthetic_size if train else synthetic_size // 4
    return _synthetic_images(n, (32, 32, 3), 10, seed=2 if train else 3)
