"""Dataset loaders (reference pyspark/bigdl/dataset/mnist.py & the
Scala load helpers in models/*/Utils).

Real IDX/CIFAR-binary files are parsed when present under ``data_dir``;
otherwise a deterministic synthetic set with learnable structure is
generated (class-dependent means) so examples/tests/benchmarks run
hermetically in this zero-egress environment.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from ..utils.rng import np_stream

TRAIN_MEAN = 0.13066047740239506 * 255
TRAIN_STD = 0.3081078 * 255
TEST_MEAN = 0.13251460696903547 * 255
TEST_STD = 0.31048024 * 255

CIFAR_MEAN = (125.3, 123.0, 113.9)
CIFAR_STD = (63.0, 62.1, 66.7)


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        raw = f.read()
    magic, = struct.unpack(">i", raw[:4])
    ndim = magic % 256
    dims = struct.unpack(">" + "i" * ndim, raw[4:4 + 4 * ndim])
    return np.frombuffer(raw, np.uint8, offset=4 + 4 * ndim).reshape(dims)


def _synthetic_images(n: int, shape, n_classes: int, seed: int,
                      proto_seed: int = 1234):
    """Class-conditional gaussian blobs — learnable by small nets.

    ``proto_seed`` fixes the class prototypes across train/test splits
    (only labels+noise vary with ``seed``) so a trained model generalizes.
    """
    rng = np_stream(seed)
    labels = rng.randint(0, n_classes, n)
    protos = np_stream(proto_seed).rand(n_classes, *shape) * 255
    imgs = protos[labels] + rng.randn(n, *shape) * 25
    return np.clip(imgs, 0, 255).astype(np.uint8), (labels + 1).astype(np.float32)


def load_mnist(data_dir: Optional[str] = None, train: bool = True,
               synthetic_size: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (images (N, 28, 28) uint8, labels (N,) float 1-based)."""
    if data_dir:
        prefix = "train" if train else "t10k"
        for ext in ("", ".gz"):
            ip = os.path.join(data_dir, f"{prefix}-images-idx3-ubyte{ext}")
            lp = os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte{ext}")
            if os.path.exists(ip) and os.path.exists(lp):
                return _read_idx(ip), _read_idx(lp).astype(np.float32) + 1
    n = synthetic_size if train else synthetic_size // 4
    return _synthetic_images(n, (28, 28), 10, seed=0 if train else 1)


def load_cifar10(data_dir: Optional[str] = None, train: bool = True,
                 synthetic_size: int = 2048) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (images (N, 32, 32, 3) uint8 BGR, labels 1-based float)."""
    if data_dir:
        files = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
                 else ["test_batch.bin"])
        paths = [os.path.join(data_dir, f) for f in files]
        if all(os.path.exists(p) for p in paths):
            imgs, labels = [], []
            for p in paths:
                raw = np.fromfile(p, np.uint8).reshape(-1, 3073)
                labels.append(raw[:, 0])
                chw = raw[:, 1:].reshape(-1, 3, 32, 32)
                imgs.append(chw.transpose(0, 2, 3, 1)[..., ::-1])  # RGB→BGR HWC
            return (np.concatenate(imgs),
                    np.concatenate(labels).astype(np.float32) + 1)
    n = synthetic_size if train else synthetic_size // 4
    return _synthetic_images(n, (32, 32, 3), 10, seed=2 if train else 3)


def load_news20(data_dir: Optional[str] = None, train: bool = True,
                synthetic_size: int = 512, n_classes: int = 20):
    """20-newsgroups-style corpus: list of (text, label 1-based float)
    (reference pyspark/bigdl/dataset/news20.py get_news20).

    When ``data_dir`` holds the extracted ``20_newsgroup/<group>/<file>``
    tree it is read; otherwise a synthetic corpus with class-specific
    keyword distributions (learnable by a bag-of-words classifier) is
    generated.
    """
    if data_dir and os.path.isdir(data_dir):
        texts = []
        groups = sorted(d for d in os.listdir(data_dir)
                        if os.path.isdir(os.path.join(data_dir, d)))
        for label, group in enumerate(groups, start=1):
            gdir = os.path.join(data_dir, group)
            for fname in sorted(os.listdir(gdir)):
                with open(os.path.join(gdir, fname), "rb") as f:
                    texts.append((f.read().decode("latin1"),
                                  np.float32(label)))
        if texts:
            return texts
    rng = np_stream(10 if train else 11)
    # 8 keywords per class + shared filler vocabulary
    filler = [f"word{i}" for i in range(100)]
    out = []
    for _ in range(synthetic_size if train else synthetic_size // 4):
        label = rng.randint(1, n_classes + 1)
        keywords = [f"topic{label}kw{k}" for k in range(8)]
        n_words = rng.randint(20, 60)
        words = [keywords[rng.randint(8)] if rng.rand() < 0.4
                 else filler[rng.randint(100)] for _ in range(n_words)]
        out.append((" ".join(words), np.float32(label)))
    return out


def load_movielens(data_dir: Optional[str] = None,
                   synthetic_size: int = 1000) -> np.ndarray:
    """MovieLens-1M style (user, item, rating) int triplets (reference
    pyspark/bigdl/dataset/movielens.py get_id_pairs/read_data_sets).
    Parses ``ratings.dat`` (``uid::mid::rating::ts``) when present,
    synthetic low-rank preference structure otherwise."""
    if data_dir:
        path = os.path.join(data_dir, "ratings.dat")
        if os.path.exists(path):
            rows = []
            with open(path) as f:
                for line in f:
                    parts = line.strip().split("::")
                    if len(parts) >= 3:
                        rows.append([int(parts[0]), int(parts[1]),
                                     int(float(parts[2]))])
            return np.asarray(rows, np.int64)
    rng = np_stream(12)
    n_users, n_items, rank = 100, 200, 4
    u = rng.randn(n_users, rank)
    v = rng.randn(n_items, rank)
    rows = []
    for _ in range(synthetic_size):
        uid = rng.randint(n_users)
        mid = rng.randint(n_items)
        score = u[uid] @ v[mid] + rng.randn() * 0.3
        rating = int(np.clip(np.round(3 + score), 1, 5))
        rows.append([uid + 1, mid + 1, rating])
    return np.asarray(rows, np.int64)


def get_glove_w2v(data_dir: Optional[str] = None, dim: int = 50,
                  vocab: Optional[list] = None):
    """word → vector map (reference pyspark/bigdl/dataset/news20.py
    get_glove_w2v).  Reads ``glove.6B.<dim>d.txt`` when present; otherwise
    deterministic random vectors per word (hash-seeded, stable across
    runs) for the given ``vocab``.
    """
    if data_dir:
        path = os.path.join(data_dir, f"glove.6B.{dim}d.txt")
        if os.path.exists(path):
            w2v = {}
            with open(path, encoding="utf8") as f:
                for line in f:
                    parts = line.rstrip().split(" ")
                    w2v[parts[0]] = np.asarray(parts[1:], np.float32)
            return w2v
    import zlib
    w2v = {}
    for word in vocab or []:
        seed = zlib.crc32(word.encode("utf8")) % (2 ** 31)
        w2v[word] = np_stream(seed).randn(dim).astype(np.float32)
    return w2v
