"""Transformer pipeline (reference dataset/Transformer.scala:44).

A ``Transformer[A, B]`` maps ``Iterator[A] → Iterator[B]``; stages chain
with ``->`` (here the ``>>`` operator or ``.and_then``) into a
``ChainedTransformer`` (Transformer.scala:86).  Cloning per worker
(reference cloneTransformer) maps to plain deepcopy — transformers stay
host-side; device work starts after batching.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Iterator


class Transformer:
    def apply(self, it: Iterator) -> Iterator:
        raise NotImplementedError

    def __call__(self, it: Iterator) -> Iterator:
        return self.apply(it)

    def and_then(self, other: "Transformer") -> "ChainedTransformer":
        return ChainedTransformer(self, other)

    # `a >> b` mirrors the reference's `a -> b`
    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        return self.and_then(other)

    def clone_transformer(self) -> "Transformer":
        return copy.deepcopy(self)


class ChainedTransformer(Transformer):
    def __init__(self, first: Transformer, last: Transformer):
        self.first, self.last = first, last

    def apply(self, it):
        return self.last(self.first(it))


class FnTransformer(Transformer):
    """Lift a per-element function into a Transformer."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def apply(self, it):
        return (self.fn(x) for x in it)


def transformer(fn: Callable[[Any], Any]) -> Transformer:
    return FnTransformer(fn)
