"""Bulk ingest (reference DataSet.ImageFolder/SeqFileFolder,
DataSet.scala:441-557, and the seqfile writer
dataset/image/BGRImgToLocalSeqFile.scala — SURVEY §2.5).

The reference stages ImageNet as Hadoop SequenceFiles of encoded BGR
images and reads them as a DistributedDataSet.  TPU-native equivalent:
TFRecord-framed shard files (same len|crc|data|crc framing as the
tensorboard writer, via the native CRC32C when built) — sharded so a
multi-host input pipeline can assign shards per host, read
sequentially (HBM-friendly large sequential IO), and shuffle by shard
order + in-shard index without loading everything.
"""
from __future__ import annotations

import os
import struct
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..visualization.crc32c import masked_crc32c
from .dataset import AbstractDataSet
from .sample import Sample

_DTYPES = {0: np.uint8, 1: np.float32, 2: np.float64, 3: np.int32,
           4: np.int64}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


class CorruptRecordError(IOError):
    """A record failed CRC verification or its framing is torn.  NOT
    retryable: the bytes on disk are wrong and will stay wrong —
    re-reading only burns the retry budget (transient I/O errors, by
    contrast, surface as plain OSError and are retried)."""


def _ingest_retry_policy():
    """Transient-I/O retry for shard reads (the resilience subsystem's
    RetryPolicy reused at the ingest layer): flaky NFS/FUSE/object-store
    reads get ``bigdl.ingest.retryTimes`` backoff-spaced attempts;
    corrupt records fail immediately."""
    from ..resilience.retry import RetryPolicy
    from ..utils.engine import get_property

    return RetryPolicy(
        max_retries=int(get_property("bigdl.ingest.retryTimes", 3)),
        backoff_base=float(get_property("bigdl.ingest.backoffBase", 0.05)),
        backoff_max=float(get_property("bigdl.ingest.backoffMax", 2.0)),
        fatal_types=(CorruptRecordError,))


# ----------------------------------------------------------------- records
def _encode_sample(sample: Sample) -> bytes:
    """feature dtype|ndim|dims|raw + label dtype|ndim|dims|raw."""
    out = bytearray()
    for arr in (np.asarray(sample.feature), np.asarray(sample.label)):
        # NOT ascontiguousarray — it promotes 0-d to (1,), breaking the
        # scalar-label shape round-trip
        if not arr.flags["C_CONTIGUOUS"]:
            arr = arr.copy()
        code = _DTYPE_CODES.get(arr.dtype)
        if code is None:
            arr = arr.astype(np.float32)
            code = _DTYPE_CODES[arr.dtype]
        out += struct.pack("<BB", code, arr.ndim)
        out += struct.pack(f"<{arr.ndim}i", *arr.shape)
        out += arr.tobytes()
    return bytes(out)


def _decode_sample(data: bytes) -> Sample:
    pos = 0
    arrays = []
    for _ in range(2):
        code, ndim = struct.unpack_from("<BB", data, pos)
        pos += 2
        shape = struct.unpack_from(f"<{ndim}i", data, pos)
        pos += 4 * ndim
        dtype = _DTYPES[code]
        n = int(np.prod(shape)) if ndim else 1
        arr = np.frombuffer(data, dtype, n, pos).reshape(shape)
        pos += arr.nbytes
        arrays.append(arr)
    return Sample(arrays[0], arrays[1])


class RecordFileWriter:
    """TFRecord framing: len | crc(len) | data | crc(data) — one shard.

    Writes follow the checkpoint layer's file_io discipline: the bytes
    go to a ``<path>.tmp.<pid>`` staging file and only a clean
    :meth:`close` — flush, fsync, rename, directory fsync — publishes
    ``<path>``.  A crash mid-write therefore leaves a staging file the
    shard listing ignores (it does not end in ``.records``), never a
    torn shard whose intact prefix would pass the CRC scan and silently
    shrink the dataset."""

    def __init__(self, path: str):
        self.path = str(path)
        self._tmp = f"{self.path}.tmp.{os.getpid()}"
        self._f = open(self._tmp, "wb")
        self.count = 0
        self.closed = False

    def write(self, data: bytes):
        if self.closed:
            raise ValueError(f"write to closed RecordFileWriter "
                             f"({self.path})")
        header = struct.pack("<Q", len(data))
        self._f.write(header)
        self._f.write(struct.pack("<I", masked_crc32c(header)))
        self._f.write(data)
        self._f.write(struct.pack("<I", masked_crc32c(data)))
        self.count += 1

    def close(self):
        if self.closed:
            return
        self.closed = True
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        os.replace(self._tmp, self.path)
        from ..utils.file_io import _fsync_dir

        _fsync_dir(os.path.dirname(self.path) or ".")

    def abort(self):
        """Drop the staging file without publishing (the crash-cleanup
        path for callers that know the shard is incomplete)."""
        if self.closed:
            return
        self.closed = True
        self._f.close()
        try:
            os.remove(self._tmp)
        except OSError:
            pass


def read_records(path: str, verify: bool = True,
                 zero_copy: bool = False) -> Iterator[bytes]:
    """Iterate a shard's payloads.  The framing scan + CRC verification
    runs in the native C++ runtime when built (one pass over the whole
    buffer on the thread pool's cache-friendly slicing-by-8 CRC);
    python fallback otherwise.

    ``zero_copy=True`` mmaps the shard and yields MEMORYVIEW payloads —
    no ``f.read`` staging copy and no per-record bytes copy, the two
    dominant costs of feeding a chip from a weak host (measured 1.6 GB/s
    each on the round-4 single-core rehearsal).  The views (and numpy
    arrays decoded from them) borrow the map, which is torn down by GC
    once the last view is dropped; consumers that hold records
    indefinitely must copy (the batcher's ``np.stack`` is the designed
    copy point)."""
    from .. import native
    from ..resilience import faults as _faults

    _faults.check_io_fault(path)  # deterministic test-injection hook
    if zero_copy and os.path.getsize(path) > 0:
        import mmap as _mmap

        with open(path, "rb") as f:
            mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
        buf = memoryview(mm)
    else:  # plain path, or an empty shard (mmap rejects empty files)
        with open(path, "rb") as f:
            buf = f.read()
    try:
        spans = native.parse_records(buf, verify=verify)
    except IOError as e:
        raise CorruptRecordError(f"corrupt record in {path}: {e}")
    if spans is not None:
        for off, length in spans:
            yield buf[off:off + length]
        return
    # pure-Python frame walk (no native lib): operates directly on the
    # memoryview — struct.unpack_from and the table CRC both accept it,
    # so the zero-copy borrow semantics match the native path and no
    # whole-shard bytes() materialization happens
    pos = 0
    while pos + 12 <= len(buf):
        (length,) = struct.unpack_from("<Q", buf, pos)
        (hcrc,) = struct.unpack_from("<I", buf, pos + 8)
        if pos + 16 + length > len(buf):
            # truncated/corrupt length field — same contract as the
            # native btpu_parse_records path
            raise CorruptRecordError(
                f"corrupt record in {path}: truncated at {pos}")
        data = buf[pos + 12:pos + 12 + length]
        (dcrc,) = struct.unpack_from("<I", buf, pos + 12 + length)
        if verify and (masked_crc32c(buf[pos:pos + 8]) != hcrc
                       or masked_crc32c(data) != dcrc):
            raise CorruptRecordError(f"corrupt record in {path}")
        yield data
        pos += 16 + length


def write_seq_files(samples: Sequence[Sample], folder: str,
                    shard_size: int = 1024,
                    prefix: str = "shard") -> List[str]:
    """Stage samples into sharded record files (reference
    BGRImgToLocalSeqFile.scala — blockSize images per SequenceFile)."""
    os.makedirs(folder, exist_ok=True)
    paths = []
    writer = None
    for i, s in enumerate(samples):
        if i % shard_size == 0:
            if writer:
                writer.close()
            path = os.path.join(folder,
                                f"{prefix}-{i // shard_size:05d}.records")
            paths.append(path)
            writer = RecordFileWriter(path)
        writer.write(_encode_sample(s))
    if writer:
        writer.close()
    return paths


class SeqFileFolder(AbstractDataSet):
    """DataSet over sharded record files (reference
    DataSet.SeqFileFolder:470-557).  ``shuffle()`` permutes shard order
    (in-shard order rides the shard — large sequential reads stay
    sequential); multi-host pipelines pass ``shard_index/shard_count``
    to read a disjoint shard subset per host.
    """

    def __init__(self, folder: str, shard_index: int = 0,
                 shard_count: int = 1, seed: int = 1):
        from ..utils.rng import RandomGenerator

        all_paths = sorted(
            os.path.join(folder, f) for f in os.listdir(folder)
            if f.endswith(".records"))
        self.paths = all_paths[shard_index::shard_count]
        self._order = list(range(len(self.paths)))
        # per-dataset generator (NOT the thread-local global RNG()):
        # shard-order shuffling draws from a stream this dataset owns,
        # so its position can be captured/restored for bitwise resume
        # and two datasets never race on one stream
        self._rng = RandomGenerator(seed)
        self._size: Optional[int] = None
        # shards whose CRCs have already been verified this process:
        # later epochs skip the CRC pass (the frame walk alone detects
        # truncation) — disk corruption is caught on first touch, and
        # re-hashing 100+ GB every epoch would starve the chip
        self._verified: set = set()

    def _read_shard(self, path: str) -> list:
        """One shard's records, with transient-I/O retry (exponential
        backoff via resilience.retry); corrupt records raise through
        immediately — re-reading bad bytes cannot help."""
        recs = _ingest_retry_policy().run(lambda: list(read_records(
            path, verify=path not in self._verified, zero_copy=True)))
        self._verified.add(path)
        return recs

    def size(self) -> int:
        if self._size is None:
            total = 0
            for p in self.paths:
                total += len(self._read_shard(p))
            self._size = total
        return self._size

    def shuffle(self):
        perm = self._rng.permutation(len(self._order))
        self._order = [self._order[int(i)] for i in perm]

    # -- checkpointable pipeline state (docs/determinism.md) -----------
    def state_dict(self) -> dict:
        """Shard order + the shuffle generator's exact stream position:
        restoring this and re-creating ``data(train=True)`` reproduces
        the record sequence bit-for-bit (iterators never mutate dataset
        state — they shuffle a cloned generator — so a state captured
        at any step boundary is exact, prefetch depth included)."""
        return {"order": list(self._order),
                "rng": self._rng.state_dict(),
                "n_shards": len(self.paths)}

    def load_state_dict(self, state: dict):
        if state.get("n_shards") == len(self.paths) and "order" in state:
            self._order = list(state["order"])
            self._rng.load_state_dict(state["rng"])
        return self

    def data(self, train: bool) -> Iterator[Sample]:
        # train iterators loop forever with a fresh shard-order shuffle
        # each pass (AbstractDataSet contract — reference
        # CachedDistriDataSet train iterator, DataSet.scala:255-299).
        # A one-shard-deep prefetch thread overlaps disk IO + CRC scan of
        # shard i+1 with sample decode of shard i; closing/abandoning the
        # generator stops the thread via the stop event.
        import queue
        import threading

        stop = threading.Event()
        q: "queue.Queue" = queue.Queue(maxsize=1)
        # train passes shuffle from a CLONE of the dataset generator:
        # the stream is a pure function of the dataset state at iterator
        # creation, and the prefetching producer can never race a
        # concurrent shuffle()/state_dict() on the shared stream — the
        # determinism contract resume depends on (docs/determinism.md)
        rng = self._rng.clone() if train else None

        def put_or_stop(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.5)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            # EVERY exit path must leave the consumer unblockable:
            # either the stop event is set (the consumer abandoned us —
            # nothing to deliver) or a sentinel/exception goes into the
            # queue.  A bare return without one would strand a consumer
            # blocked in q.get() forever.
            try:
                while not stop.is_set():
                    if train:
                        perm = rng.permutation(len(self._order))
                        order = [self._order[int(i)] for i in perm]
                    else:
                        order = list(self._order)  # snapshot per pass
                    for shard in order:
                        recs = self._read_shard(self.paths[shard])
                        if not put_or_stop(recs):
                            return
                    if not train:
                        put_or_stop(None)
                        return
            except Exception as e:  # surface IO/corruption to the consumer
                put_or_stop(e)
            except BaseException as e:
                # SystemExit & co. must not silently kill the thread
                # (and must not be re-raised verbatim in the consumer,
                # where SystemExit would take the whole process down)
                put_or_stop(RuntimeError(
                    f"ingest producer died: {type(e).__name__}: {e}"))

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        try:
            while True:
                # bounded get + liveness check: if the producer died
                # without managing to deliver (it tries hard above),
                # fail loudly instead of blocking forever — the
                # abandonment-race guard on the consumer side
                while True:
                    if stop.is_set():
                        return
                    try:
                        recs = q.get(timeout=0.5)
                        break
                    except queue.Empty:
                        if not thread.is_alive() and q.empty():
                            raise RuntimeError(
                                "ingest producer thread died without "
                                "delivering a result")
                if recs is None:
                    return
                if isinstance(recs, Exception):
                    raise recs
                for rec in recs:
                    yield _decode_sample(rec)
        finally:
            stop.set()


# ----------------------------------------------------------------- images
def image_folder(path: str, scale_to: Optional[int] = None
                 ) -> List[Tuple[np.ndarray, float]]:
    """Read a <path>/<class>/<image> tree into (BGR HWC uint8, 1-based
    label) pairs (reference DataSet.ImageFolder:441-470, LocalImgReader
    scaleTo).  Class ids are assigned by sorted directory name, matching
    the reference's consistent label mapping."""
    from PIL import Image

    classes = sorted(d for d in os.listdir(path)
                     if os.path.isdir(os.path.join(path, d)))
    out = []
    for label, cls in enumerate(classes, start=1):
        cdir = os.path.join(path, cls)
        for fname in sorted(os.listdir(cdir)):
            try:
                img = Image.open(os.path.join(cdir, fname)).convert("RGB")
            except Exception:
                continue
            if scale_to:
                w, h = img.size
                ratio = scale_to / min(w, h)
                img = img.resize((max(scale_to, int(w * ratio)),
                                  max(scale_to, int(h * ratio))))
            rgb = np.asarray(img, np.uint8)
            out.append((rgb[:, :, ::-1], float(label)))  # RGB→BGR
    return out
