from .dataset import (
    AbstractDataSet, LocalArrayDataSet, ShardedDataSet, TransformedDataSet,
    array, rdd, sort_data,
)
from .sample import (
    MiniBatch, PaddingParam, Sample, SampleToBatch, SampleToMiniBatch,
)
from .transformer import ChainedTransformer, FnTransformer, Transformer, transformer
from .ingest import (
    RecordFileWriter, SeqFileFolder, image_folder, read_records,
    write_seq_files,
)
from . import datasets, image, ingest, text
from .clickstream import ZipfClickstream, zipf_probs
from .prefetch import DevicePrefetcher, InlineFeed, make_feed


class DataSet:
    """Factory namespace matching the reference ``DataSet`` object
    (dataset/DataSet.scala:319-557: array/rdd/ImageFolder/SeqFileFolder);
    the free functions above are the primary API, this mirrors the
    reference spelling."""

    array = staticmethod(array)
    rdd = staticmethod(rdd)
    ImageFolder = staticmethod(image_folder)
    SeqFileFolder = SeqFileFolder
