from .dataset import (
    AbstractDataSet, LocalArrayDataSet, ShardedDataSet, TransformedDataSet,
    array, rdd, sort_data,
)
from .sample import (
    MiniBatch, PaddingParam, Sample, SampleToBatch, SampleToMiniBatch,
)
from .transformer import ChainedTransformer, FnTransformer, Transformer, transformer
from .ingest import (
    RecordFileWriter, SeqFileFolder, image_folder, read_records,
    write_seq_files,
)
from . import datasets, image, ingest, text
