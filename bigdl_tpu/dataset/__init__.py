from .dataset import (
    AbstractDataSet, LocalArrayDataSet, ShardedDataSet, TransformedDataSet,
    array, rdd, sort_data,
)
from .sample import (
    MiniBatch, PaddingParam, Sample, SampleToBatch, SampleToMiniBatch,
)
from .transformer import ChainedTransformer, FnTransformer, Transformer, transformer
from . import datasets, image, text
