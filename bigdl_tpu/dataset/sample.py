"""Sample / MiniBatch / SampleToMiniBatch (reference dataset/Sample.scala:31,
MiniBatch.scala:33-120, Transformer.scala:309).

MiniBatch holds stacked jax-ready numpy arrays (device transfer happens
once per batch in the optimizer — the infeed seam).  Padding params
reproduce the reference's variable-length NLP batching; batches are
padded to fixed bucket lengths so XLA sees static shapes.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from ..utils.table import Table
from .transformer import Transformer


class Sample:
    """One feature/label pair (reference dataset/Sample.scala:31).
    Multi-tensor features/labels are lists."""

    def __init__(self, feature, label):
        self.feature = feature
        self.label = label

    def feature_shape(self):
        return np.asarray(self.feature).shape

    def label_shape(self):
        return np.asarray(self.label).shape

    def __repr__(self):
        return f"Sample(feature={self.feature_shape()}, label={self.label_shape()})"


class PaddingParam:
    """Padding config (reference dataset/MiniBatch.scala:103-120 PaddingParam).

    ``padding_value``: fill value; ``fixed_length``: pad every batch to
    this length (static shapes for XLA) instead of the batch max.
    """

    def __init__(self, padding_value: float = 0.0,
                 fixed_length: Optional[int] = None):
        self.padding_value = padding_value
        self.fixed_length = fixed_length


class MiniBatch:
    """Stacked batch (reference dataset/MiniBatch.scala:33)."""

    def __init__(self, inputs, targets):
        self.inputs = inputs
        self.targets = targets

    def size(self) -> int:
        first = self.inputs if not isinstance(self.inputs, (list, tuple)) \
            else self.inputs[0]
        return np.asarray(first).shape[0]

    def get_input(self):
        return self.inputs

    def get_target(self):
        return self.targets

    def slice(self, offset: int, length: int) -> "MiniBatch":
        """1-based slice along the batch dim (reference MiniBatch.slice)."""
        s = slice(offset - 1, offset - 1 + length)

        def cut(x):
            if isinstance(x, (list, tuple)):
                return type(x)(cut(v) for v in x)
            return x[s]

        return MiniBatch(cut(self.inputs), cut(self.targets))


def _pad_stack(arrs: Sequence[np.ndarray], param: Optional[PaddingParam]):
    arrs = [np.asarray(a) for a in arrs]
    shapes = {a.shape for a in arrs}
    if len(shapes) == 1 and (param is None or param.fixed_length is None):
        return np.stack(arrs)
    if param is None:
        param = PaddingParam()
    max_dims = [max(a.shape[i] for a in arrs) for i in range(arrs[0].ndim)]
    if param.fixed_length is not None:
        max_dims[0] = max(param.fixed_length, max_dims[0])
    out = np.full([len(arrs)] + max_dims, param.padding_value,
                  dtype=arrs[0].dtype)
    for i, a in enumerate(arrs):
        idx = (i,) + tuple(slice(0, s) for s in a.shape)
        out[idx] = a
    return out


class SampleToMiniBatch(Transformer):
    """Group Samples into MiniBatches (reference Transformer.scala:309),
    with optional feature/label padding (PaddingParam)."""

    def __init__(self, batch_size: int,
                 feature_padding_param: Optional[PaddingParam] = None,
                 label_padding_param: Optional[PaddingParam] = None,
                 partition_num: Optional[int] = None,
                 drop_last: bool = False):
        self.batch_size = batch_size
        self.feature_padding_param = feature_padding_param
        self.label_padding_param = label_padding_param
        self.drop_last = drop_last

    def apply(self, it: Iterator[Sample]) -> Iterator[MiniBatch]:
        buf: List[Sample] = []
        for s in it:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self.make(buf)
                buf = []
        if buf and not self.drop_last:
            yield self.make(buf)

    def make(self, buf: List[Sample]) -> MiniBatch:
        multi_f = isinstance(buf[0].feature, (list, tuple))
        multi_l = isinstance(buf[0].label, (list, tuple))
        if multi_f:
            feats = [
                _pad_stack([s.feature[i] for s in buf], self.feature_padding_param)
                for i in range(len(buf[0].feature))]
        else:
            feats = _pad_stack([s.feature for s in buf], self.feature_padding_param)
        if multi_l:
            labels = [
                _pad_stack([s.label[i] for s in buf], self.label_padding_param)
                for i in range(len(buf[0].label))]
        else:
            labels = _pad_stack([s.label for s in buf], self.label_padding_param)
        return MiniBatch(feats, labels)

    #: compat alias — ``make`` is public API now (the eval/predict
    #: drivers build tail batches directly); old callers keep working
    _make = make


SampleToBatch = SampleToMiniBatch  # reference Transformer.scala:136 alias
