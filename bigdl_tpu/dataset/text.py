"""Text pipeline (reference dataset/text/ — SURVEY §2.5).

Host-side tokenize → index → sample stages feeding the ``Transformer``
chain, rebuilt without the OpenNLP/Hadoop dependencies:

- ``SentenceSplitter``   (SentenceSplitter.scala:33)  document → sentences
- ``SentenceTokenizer``  (SentenceTokenizer.scala:34) sentence → tokens
- ``SentenceBiPadding``  (SentenceBiPadding.scala:27) wraps with start/end
- ``Dictionary``         (Dictionary.scala:32)        top-k vocab by freq
- ``TextToLabeledSentence`` (TextToLabeledSentence.scala:43) next-word LM pairs
- ``LabeledSentenceToSample`` (LabeledSentenceToSample.scala:55) one-hot Samples

TPU notes: everything here is host preprocessing; static shapes for XLA
come from ``fix_data_length``/``fix_label_length`` (the reference's
padding contract) or from ``SampleToMiniBatch``'s ``PaddingParam``.
"""
from __future__ import annotations

import os
import re
from collections import Counter
from typing import Iterable, List, Optional, Sequence

import numpy as np

from .sample import Sample
from .transformer import Transformer

SENTENCE_START = "SENTENCESTART"  # reference utils/SentenceToken.scala
SENTENCE_END = "SENTENCEEND"


class SentenceSplitter(Transformer):
    """Document string → list of sentence strings.

    The reference uses OpenNLP when a model file is given and splits on
    periods otherwise (SentenceSplitter.scala:70-73); only the
    dependency-free default survives here.
    """

    def apply(self, it):
        return (sent for doc in it for sent in doc.split(".")
                if sent.strip())


class SentenceTokenizer(Transformer):
    """Sentence string → token array (SentenceTokenizer.scala:51-66).

    The OpenNLP ``SimpleTokenizer`` default splits on whitespace and
    separates punctuation classes; a regex reproduces that behavior.
    """

    _TOKEN = re.compile(r"\w+|[^\w\s]+")

    def apply(self, it):
        return (self._TOKEN.findall(sentence) for sentence in it)


class SentenceBiPadding(Transformer):
    """x → "start x end" (SentenceBiPadding.scala:35-40)."""

    def __init__(self, start: Optional[str] = None, end: Optional[str] = None):
        self.start = start or SENTENCE_START
        self.end = end or SENTENCE_END

    def apply(self, it):
        return (f"{self.start} {x} {self.end}" for x in it)


class Dictionary:
    """Top-``vocab_size`` words by frequency; the rest are "discarded"
    (Dictionary.scala:192-200 ``update``).

    ``get_index`` maps unknown words to ``vocab_size`` (the out-of-vocab
    bucket, Dictionary.scala:68-70); ``get_word`` of an unknown index
    draws from the discard list (Dictionary.scala:87-91).
    """

    def __init__(self, sentences: Optional[Iterable[Sequence[str]]] = None,
                 vocab_size: int = 10000, directory: Optional[str] = None):
        if directory is not None:
            self._load(directory)
            return
        freq = Counter()
        n_sentences = 0
        for sentence in sentences or []:
            n_sentences += 1
            freq.update(sentence)
        # ascending by count, keep the top `length` tail — ties resolve
        # the same way for a stable word->index assignment
        ordered = sorted(freq.items(), key=lambda kv: (kv[1], kv[0]))
        length = min(vocab_size, len(ordered))
        kept = ordered[len(ordered) - length:]
        self._vocabulary = [w for w, _ in kept]
        self._word2index = {w: i for i, w in enumerate(self._vocabulary)}
        self._index2word = {i: w for w, i in self._word2index.items()}
        self._discard = [w for w, _ in ordered[:len(ordered) - length]]

    def vocab_size(self) -> int:
        return len(self._vocabulary)

    def discard_size(self) -> int:
        return len(self._discard)

    def vocabulary(self) -> List[str]:
        return list(self._vocabulary)

    def discard_vocab(self) -> List[str]:
        return list(self._discard)

    def word2index(self):
        return dict(self._word2index)

    def index2word(self):
        return dict(self._index2word)

    def get_index(self, word: str) -> int:
        return self._word2index.get(word, len(self._vocabulary))

    def get_word(self, index) -> str:
        index = int(index)
        if index in self._index2word:
            return self._index2word[index]
        from ..utils.rng import RNG
        if self._discard:
            return self._discard[int(RNG().random_int(0, len(self._discard)))]
        return self._index2word[int(RNG().random_int(0, len(self._vocabulary)))]

    def save(self, folder: str):
        """dictionary.txt ("word -> idx" lines) + discard.txt
        (Dictionary.scala:113-129)."""
        os.makedirs(folder, exist_ok=True)
        with open(os.path.join(folder, "dictionary.txt"), "w") as f:
            f.write("\n".join(f"{w} -> {i}"
                              for w, i in self._word2index.items()))
        with open(os.path.join(folder, "discard.txt"), "w") as f:
            f.write("\n".join(self._discard))

    def _load(self, directory: str):
        path = os.path.join(directory, "dictionary.txt")
        self._word2index = {}
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                word, idx = line.rstrip("\n").rsplit("->", 1)
                self._word2index[word.rstrip(" ")] = int(idx.lstrip(" "))
        self._index2word = {i: w for w, i in self._word2index.items()}
        self._vocabulary = list(self._word2index)
        discard_path = os.path.join(directory, "discard.txt")
        self._discard = []
        if os.path.exists(discard_path):
            with open(discard_path) as f:
                self._discard = [ln.rstrip("\n") for ln in f if ln.strip()]


class LabeledSentence:
    """Token-index sequence + its label sequence (text/Types.scala:37)."""

    def __init__(self, data, label):
        self.data = np.asarray(data, np.float32)
        self.label = np.asarray(label, np.float32)

    def data_length(self) -> int:
        return int(self.data.shape[0])

    def label_length(self) -> int:
        return int(self.label.shape[0])


class TextToLabeledSentence(Transformer):
    """Tokens → next-word-prediction pair: data = idx[:-1], label = idx[1:]
    (TextToLabeledSentence.scala:47-57)."""

    def __init__(self, dictionary: Dictionary):
        self.dictionary = dictionary

    def apply(self, it):
        def convert(sentence):
            idx = np.array([self.dictionary.get_index(w) for w in sentence],
                           np.float32)
            return LabeledSentence(idx[:-1], idx[1:])
        return (convert(s) for s in it)


class LabeledSentenceToSample(Transformer):
    """One-hot features + 1-based label targets
    (LabeledSentenceToSample.scala:68-118).

    Padding semantics match the reference exactly: feature positions past
    the sentence repeat the END token's one-hot; label positions past the
    sentence repeat the START token index (+1 for the 1-based
    ClassNLLCriterion target convention).
    """

    def __init__(self, vocab_length: int,
                 fix_data_length: Optional[int] = None,
                 fix_label_length: Optional[int] = None):
        self.vocab_length = vocab_length
        self.fix_data_length = fix_data_length
        self.fix_label_length = fix_label_length

    def apply(self, it):
        return (self._convert(s) for s in it)

    def _convert(self, sentence: LabeledSentence) -> Sample:
        data_length = self.fix_data_length or sentence.data_length()
        label_length = self.fix_label_length or sentence.label_length()
        feature = np.zeros((data_length, self.vocab_length), np.float32)
        label = np.zeros((label_length,), np.float32)

        start_token = float(sentence.data[0])
        end_token = (0 if label_length == 1
                     else int(sentence.label[sentence.label_length() - 1]))

        n = min(sentence.data_length(), data_length)
        feature[np.arange(n), sentence.data[:n].astype(np.int64)] = 1.0
        feature[n:, end_token] = 1.0

        m = min(sentence.label_length(), label_length)
        label[:m] = sentence.label[:m] + 1.0
        label[m:] = start_token + 1.0
        return Sample(feature, label)
