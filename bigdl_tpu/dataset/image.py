"""Image pipeline transformers (reference dataset/image/: GreyImg* for
MNIST, BGRImg* for CIFAR/ImageNet, HFlip, ColorJitter, Lighting, crop).

Images are numpy HWC float arrays on the host; all transforms are
host-side (the reference's MTLabeledBGRImgToBatch multithreading is
unnecessary — batching cost is trivial next to the jitted step)."""
from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from ..utils.rng import RNG
from .sample import Sample
from .transformer import Transformer


class GreyImgNormalizer(Transformer):
    """reference dataset/image/GreyImgNormalizer.scala"""

    def __init__(self, mean: float, std: float):
        self.mean, self.std = mean, std

    def apply(self, it):
        for img, label in it:
            yield (np.asarray(img, np.float32) - self.mean) / self.std, label


class BGRImgNormalizer(Transformer):
    """Per-channel normalize (reference dataset/image/BGRImgNormalizer.scala)."""

    def __init__(self, mean: Tuple[float, float, float],
                 std: Tuple[float, float, float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def apply(self, it):
        for img, label in it:
            yield (np.asarray(img, np.float32) - self.mean) / self.std, label


class HFlip(Transformer):
    """Random horizontal flip (reference dataset/image/HFlip.scala)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold

    def apply(self, it):
        for img, label in it:
            if RNG().uniform() < self.threshold:
                img = np.ascontiguousarray(np.asarray(img)[:, ::-1])
            yield img, label


class BGRImgCropper(Transformer):
    """Random crop (reference dataset/image/BGRImgCropper.scala)."""

    def __init__(self, crop_width: int, crop_height: int):
        self.cw, self.ch = crop_width, crop_height

    def apply(self, it):
        for img, label in it:
            img = np.asarray(img)
            h, w = img.shape[:2]
            y = int(RNG().random_int(0, max(h - self.ch, 0) + 1))
            x = int(RNG().random_int(0, max(w - self.cw, 0) + 1))
            yield img[y:y + self.ch, x:x + self.cw], label


class BGRImgRdmCropper(BGRImgCropper):
    """Random crop with zero padding (reference BGRImgRdmCropper.scala)."""

    def __init__(self, crop_width: int, crop_height: int, padding: int = 0):
        super().__init__(crop_width, crop_height)
        self.padding = padding

    def apply(self, it):
        def padded(src):
            for img, label in src:
                img = np.asarray(img)
                p = self.padding
                if p > 0:
                    img = np.pad(img, [(p, p), (p, p)] + [(0, 0)] * (img.ndim - 2))
                yield img, label

        return super().apply(padded(it))


class CenterCrop(Transformer):
    def __init__(self, crop_width: int, crop_height: int):
        self.cw, self.ch = crop_width, crop_height

    def apply(self, it):
        for img, label in it:
            img = np.asarray(img)
            h, w = img.shape[:2]
            y, x = (h - self.ch) // 2, (w - self.cw) // 2
            yield img[y:y + self.ch, x:x + self.cw], label


class ColorJitter(Transformer):
    """Random brightness/contrast/saturation (reference
    dataset/image/ColorJitter.scala)."""

    def __init__(self, delta: float = 0.4):
        self.delta = delta

    def apply(self, it):
        for img, label in it:
            img = np.asarray(img, np.float32)
            order = RNG().permutation(3)
            for o in order:
                alpha = 1.0 + float(RNG().uniform(-self.delta, self.delta))
                if o == 0:  # brightness
                    img = img * alpha
                elif o == 1:  # contrast
                    img = img * alpha + (1 - alpha) * img.mean()
                else:  # saturation
                    grey = img.mean(axis=-1, keepdims=True)
                    img = img * alpha + (1 - alpha) * grey
            yield img, label


class Lighting(Transformer):
    """AlexNet PCA lighting noise (reference dataset/image/Lighting.scala)."""

    EIGVAL = np.array([0.2175, 0.0188, 0.0045], np.float32)
    EIGVEC = np.array([[-0.5675, 0.7192, 0.4009],
                       [-0.5808, -0.0045, -0.8140],
                       [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alphastd: float = 0.1):
        self.alphastd = alphastd

    def apply(self, it):
        for img, label in it:
            alpha = RNG().normal(0, self.alphastd, (3,)).astype(np.float32)
            shift = (self.EIGVEC * alpha * self.EIGVAL).sum(axis=1)
            yield np.asarray(img, np.float32) + shift, label


class GreyImgToSample(Transformer):
    """(H, W) grey image + 1-based label → Sample with (1, H, W) feature
    (reference GreyImgToSample.scala / GreyImgToBatch)."""

    def apply(self, it):
        for img, label in it:
            feat = np.asarray(img, np.float32)[None, :, :]
            yield Sample(feat, np.float32(label))


class BGRImgToSample(Transformer):
    """HWC BGR image → CHW Sample (reference BGRImgToSample.scala)."""

    def apply(self, it):
        for img, label in it:
            feat = np.asarray(img, np.float32).transpose(2, 0, 1)
            yield Sample(feat, np.float32(label))


class MTLabeledImgToBatch(Transformer):
    """(HWC image, label) stream → MiniBatch stream with native
    multithreaded normalize + layout + stack (reference
    dataset/image/MTLabeledBGRImgToBatch.scala:46 — one worker per image
    chunk assembling a shared batch buffer; here the chunked copy runs in
    the C++ thread pool, bigdl_tpu/native batch_images)."""

    def __init__(self, batch_size: int, mean=(0.0, 0.0, 0.0),
                 std=(1.0, 1.0, 1.0), drop_last: bool = False):
        self.batch_size = batch_size
        self.mean, self.std = mean, std
        self.drop_last = drop_last

    def apply(self, it):
        from .. import native
        from .sample import MiniBatch

        buf, labels = [], []
        for img, label in it:
            buf.append(np.asarray(img))
            labels.append(np.float32(label))
            if len(buf) == self.batch_size:
                yield self._make(native, MiniBatch, buf, labels)
                buf, labels = [], []
        if buf and not self.drop_last:
            yield self._make(native, MiniBatch, buf, labels)

    def _make(self, native, MiniBatch, buf, labels):
        batch = native.batch_images(np.stack(buf), self.mean, self.std)
        return MiniBatch(batch, np.asarray(labels, np.float32))
