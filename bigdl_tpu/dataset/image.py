"""Image pipeline transformers (reference dataset/image/: GreyImg* for
MNIST, BGRImg* for CIFAR/ImageNet, HFlip, ColorJitter, Lighting, crop).

Images are numpy HWC float arrays on the host; all transforms are
host-side (the reference's MTLabeledBGRImgToBatch multithreading is
unnecessary — batching cost is trivial next to the jitted step)."""
from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from ..utils.rng import RNG
from .sample import Sample
from .transformer import Transformer


class GreyImgNormalizer(Transformer):
    """reference dataset/image/GreyImgNormalizer.scala"""

    def __init__(self, mean: float, std: float):
        self.mean, self.std = mean, std

    def apply(self, it):
        for img, label in it:
            yield (np.asarray(img, np.float32) - self.mean) / self.std, label


class BGRImgNormalizer(Transformer):
    """Per-channel normalize (reference dataset/image/BGRImgNormalizer.scala)."""

    def __init__(self, mean: Tuple[float, float, float],
                 std: Tuple[float, float, float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def apply(self, it):
        for img, label in it:
            yield (np.asarray(img, np.float32) - self.mean) / self.std, label


class HFlip(Transformer):
    """Random horizontal flip (reference dataset/image/HFlip.scala)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold

    def apply(self, it):
        for img, label in it:
            if RNG().uniform() < self.threshold:
                img = np.ascontiguousarray(np.asarray(img)[:, ::-1])
            yield img, label


class BGRImgCropper(Transformer):
    """Random crop (reference dataset/image/BGRImgCropper.scala)."""

    def __init__(self, crop_width: int, crop_height: int):
        self.cw, self.ch = crop_width, crop_height

    def apply(self, it):
        for img, label in it:
            img = np.asarray(img)
            h, w = img.shape[:2]
            y = int(RNG().random_int(0, max(h - self.ch, 0) + 1))
            x = int(RNG().random_int(0, max(w - self.cw, 0) + 1))
            yield img[y:y + self.ch, x:x + self.cw], label


class BGRImgRdmCropper(BGRImgCropper):
    """Random crop with zero padding (reference BGRImgRdmCropper.scala)."""

    def __init__(self, crop_width: int, crop_height: int, padding: int = 0):
        super().__init__(crop_width, crop_height)
        self.padding = padding

    def apply(self, it):
        def padded(src):
            for img, label in src:
                img = np.asarray(img)
                p = self.padding
                if p > 0:
                    img = np.pad(img, [(p, p), (p, p)] + [(0, 0)] * (img.ndim - 2))
                yield img, label

        return super().apply(padded(it))


class CenterCrop(Transformer):
    def __init__(self, crop_width: int, crop_height: int):
        self.cw, self.ch = crop_width, crop_height

    def apply(self, it):
        for img, label in it:
            img = np.asarray(img)
            h, w = img.shape[:2]
            y, x = (h - self.ch) // 2, (w - self.cw) // 2
            yield img[y:y + self.ch, x:x + self.cw], label


class ColorJitter(Transformer):
    """Random brightness/contrast/saturation (reference
    dataset/image/ColorJitter.scala)."""

    def __init__(self, delta: float = 0.4):
        self.delta = delta

    def apply(self, it):
        for img, label in it:
            img = np.asarray(img, np.float32)
            order = RNG().permutation(3)
            for o in order:
                alpha = 1.0 + float(RNG().uniform(-self.delta, self.delta))
                if o == 0:  # brightness
                    img = img * alpha
                elif o == 1:  # contrast
                    img = img * alpha + (1 - alpha) * img.mean()
                else:  # saturation
                    grey = img.mean(axis=-1, keepdims=True)
                    img = img * alpha + (1 - alpha) * grey
            yield img, label


class Lighting(Transformer):
    """AlexNet PCA lighting noise (reference dataset/image/Lighting.scala)."""

    EIGVAL = np.array([0.2175, 0.0188, 0.0045], np.float32)
    EIGVEC = np.array([[-0.5675, 0.7192, 0.4009],
                       [-0.5808, -0.0045, -0.8140],
                       [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alphastd: float = 0.1):
        self.alphastd = alphastd

    def apply(self, it):
        for img, label in it:
            alpha = RNG().normal(0, self.alphastd, (3,)).astype(np.float32)
            shift = (self.EIGVEC * alpha * self.EIGVAL).sum(axis=1)
            yield np.asarray(img, np.float32) + shift, label


class GreyImgToSample(Transformer):
    """(H, W) grey image + 1-based label → Sample with (1, H, W) feature
    (reference GreyImgToSample.scala / GreyImgToBatch)."""

    def apply(self, it):
        for img, label in it:
            feat = np.asarray(img, np.float32)[None, :, :]
            yield Sample(feat, np.float32(label))


class BGRImgToSample(Transformer):
    """HWC BGR image → CHW Sample (reference BGRImgToSample.scala)."""

    def apply(self, it):
        for img, label in it:
            feat = np.asarray(img, np.float32).transpose(2, 0, 1)
            yield Sample(feat, np.float32(label))


class MTLabeledImgToBatch(Transformer):
    """(HWC image, label) stream → MiniBatch stream with native
    multithreaded normalize + layout + stack (reference
    dataset/image/MTLabeledBGRImgToBatch.scala:46 — one worker per image
    chunk assembling a shared batch buffer; here the chunked copy runs in
    the C++ thread pool, bigdl_tpu/native batch_images).

    ``device_normalize=True`` moves normalize + NHWC→NCHW onto the
    accelerator: the host emits a pure uint8 stack (memcpy speed) and
    the model starts with ``nn.ImageNormalize(mean, std)``, which XLA
    fuses into the stem conv.  Use when the host is infeed-bound
    (docs/PERF.md round-4: a 1-core host tripled its pipeline rate)."""

    def __init__(self, batch_size: int, mean=(0.0, 0.0, 0.0),
                 std=(1.0, 1.0, 1.0), drop_last: bool = False,
                 device_normalize: bool = False):
        self.batch_size = batch_size
        self.mean, self.std = mean, std
        self.drop_last = drop_last
        self.device_normalize = device_normalize

    def apply(self, it):
        from .. import native
        from .sample import MiniBatch

        buf, labels = [], []
        for img, label in it:
            buf.append(np.asarray(img))
            labels.append(np.float32(label))
            if len(buf) == self.batch_size:
                yield self._make(native, MiniBatch, buf, labels)
                buf, labels = [], []
        if buf and not self.drop_last:
            yield self._make(native, MiniBatch, buf, labels)

    def _make(self, native, MiniBatch, buf, labels):
        if self.device_normalize:
            # uint8 NHWC stack only — normalization belongs to the
            # device (nn.ImageNormalize at the head of the model)
            return MiniBatch(np.stack(buf),
                             np.asarray(labels, np.float32))
        batch = native.batch_images(np.stack(buf), self.mean, self.std)
        return MiniBatch(batch, np.asarray(labels, np.float32))


class BGRImgPixelNormalizer(Transformer):
    """Subtract a full per-pixel mean image (reference
    dataset/image/BGRImgPixelNormalizer.scala: content - means,
    elementwise over the whole H*W*3 buffer)."""

    def __init__(self, means):
        self.means = np.asarray(means, np.float32)

    def apply(self, it):
        for img, label in it:
            img = np.asarray(img, np.float32)
            if img.size != self.means.size:
                raise ValueError(
                    f"mean image has {self.means.size} values, image has "
                    f"{img.size}")
            yield img - self.means.reshape(img.shape), label


class BytesToBGRImg(Transformer):
    """(bytes, label) record → (HWC BGR float image, label).  Record
    layout per the reference (BytesToBGRImg.scala:33): 4-byte big-endian
    width, 4-byte big-endian height, then H*W*3 BGR pixel bytes; pixels
    are divided by ``normalize``."""

    def __init__(self, normalize: float = 255.0):
        self.normalize = float(normalize)

    def apply(self, it):
        for data, label in it:
            w = int.from_bytes(data[0:4], "big")
            h = int.from_bytes(data[4:8], "big")
            px = np.frombuffer(data, np.uint8, h * w * 3, offset=8)
            img = px.reshape(h, w, 3).astype(np.float32) / self.normalize
            yield img, label


class BytesToGreyImg(Transformer):
    """(bytes, label) → (row x col grey float image /255, label)
    (reference BytesToGreyImg.scala:33; MNIST idx pixel payload)."""

    def __init__(self, row: int, col: int):
        self.row, self.col = row, col

    def apply(self, it):
        for data, label in it:
            px = np.frombuffer(data, np.uint8)
            if px.size != self.row * self.col:
                raise ValueError(
                    f"record has {px.size} bytes, expected "
                    f"{self.row}x{self.col}")
            yield (px.reshape(self.row, self.col).astype(np.float32)
                   / 255.0), label


class GreyImgCropper(BGRImgCropper):
    """Random crop on (H, W) grey images (reference GreyImgCropper.scala)
    — the crop body is dimension-agnostic, so the BGR cropper serves."""


class GreyImgToBatch(Transformer):
    """Grey image stream → MiniBatch stream with (B, H, W) features
    (reference GreyImgToBatch.scala:36; trailing partial batch kept)."""

    def __init__(self, batch_size: int):
        self.batch_size = batch_size

    def _stack(self, imgs, labels):
        from .sample import MiniBatch

        return MiniBatch(np.stack(imgs).astype(np.float32),
                         np.asarray(labels, np.float32))

    def apply(self, it):
        imgs, labels = [], []
        for img, label in it:
            imgs.append(np.asarray(img, np.float32))
            labels.append(np.float32(label))
            if len(imgs) == self.batch_size:
                yield self._stack(imgs, labels)
                imgs, labels = [], []
        if imgs:
            yield self._stack(imgs, labels)


class BGRImgToBatch(GreyImgToBatch):
    """HWC BGR image stream → MiniBatch stream with (B, 3, H, W) CHW
    features (reference BGRImgToBatch.scala)."""

    def _stack(self, imgs, labels):
        from .sample import MiniBatch

        feat = np.stack(imgs).astype(np.float32).transpose(0, 3, 1, 2)
        return MiniBatch(feat, np.asarray(labels, np.float32))


class LocalImgReader(Transformer):
    """(path, label) → (HWC BGR float image / normalize, label).
    ``scale_to`` resizes the shorter edge (aspect preserved, reference
    LocalScaleImgReader); ``resize_w``/``resize_h`` force both edges
    (reference LocalResizeImgReader).  Uses PIL, as the seq-file ingest
    already does (ingest.py)."""

    NO_SCALE = -1

    def __init__(self, scale_to: int = NO_SCALE, normalize: float = 255.0,
                 resize_w: Optional[int] = None,
                 resize_h: Optional[int] = None):
        self.scale_to = scale_to
        self.normalize = float(normalize)
        self.resize_w, self.resize_h = resize_w, resize_h

    def _load(self, path):
        from PIL import Image

        im = Image.open(path).convert("RGB")
        if self.resize_w is not None and self.resize_h is not None:
            im = im.resize((self.resize_w, self.resize_h), Image.BILINEAR)
        elif self.scale_to != self.NO_SCALE:
            w, h = im.size
            if w < h:
                im = im.resize(
                    (self.scale_to, max(1, h * self.scale_to // w)),
                    Image.BILINEAR)
            else:
                im = im.resize(
                    (max(1, w * self.scale_to // h), self.scale_to),
                    Image.BILINEAR)
        rgb = np.asarray(im, np.float32)
        return rgb[:, :, ::-1] / self.normalize  # BGR, like the reference

    def apply(self, it):
        for path, label in it:
            yield self._load(path), label


# reference class name (dataset/image/MTLabeledBGRImgToBatch.scala:46)
MTLabeledBGRImgToBatch = MTLabeledImgToBatch
