"""DataSet abstractions (reference dataset/DataSet.scala:46-557).

``LocalArrayDataSet`` mirrors the reference's in-memory dataset with
index-array shuffling (CachedDistriDataSet.shuffle, DataSet.scala:292).
``ShardedDataSet`` is the TPU-native replacement for
``DistributedDataSet``: instead of one RDD partition per executor, one
host iterator yields *global* batches that the distributed optimizer
shards over the mesh's data axis (device_put with a NamedSharding — the
infeed analogue of ZippedPartitionsWithLocalityRDD colocation).
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from ..utils.rng import RNG
from .transformer import Transformer


class AbstractDataSet:
    """reference dataset/DataSet.scala:46"""

    def data(self, train: bool) -> Iterator:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self):
        raise NotImplementedError

    def transform(self, transformer: Transformer) -> "TransformedDataSet":
        return TransformedDataSet(self, transformer)

    # -- checkpointable pipeline state (docs/determinism.md) -----------
    # Datasets that own ordering/shuffling state override these so the
    # optimizer can capture the input pipeline inside a checkpoint and
    # resume on the exact next batch.  The base contract is "stateless":
    # safe for purely functional sources.
    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, state: dict):
        return self

    # `ds -> transformer` spelled `ds >> transformer`
    def __rshift__(self, transformer: Transformer):
        return self.transform(transformer)


class LocalArrayDataSet(AbstractDataSet):
    """In-memory dataset with index shuffling (reference DataSet.scala:128)."""

    def __init__(self, data: Sequence):
        self._data = list(data)
        self._index = np.arange(len(self._data))

    def size(self) -> int:
        return len(self._data)

    def shuffle(self):
        RNG().shuffle(self._index)
        return self

    def state_dict(self) -> dict:
        # the live index permutation IS the epoch's record order; the
        # shuffler (the thread-local RNG()) is captured separately by
        # the optimizer's train-state checkpoint
        return {"index": np.array(self._index)}

    def load_state_dict(self, state: dict):
        idx = np.asarray(state.get("index", ()))
        if idx.shape == self._index.shape:
            self._index = idx.copy()
        return self

    def data(self, train: bool) -> Iterator:
        if train:
            # infinite looping iterator (reference DataSet.scala:255-288)
            def gen():
                while True:
                    for i in self._index:
                        yield self._data[i]

            return gen()
        return (self._data[i] for i in range(len(self._data)))


class TransformedDataSet(AbstractDataSet):
    def __init__(self, base: AbstractDataSet, transformer: Transformer):
        self.base = base
        self.transformer = transformer

    def size(self) -> int:
        return self.base.size()

    def shuffle(self):
        self.base.shuffle()
        return self

    def state_dict(self) -> dict:
        return self.base.state_dict()

    def load_state_dict(self, state: dict):
        self.base.load_state_dict(state)
        return self

    def data(self, train: bool) -> Iterator:
        return self.transformer(self.base.data(train))


class ShardedDataSet(LocalArrayDataSet):
    """Distributed-dataset seam: batches from here are device_put with a
    ``data``-axis sharding by the DistriOptimizer (P1 in SURVEY §2.2).
    ``partition_num`` is kept for API parity; sharding happens at infeed.
    """

    def __init__(self, data: Sequence, partition_num: int = 1):
        super().__init__(data)
        self.partition_num = partition_num


def array(data: Sequence) -> LocalArrayDataSet:
    """reference DataSet.array (DataSet.scala:325)"""
    return LocalArrayDataSet(data)


def rdd(data: Sequence, partition_num: int = 1) -> ShardedDataSet:
    """reference DataSet.rdd (DataSet.scala:348) — host-sharded stand-in."""
    return ShardedDataSet(data, partition_num)


def sort_data(samples, ascending: bool = True):
    """Length-sorted batching helper (reference DataSet.sortData:372-400)."""
    return sorted(samples, key=lambda s: np.asarray(s.feature).shape[0],
                  reverse=not ascending)
