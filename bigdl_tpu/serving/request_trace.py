"""Cross-replica request tracing — sink, recorder, stitcher.

PR 9's fleet made requests hop processes (retries, hedges, the
prefill→decode handoff); the per-process tracer left three
unstitchable span fragments per hedged request.  This module closes
the loop:

* :class:`ReplicaTraceSink` — bound into each replica: request-phase
  spans (``queue`` / ``batch`` / ``execute`` / ``prefill`` /
  ``decode`` / ``kv_gather`` / ``error`` — the shared vocabulary in
  :mod:`bigdl_tpu.telemetry.trace_context`) land in the replica's own
  :class:`~bigdl_tpu.telemetry.Tracer` ring AND accumulate per trace;
  when the request resolves, the fragment publishes over the elastic
  KV transport under ``trc/<incarnation>/<trace_id>/<host>`` riding a
  :class:`~bigdl_tpu.telemetry.BackgroundPublisher` — the hot path
  never blocks on transport I/O.
* :class:`RequestTracer` — router-side: mints the
  :class:`~bigdl_tpu.telemetry.trace_context.TraceContext` at submit,
  records the root ``request`` span and one ``attempt`` span per
  dispatch (primary / retry / hedge — each carrying the REMAINING
  deadline budget at fork time), runs the
  :class:`~bigdl_tpu.telemetry.trace_context.TailSampler` at
  completion, and **stitches** kept traces: fragments are collected
  from the KV keyspace, clock-aligned per host (mono/wall anchor
  pairs), hedge-loser attempts labeled ``hedge_outcome=lost``, and the
  whole thing exported as one cross-replica Perfetto (Chrome-trace)
  timeline — one pid per host.
* :func:`trace_coverage` / :func:`trace_attribution` — the analysis
  layer ``tools/trace_report.py`` builds on: span-union coverage of
  the request wall clock (lost hedges excluded, so duplicate duty is
  never double-counted) and the queue/compute/transport phase
  attribution whose argmax names the critical path.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from ..telemetry.publish import BackgroundPublisher
from ..telemetry.trace_context import (TailSampler, TraceContext,
                                       TRACE_KV_PREFIX, trace_key)
from ..telemetry.tracer import Tracer, _check_category

log = logging.getLogger("bigdl_tpu")

__all__ = [
    "ReplicaTraceSink", "RequestTracer", "stitch_fragments",
    "trace_attribution", "trace_coverage",
]

#: phase attribution buckets the critical-path analysis reports: every
#: stitched span category maps into exactly one
PHASE_OF_CATEGORY = {
    "queue": "queue",
    "batch": "batch",
    "execute": "compute",
    "prefill": "compute",
    "decode": "compute",
    "kv_gather": "kv",
    "handoff": "transport",
    "swap_window": "swap",
    "error": "error",
}


def _clock_anchor(mono_clock: Callable[[], float]) -> dict:
    """A (monotonic, wall) clock pair sampled back-to-back — what lets
    the stitcher map another host's monotonic timeline onto its own."""
    return {"mono": float(mono_clock()), "wall": time.time()}


class ReplicaTraceSink:
    """Per-replica request-span recorder + background KV publisher.

    ``transport=None`` keeps fragments local (the router-side sink and
    unit tests); with a transport, :meth:`finish` publishes the
    fragment under ``trc/<incarnation>/<trace_id>/<host>`` through a
    never-blocking :class:`BackgroundPublisher`.
    """

    def __init__(self, host: str, transport=None,
                 incarnation_of: Optional[Callable[[], int]] = None,
                 publisher: Optional[BackgroundPublisher] = None,
                 capacity: int = 4096, max_traces: int = 512,
                 eager_publish: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        self.host = str(host)
        self.transport = transport
        #: eager: publish the fragment the moment the request resolves
        #: (standalone servers).  Lazy (the fleet wiring): buffer it
        #: and publish only when the router's TAIL decision keeps the
        #: trace (``publish_trace`` via ``RequestTracer.on_keep``) —
        #: dropped traces never touch the transport, which is what
        #: keeps tracing overhead inside the <=3% budget
        self.eager_publish = bool(eager_publish)
        self._incarnation_of = incarnation_of or (lambda: 0)
        self.tracer = Tracer(capacity=capacity, clock=clock)
        self._clock = clock
        self._lock = threading.Lock()
        # trace_id -> [span dicts]; bounded, oldest trace evicted
        self._by_trace: "OrderedDict[str, List[dict]]" = OrderedDict()
        self.max_traces = int(max_traces)
        self._next_span_id = 0
        self._bound: set = set()   # traces mirrored into the ring
        # recent hot-swap/canary windows: attached to any overlapping
        # trace at publish time (a canary stall explains a latency
        # spike better than "queue" ever could)
        self._swaps: List[dict] = []
        self.published = 0
        self.evicted_traces = 0
        self._publisher = publisher
        self._own_publisher = publisher is None

    # ------------------------------------------------------------ recording
    def record(self, ctx: Optional[TraceContext], name: str,
               category: str, start: float, duration: float,
               **args) -> None:
        """Retro-record one request-phase span for ``ctx`` (no-op for
        untraced / unsampled requests — the cost when tracing is off is
        one None check)."""
        if ctx is None or not ctx.sampled:
            return
        self.record_raw(ctx.trace_id, ctx.span_id, ctx.attempt, name,
                        category, start, duration, **args)

    def record_raw(self, trace_id: str, parent_span_id: int,
                   attempt: int, name: str, category: str,
                   start: float, duration: float, **args) -> None:
        """The context-free spelling.  Hot path: ONE dict + one lock —
        the span dict lands in the per-trace buffer; binding into the
        replica's Tracer ring happens at :meth:`fragment` time (i.e.
        for traces the tail sampler kept), never per request."""
        _check_category(category)
        args.update(trace_id=trace_id, parent_span_id=parent_span_id,
                    attempt=attempt, host=self.host)
        span = {"name": str(name), "cat": category,
                "start": float(start),
                "dur": max(0.0, float(duration)),
                "tid": threading.get_ident(), "args": args}
        with self._lock:
            self._next_span_id += 1
            span["id"] = self._next_span_id
            spans = self._by_trace.get(trace_id)
            if spans is None:
                spans = self._by_trace[trace_id] = []
                while len(self._by_trace) > self.max_traces:
                    self._by_trace.popitem(last=False)
                    self.evicted_traces += 1
            spans.append(span)

    def _bind_ring(self, trace_id: str, spans: List[dict]) -> None:
        """Mirror one kept trace's spans into the replica's Tracer
        ring (replica-local Perfetto export / category totals) — once
        per trace, off the request hot path."""
        with self._lock:
            if trace_id in self._bound:
                return
            self._bound.add(trace_id)
            while len(self._bound) > 4 * self.max_traces:
                self._bound.pop()
        for sp in spans:
            try:
                self.tracer.record(sp["name"], sp["cat"], sp["start"],
                                   sp["dur"], **(sp.get("args") or {}))
            except ValueError:
                pass

    def record_swap_window(self, start: float, duration: float,
                           outcome: str) -> None:
        """One hot-swap/canary window (``outcome``: ``installed`` |
        ``rejected``) — kept in a bounded recent list and attached to
        overlapping traces at publish."""
        span = self.tracer.record("swap", "swap_window", start,
                                  duration, host=self.host,
                                  outcome=outcome)
        if span is None:
            return
        with self._lock:
            self._swaps.append(span.to_dict())
            del self._swaps[:-64]

    # ------------------------------------------------------------ publishing
    def publisher(self) -> BackgroundPublisher:
        if self._publisher is None:
            self._publisher = BackgroundPublisher(
                incarnation_of=None,
                name=f"bigdl-trace-{self.host}")
        return self._publisher

    def fragment(self, trace_id: str) -> Optional[dict]:
        """The fragment payload for one trace (overlapping swap
        windows included), or None when nothing was recorded.  Called
        for KEPT traces (publish / stitch) — this is also where the
        trace binds into the replica's Tracer ring."""
        with self._lock:
            spans = list(self._by_trace.get(trace_id) or ())
            swaps = list(self._swaps)
        if not spans:
            return None
        self._bind_ring(trace_id, spans)
        t0 = min(s["start"] for s in spans)
        t1 = max(s["start"] + s["dur"] for s in spans)
        for sw in swaps:
            if sw["start"] < t1 and sw["start"] + sw["dur"] > t0:
                spans.append(sw)
        return {
            "host": self.host,
            "trace_id": trace_id,
            "incarnation": int(self._incarnation_of() or 0),
            "spans": spans,
            "clock_anchor": _clock_anchor(self._clock),
        }

    def finish(self, ctx: Optional[TraceContext]) -> None:
        """The request resolved on this replica: with eager
        publishing, queue its fragment now; with lazy (fleet)
        publishing, leave it buffered for the router's tail decision
        (``publish_trace``)."""
        if ctx is None or not ctx.sampled:
            return
        if self.eager_publish:
            self.publish_trace(ctx.trace_id)

    def publish_trace(self, trace_id: str) -> None:
        """Queue one trace's fragment for background publication
        (coalesced per (trace, host) — a decode retry on the same
        replica republishes the superset)."""
        if self.transport is None:
            return

        def publish():
            frag = self.fragment(trace_id)
            if frag is None:
                return
            self.transport.put(
                trace_key(frag["incarnation"], trace_id, self.host),
                json.dumps(frag))
            with self._lock:
                self.published += 1

        self.publisher().submit(publish,
                                key=f"trc:{trace_id}:{self.host}")

    def flush(self, timeout: float = 5.0) -> bool:
        """Drain pending fragment publications (the stitcher's read
        barrier)."""
        if self._publisher is None:
            return True
        return self._publisher.drain(timeout=timeout)

    def close(self):
        if self._publisher is not None and self._own_publisher:
            self._publisher.close()

    def snapshot(self) -> dict:
        with self._lock:
            return {"host": self.host,
                    "open_traces": len(self._by_trace),
                    "published": self.published,
                    "evicted_traces": self.evicted_traces,
                    "spans_dropped": self.tracer.dropped}


class _TraceState:
    """Router-side bookkeeping for one in-flight traced request.

    Attempt/root spans are BUFFERED here (plain dicts, no tracer
    traffic) and only materialize into the router sink when the tail
    sampler keeps the trace — a dropped trace costs zero router-side
    span records, which is what keeps tracing overhead inside its
    budget.  A hedge loser closing after the keep decision
    materializes late (``kept`` flag)."""

    __slots__ = ("ctx", "kind", "t0", "lock", "next_span_id",
                 "attempts", "lost_attempts", "retried", "hedged",
                 "deadline_s", "queue_window", "handoffs", "kept")

    def __init__(self, ctx: TraceContext, kind: str, t0: float,
                 deadline_s: Optional[float]):
        self.ctx = ctx
        self.kind = kind
        self.t0 = t0
        self.lock = threading.Lock()
        self.next_span_id = 1      # 1 = the root request span
        self.attempts: List[dict] = []
        self.lost_attempts: set = set()
        self.retried = False
        self.hedged = False
        self.deadline_s = deadline_s
        self.queue_window: Optional[tuple] = None
        self.handoffs: List[dict] = []
        self.kept = False

    def alloc_span_id(self) -> int:
        with self.lock:
            self.next_span_id += 1
            return self.next_span_id


class RequestTracer:
    """The router side: context minting, attempt spans, tail sampling,
    and stitching.  One per :class:`~.router.FleetRouter`."""

    def __init__(self, transport=None,
                 incarnation_of: Optional[Callable[[], int]] = None,
                 sampler: Optional[TailSampler] = None,
                 host: str = "router", keep_max: int = 256,
                 clock: Callable[[], float] = time.monotonic):
        self.transport = transport
        self._clock = clock
        self.sampler = sampler or TailSampler()
        self.sink = ReplicaTraceSink(host, transport=None,
                                     incarnation_of=incarnation_of,
                                     clock=clock)
        self._lock = threading.Lock()
        self._kept: "OrderedDict[str, dict]" = OrderedDict()
        self.keep_max = int(keep_max)
        self.minted = 0
        #: called with the trace_id of every KEPT trace (the fleet
        #: wires it to each replica sink's ``publish_trace`` — the
        #: tail decision pulls fragments onto the transport)
        self.on_keep: Optional[Callable[[str], None]] = None

    # ------------------------------------------------------------ lifecycle
    def begin(self, kind: str,
              deadline_s: Optional[float]) -> _TraceState:
        ctx = TraceContext.mint(deadline_s=deadline_s)
        with self._lock:
            self.minted += 1
        return _TraceState(ctx, kind, self._clock(), deadline_s)

    def router_queue(self, state: _TraceState, t_start: float,
                     t_end: float) -> None:
        """The router-pool wait between enqueue and the drive thread
        picking the request up (buffered; materialized on keep)."""
        state.queue_window = (t_start, max(0.0, t_end - t_start))

    def handoff(self, state: _TraceState, t_start: float,
                duration: float, **args) -> None:
        """The router-side prefill→decode handoff hop (buffered;
        materialized on keep)."""
        with state.lock:
            state.handoffs.append({"t_start": t_start,
                                   "duration": duration, "args": args})

    def attempt_begin(self, state: _TraceState, replica: str,
                      kind: str, remaining_s: Optional[float],
                      hedge: bool = False) -> TraceContext:
        """Fork the context for one dispatch attempt; the wire form of
        the returned child is what rides ``submit(..., trace=...)``."""
        span_id = state.alloc_span_id()
        with state.lock:
            idx = len(state.attempts)
            state.attempts.append({
                "span_id": span_id, "replica": replica, "kind": kind,
                "t_start": self._clock(), "hedge": bool(hedge),
                "remaining_s": remaining_s, "index": idx,
            })
            if hedge:
                state.hedged = True
            elif idx > 0:
                state.retried = True
        phase = kind if kind in ("prefill", "decode") else None
        return state.ctx.child(span_id, remaining_s=remaining_s,
                               attempt=idx, phase=phase)

    def attempt_end(self, state: _TraceState, ctx: TraceContext,
                    status: Optional[str],
                    hedge_outcome: Optional[str] = None) -> None:
        """Close one attempt — including a hedge loser at DISCARD time
        (``hedge_outcome="lost"``), so duplicate duty is labeled
        instead of leaking as an orphan.  Buffered until the trace is
        kept; a loser closing after the keep decision materializes
        immediately."""
        with state.lock:
            att = state.attempts[ctx.attempt]
            if att.get("closed"):
                return
            att["closed"] = True
            att["t_end"] = self._clock()
            att["status"] = status
            if hedge_outcome is not None:
                att["hedge_outcome"] = hedge_outcome
            if hedge_outcome == "lost":
                state.lost_attempts.add(ctx.attempt)
            late = state.kept
        if late:
            self._record_attempt(state, att)

    def _record_attempt(self, state: _TraceState, att: dict) -> None:
        args = {"replica": att["replica"], "kind": att["kind"],
                "status": att.get("status"),
                "span_id": att["span_id"],
                "remaining_budget_s": att["remaining_s"]}
        if att["hedge"]:
            args["hedge"] = True
        if att.get("hedge_outcome") is not None:
            args["hedge_outcome"] = att["hedge_outcome"]
        # attempt spans parent the ROOT span (id 1)
        self.sink.record_raw(
            state.ctx.trace_id, 1, att["index"],
            f"attempt:{att['replica']}", "attempt", att["t_start"],
            att.get("t_end", att["t_start"]) - att["t_start"], **args)

    def mark_lost(self, state: _TraceState, ctx: TraceContext) -> None:
        """Record — at winner time — that this attempt's response will
        be discarded, so the stitcher labels its replica spans even
        before the loser's late response arrives."""
        with state.lock:
            state.lost_attempts.add(ctx.attempt)

    def finish(self, state: _TraceState, status: str, ok: bool,
               latency_s: float,
               p99_s: Optional[float]) -> Optional[str]:
        """Run the tail sampler; on keep, materialize the buffered
        root/queue/attempt spans into the router sink and fire
        ``on_keep``.  Returns the keep reason (None = dropped: the
        request's trace state cost zero tracer traffic and is simply
        released)."""
        reason = self.sampler.keep(
            ok=ok, retried=state.retried, hedged=state.hedged,
            latency_s=latency_s, p99_s=p99_s)
        if reason is None:
            return None
        with state.lock:
            state.kept = True
            closed = [a for a in state.attempts if a.get("closed")]
            handoffs = list(state.handoffs)
        # multi-tenant attribution rides the root span + kept summary:
        # one kept trace names the tenant/model/version it served
        tenancy = {}
        if state.ctx.tenant is not None:
            tenancy = {"tenant": state.ctx.tenant,
                       "model": state.ctx.model,
                       "model_version": state.ctx.model_version}
        self.sink.record(state.ctx, f"request:{state.kind}", "request",
                         state.t0, latency_s, kind=state.kind,
                         status=status, span_id=1,
                         deadline_s=state.deadline_s,
                         retried=state.retried, hedged=state.hedged,
                         keep_reason=reason,
                         lost_attempts=sorted(state.lost_attempts),
                         **tenancy)
        if state.queue_window is not None:
            self.sink.record(state.ctx, "router_queue", "queue",
                             state.queue_window[0],
                             state.queue_window[1])
        for att in closed:
            self._record_attempt(state, att)
        for h in handoffs:
            self.sink.record(state.ctx, "handoff", "handoff",
                             h["t_start"], h["duration"], **h["args"])
        with self._lock:
            self._kept[state.ctx.trace_id] = {
                "trace_id": state.ctx.trace_id, "kind": state.kind,
                "status": status, "latency_s": latency_s,
                "reason": reason, "t0": state.t0,
                "retried": state.retried, "hedged": state.hedged,
                "lost_attempts": sorted(state.lost_attempts),
                **tenancy,
            }
            while len(self._kept) > self.keep_max:
                self._kept.popitem(last=False)
        if self.on_keep is not None:
            try:
                self.on_keep(state.ctx.trace_id)
            except Exception:
                log.warning("trace on_keep hook failed",
                            exc_info=True)
        return reason

    # ------------------------------------------------------------ stitching
    def kept_traces(self) -> List[dict]:
        with self._lock:
            return [dict(v) for v in self._kept.values()]

    def _kv_fragments(self, trace_id: str) -> List[dict]:
        """Every host's published fragment for one trace, across
        incarnations (a mid-trace eject bumps the incarnation between
        two replicas' publishes — both halves still stitch)."""
        if self.transport is None:
            return []
        needle = f"/{trace_id}/"
        out = []
        for key in self.transport.keys(TRACE_KV_PREFIX):
            if needle not in key:
                continue
            raw = self.transport.get(key)
            if raw is None:
                continue
            try:
                out.append(json.loads(raw))
            except ValueError:
                continue
        return out

    def stitch(self, trace_id: str,
               skew: Optional[Dict[str, dict]] = None,
               flush_sinks: Optional[List[ReplicaTraceSink]] = None
               ) -> Optional[dict]:
        """One cross-replica Perfetto (Chrome-trace) timeline for a
        kept trace: the router fragment plus every replica's KV
        fragment, clock-aligned onto the router's monotonic timeline,
        hedge-loser attempts labeled.  ``skew`` (host → ``{"skew":
        ratio}``, e.g. the fleet/cluster snapshot's per-host step-time
        skew) rides onto each host's process metadata."""
        for s in flush_sinks or ():
            # lazily-published sinks may still hold this trace's
            # fragment: pull it (coalesced no-op when already queued)
            s.publish_trace(trace_id)
            s.flush()
        router_frag = self.sink.fragment(trace_id)
        frags = self._kv_fragments(trace_id)
        if router_frag is not None:
            frags.insert(0, router_frag)
        if not frags:
            return None
        with self._lock:
            kept = self._kept.get(trace_id)
        lost = set((kept or {}).get("lost_attempts") or ())
        return stitch_fragments(frags, reference_host=self.sink.host,
                                lost_attempts=lost, skew=skew,
                                summary=kept)

    def snapshot(self) -> dict:
        return {
            "minted": self.minted,
            "sampler": self.sampler.snapshot(),
            "kept_traces": len(self._kept),
            "router_sink": self.sink.snapshot(),
        }

    def close(self):
        self.sink.close()


# ---------------------------------------------------------------------------
# stitching + analysis (pure functions — tools/trace_report.py reuses)
# ---------------------------------------------------------------------------

def stitch_fragments(fragments: List[dict],
                     reference_host: str = "router",
                     lost_attempts: Optional[set] = None,
                     skew: Optional[Dict[str, dict]] = None,
                     summary: Optional[dict] = None) -> dict:
    """Fold per-host fragments into one Chrome-trace dict: one pid per
    host (process_name metadata), timestamps mapped onto the reference
    host's monotonic clock via each fragment's (mono, wall) anchor
    pair, lost-hedge attempts' spans labeled ``hedge_outcome=lost``."""
    lost = lost_attempts or set()
    ref = next((f for f in fragments
                if f.get("host") == reference_host), fragments[0])
    ref_anchor = ref.get("clock_anchor") or {}
    ref_delta = (ref_anchor.get("wall", 0.0)
                 - ref_anchor.get("mono", 0.0))
    events = []
    hosts = []
    for frag in fragments:
        host = str(frag.get("host", "?"))
        if host not in hosts:
            hosts.append(host)
        pid = hosts.index(host) + 1
        anchor = frag.get("clock_anchor") or {}
        # host mono -> reference mono: synchronized wall clocks anchor
        # the two monotonic timelines (offset ~0 in-process; the real
        # cross-host correction in production)
        offset = ((anchor.get("wall", 0.0) - anchor.get("mono", 0.0))
                  - ref_delta) if anchor and ref_anchor else 0.0
        host_skew = (skew or {}).get(host) or {}
        meta_args = {"host": host}
        if host_skew:
            meta_args["step_time_skew"] = host_skew.get("skew")
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": host, **meta_args}})
        for sp in frag.get("spans", ()):
            args = dict(sp.get("args") or {})
            if args.get("attempt") in lost \
                    and args.get("hedge_outcome") is None \
                    and sp.get("cat") != "request":
                args["hedge_outcome"] = "lost"
            events.append({
                "name": sp["name"], "cat": sp["cat"], "ph": "X",
                "ts": (sp["start"] + offset) * 1e6,
                "dur": sp["dur"] * 1e6,
                "pid": pid, "tid": sp.get("tid", 0),
                "args": args,
            })
    out = {"traceEvents": events, "displayTimeUnit": "ms",
           "hosts": hosts}
    if summary:
        out["summary"] = dict(summary)
    return out


def _span_events(trace: dict, include_lost: bool = False) -> List[dict]:
    return [e for e in trace.get("traceEvents", ())
            if e.get("ph") == "X"
            and (include_lost
                 or (e.get("args") or {}).get("hedge_outcome")
                 != "lost")]


def _root_event(trace: dict) -> Optional[dict]:
    roots = [e for e in _span_events(trace, include_lost=True)
             if e.get("cat") == "request"]
    return roots[0] if roots else None


def _union_seconds(intervals: List[Tuple[float, float]]) -> float:
    total = 0.0
    end = None
    for a, b in sorted(intervals):
        if end is None or a > end:
            total += b - a
            end = b
        elif b > end:
            total += b - end
            end = b
    return total


def trace_coverage(trace: dict) -> Optional[float]:
    """Fraction of the root request's wall clock covered by the union
    of its child spans.  Hedge losers MAY contribute to the union — a
    union cannot double-count, and the pre-hedge wait is legitimately
    covered by the (discarded) primary attempt — while the phase SUMS
    in :func:`trace_attribution` exclude them.  None without a root
    span."""
    root = _root_event(trace)
    if root is None or root.get("dur", 0) <= 0:
        return None
    r0, r1 = root["ts"], root["ts"] + root["dur"]
    ivs = []
    for e in _span_events(trace, include_lost=True):
        if e is root or e.get("cat") in ("request", "swap_window"):
            continue
        a = max(r0, e["ts"])
        b = min(r1, e["ts"] + e.get("dur", 0))
        if b > a:
            ivs.append((a, b))
    return min(1.0, _union_seconds(ivs) / (r1 - r0))


def trace_attribution(trace: dict) -> Optional[dict]:
    """Where one request's wall clock went: seconds per phase (queue /
    batch / compute / kv / swap / transport), per-replica compute
    seconds, and the critical-path phase (argmax).  ``transport`` is
    the unattributed remainder — the cross-process hops no single
    host's spans can see."""
    root = _root_event(trace)
    if root is None or root.get("dur", 0) <= 0:
        return None
    r0, r1 = root["ts"], root["ts"] + root["dur"]
    wall = (r1 - r0) / 1e6
    phases: Dict[str, float] = {}
    by_replica: Dict[str, float] = {}
    covered = []
    for e in _span_events(trace):
        cat = e.get("cat")
        if e is root or cat in ("request", "attempt"):
            continue
        phase = PHASE_OF_CATEGORY.get(cat)
        if phase is None:
            continue
        a = max(r0, e["ts"])
        b = min(r1, e["ts"] + e.get("dur", 0))
        if b <= a:
            continue
        secs = (b - a) / 1e6
        phases[phase] = phases.get(phase, 0.0) + secs
        if phase != "swap":
            covered.append((a, b))
        if phase == "compute":
            host = (e.get("args") or {}).get("host", "?")
            by_replica[host] = by_replica.get(host, 0.0) + secs
    phases["transport"] = max(
        0.0, wall - _union_seconds(covered) / 1e6)
    ranked = sorted(
        ((s, p) for p, s in phases.items() if p != "swap"),
        reverse=True)
    critical = ranked[0][1] if ranked else None
    busiest = max(by_replica.items(), key=lambda kv: kv[1])[0] \
        if by_replica else None
    root_args = root.get("args") or {}
    return {
        "wall_s": wall,
        "tenant": root_args.get("tenant"),
        "model": root_args.get("model"),
        "phases": {p: round(s, 6) for p, s in sorted(phases.items())},
        "compute_by_replica": {h: round(s, 6)
                               for h, s in sorted(by_replica.items())},
        "critical_phase": critical,
        "critical_replica": busiest,
        "coverage": trace_coverage(trace),
    }
