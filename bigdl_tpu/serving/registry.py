"""Multi-tenant model registry + per-tenant admission control.

Two small, lock-disciplined objects turn the single-model fleet into a
multi-tenant one without touching the per-replica serving machinery:

:class:`ModelRegistry` is the fleet's name service.  Replicas already
advertise health snapshots over the fleet KV; with multi-tenancy each
snapshot also carries the (model, version) pair its server holds, and
the registry records which models are *supposed* to exist.  The router
consults ``lookup(model)`` at admission (a miss is a typed
``NOT_FOUND`` — no queue slot, no retry burn) and re-checks it every
attempt, so an entry that vanishes mid-flight
(:func:`resilience.faults.unregister_model_mid_flight`) converts the
already-queued requests into typed NOT_FOUND instead of letting them
spin against replicas that no longer serve the model.

:class:`AdmissionController` is the noisy-neighbor wall.  Each tenant
gets a weighted share of the router's inflight capacity; admission is
a single atomic check under one lock:

1. tenant over its own budget  → shed ``"tenant_quota"`` — ONLY the
   over-quota tenant sheds (typed OVERLOADED); every under-quota
   tenant keeps its full budget untouched.
2. fleet-wide capacity exhausted → shed ``"global"`` — the only case
   where an under-budget tenant can be refused.
3. otherwise → admitted, one slot charged to the tenant.

Weighted fair shedding *before* global shedding is the ordering the
multi-tenant chaos tests pin: a tenant-A flood
(:func:`resilience.faults.tenant_flood` charges phantom inflight units
against A's quota at every decision) drives A into case 1 while B
rides entirely in case 3.  Budgets are derived once from the quota
weights (``floor(capacity * w_t / Σw)``, min 1), so Σ budgets ≤
capacity and a tenant inside its budget can only be refused by genuine
fleet-wide exhaustion.

Per-tenant deadline budgets ride the same object: ``deadline_for``
clamps a request's deadline to the tenant's ceiling, so one tenant
cannot monopolize replicas with arbitrarily long deadlines.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..resilience import faults as _faults
from ..telemetry.events import record_change as _record_change

__all__ = ["ModelRegistry", "AdmissionController"]


class ModelRegistry:
    """Thread-safe (model -> version) table the router admits against.

    The registry is intentionally *descriptive*, not authoritative:
    which replicas actually hold a model comes from their live health
    snapshots (:meth:`advertisers`); the registry only answers "is this
    model supposed to exist, and at which version?" — the admission
    check that makes an unknown model a typed NOT_FOUND instead of a
    retry storm against replicas that will never serve it.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._models: Dict[str, str] = {}

    def register(self, model: str, version: str = "v1") -> str:
        """Register (or re-version) ``model``; returns the version."""
        with self._lock:
            prior = self._models.get(str(model))
            self._models[str(model)] = str(version)
        if prior != str(version):
            _record_change("model_registered", f"version={version}",
                           source="serving.registry", model=model)
        return str(version)

    def unregister(self, model: str) -> bool:
        """Drop ``model``; True when it was registered."""
        with self._lock:
            dropped = self._models.pop(str(model), None) is not None
        if dropped:
            _record_change("model_unregistered",
                           source="serving.registry", model=model)
        return dropped

    def lookup(self, model: str) -> Optional[str]:
        """The registered version of ``model``, or None.

        Consults the armed registry faults first: an
        ``unregister_model_mid_flight`` entry fires here, dropping the
        model so this very lookup (and every later one) misses — the
        deterministic injection point for the vanishing-entry chaos
        case."""
        model = str(model)
        if _faults.check_registry_fault(model):
            self.unregister(model)
        with self._lock:
            return self._models.get(model)

    def has(self, model: str) -> bool:
        return self.lookup(model) is not None

    def models(self) -> Dict[str, str]:
        """Snapshot copy of the (model -> version) table."""
        with self._lock:
            return dict(self._models)

    @staticmethod
    def advertisers(model: str, health: Dict[str, dict]) -> List[str]:
        """Replica ids whose health snapshot advertises ``model``.

        A replica with no ``model`` key (single-model fleets predating
        the registry) advertises nothing here — multi-model routing
        only dispatches over explicit advertisers."""
        model = str(model)
        return [rid for rid, h in health.items()
                if (h or {}).get("model") == model]


class AdmissionController:
    """Per-tenant weighted max-inflight admission with fair shedding.

    ``quotas`` maps tenant -> weight; each tenant's guaranteed budget
    is ``max(1, floor(capacity * weight / Σweights))`` slots.  Tenants
    absent from ``quotas`` get ``default_slots`` (they exist — a new
    tenant must not be an unbounded hole — but carry no reserved
    share).  ``try_admit``/``release`` are atomic under one lock, so
    concurrent admits across tenants can never overshoot either a
    tenant budget or the global capacity, and releases can never drive
    a count negative (the quota-accounting invariants the concurrency
    hammer test pins).
    """

    #: admission-decision vocabulary (the ``decision`` label of
    #: ``bigdl_tenant_admission_total``)
    ADMITTED = "admitted"
    TENANT_QUOTA = "tenant_quota"
    GLOBAL = "global"

    def __init__(self, capacity: int,
                 quotas: Optional[Dict[str, float]] = None,
                 default_slots: int = 1,
                 deadline_budgets: Optional[Dict[str, float]] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {}
        self._total = 0
        self._default_slots = max(1, int(default_slots))
        self._deadline_budgets = dict(deadline_budgets or {})
        quotas = dict(quotas or {})
        total_w = sum(max(0.0, float(w)) for w in quotas.values())
        self._budgets: Dict[str, int] = {}
        for tenant, w in quotas.items():
            if total_w <= 0:
                share = self._default_slots
            else:
                share = int(self.capacity * max(0.0, float(w)) / total_w)
            self._budgets[str(tenant)] = max(1, share)

    def budget(self, tenant: str) -> int:
        """The tenant's guaranteed inflight budget (slots)."""
        return self._budgets.get(str(tenant), self._default_slots)

    def try_admit(self, tenant: str) -> Tuple[bool, str]:
        """One atomic admission decision for ``tenant``.

        Returns ``(True, "admitted")`` with one slot charged, or
        ``(False, reason)`` where ``reason`` is ``"tenant_quota"``
        (tenant over its own budget — weighted fair shed) or
        ``"global"`` (fleet-wide capacity exhausted).  An armed
        :func:`resilience.faults.tenant_flood` adds phantom inflight
        units to the tenant's count before the check."""
        tenant = str(tenant)
        phantom = _faults.check_tenant_flood(tenant)
        with self._lock:
            held = self._inflight.get(tenant, 0)
            if held + phantom >= self.budget(tenant):
                return False, self.TENANT_QUOTA
            if self._total >= self.capacity:
                return False, self.GLOBAL
            self._inflight[tenant] = held + 1
            self._total += 1
            return True, self.ADMITTED

    def release(self, tenant: str):
        """Return ``tenant``'s slot.  Over-release is clamped (never a
        negative count) — the router releases exactly once per admitted
        request via the future's single-fire done callback, but a
        clamped floor keeps a buggy caller from corrupting every later
        admission decision."""
        tenant = str(tenant)
        with self._lock:
            held = self._inflight.get(tenant, 0)
            if held > 0:
                self._inflight[tenant] = held - 1
                self._total -= 1

    def inflight(self, tenant: Optional[str] = None) -> int:
        with self._lock:
            if tenant is None:
                return self._total
            return self._inflight.get(str(tenant), 0)

    def deadline_for(self, tenant: str,
                     deadline_s: Optional[float]) -> Optional[float]:
        """Clamp a requested deadline to the tenant's budget (None
        passes an unbudgeted tenant's request through unchanged; a
        budgeted tenant with no requested deadline gets its ceiling)."""
        cap = self._deadline_budgets.get(str(tenant))
        if cap is None:
            return deadline_s
        if deadline_s is None:
            return float(cap)
        return min(float(deadline_s), float(cap))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "total_inflight": self._total,
                "inflight": dict(self._inflight),
                "budgets": dict(self._budgets),
            }
