"""Telemetry-driven autoscaling: replica counts that track load.

A fixed fleet sized for the peak wastes chips off-peak and sheds at
the peak it was mis-sized for.  The :class:`Autoscaler` is a control
loop over the signals the router already aggregates — per-pool p99,
shed rate, published queue depth, and KV-pool occupancy, all read from
the health snapshots replicas publish every heartbeat — scaling each
role pool (``prefill`` / ``decode`` / ``both``) **independently**:
prefill is compute-bound and decode HBM-bound (the PR 6 roofline
split), so their load signals, and therefore their replica counts,
move separately.

Control discipline (what keeps it from flapping):

* **Hysteresis** — a breach (or idle) signal must sustain for
  ``sustain`` (``idle_sustain``) consecutive evaluations before any
  action; one noisy sample scales nothing.
* **Cooldown** — after any action the pool holds for ``cooldown_s``;
  a new replica needs time to warm (the persisted compile cache —
  ``bigdl.serving.compileCache`` — shrinks exactly this window) before
  its effect is measurable.
* **Bounds** — ``min_replicas``/``max_replicas`` clamp every pool.
* **Drain-before-retire** — scale-down rides the graceful-preemption
  path (:meth:`~.fleet.ServingFleet.remove_replica` with
  ``drain=True``): admission stops, everything admitted finishes
  (paged decodes resolve and release their pages), then the replica
  leaves membership.

Every decision is a structured event (kept in ``decisions``, logged)
plus a ``bigdl_autoscale_decisions_total{pool,direction}`` counter in
the router registry, so the scaling history is scrape-visible next to
the request metrics it acted on.

Since the online health engine (``telemetry/slo.py``) the breach
signal is, by default, an **SLO verdict**: the per-pool signals feed a
:class:`~bigdl_tpu.telemetry.timeseries.MetricRecorder`, each raw
watermark is a declarative rule in a
:class:`~bigdl_tpu.telemetry.slo.SloEngine`, and a breach is a FIRING
alert — same thresholds, same hysteresis/cooldown/bounds semantics
(decision-for-decision identical, tested), but every breach and
recovery is now a structured ``bigdl_alerts_total`` transition an
operator can scrape and page on.  ``signal_source="raw"`` keeps the
pre-SLO inline-comparison path as the fallback.
"""
from __future__ import annotations

import logging
import math
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..telemetry.events import record_change as _record_change
from .pools import serves_phase, split_pool

log = logging.getLogger("bigdl_tpu")

__all__ = ["AutoscalePolicy", "Autoscaler"]


@dataclass
class AutoscalePolicy:
    """Per-pool scaling policy — thresholds, hysteresis, bounds."""
    min_replicas: int = 1
    max_replicas: int = 8
    #: scale-up watermarks: breach ANY of these...
    p99_high_s: float = 0.5
    shed_high: float = 0.02        # shed fraction of the eval window
    queue_high: int = 32           # summed published queue depth
    kv_occupancy_high: float = 0.90
    #: ...for this many consecutive evaluations
    sustain: int = 2
    #: scale-down watermarks: ALL of these, sustained idle_sustain
    p99_idle_s: float = 0.050
    queue_idle: int = 1
    kv_occupancy_idle: float = 0.50
    idle_sustain: int = 3
    #: traffic-activity gate: when set, p99/queue breaches only count
    #: while the pool saw MORE than this many requests since the last
    #: evaluation (the published p99 is a windowed quantile — over no
    #: fresh traffic it is stale history, not an actionable signal),
    #: and a quiet pool (≤ this delta) reads as idle regardless of
    #: that stale p99.  None disables the gate (breaches always
    #: actionable; idleness judged by p99_idle_s alone).
    idle_requests_delta: Optional[int] = None
    #: no second action within the cooldown
    cooldown_s: float = 10.0
    #: drain budget for scale-down
    drain_timeout_s: float = 10.0


@dataclass
class _PoolState:
    breach_streak: int = 0
    idle_streak: int = 0
    last_action_t: float = -math.inf
    last_direction: Optional[str] = None
    spawned: int = 0
    last_shed: Dict[str, int] = field(default_factory=dict)
    last_total: Dict[str, int] = field(default_factory=dict)


class Autoscaler:
    """Scales a :class:`~.fleet.ServingFleet`'s role pools from the
    registry signals the router aggregates.

    Parameters
    ----------
    fleet : the running ServingFleet (its pump loop keeps the health
        snapshots the signals are read from fresh).
    replica_factory : ``(replica_id, role) -> InferenceServer`` —
        builds an UNSTARTED server for a scale-up;
        :meth:`~.fleet.ServingFleet.add_replica` starts it.
    pools : pools to manage.  A pool spec is a bare role
        (``"decode"``) or a tenant-scoped ``"model:role"``
        (:func:`~.pools.split_pool`), so a multi-tenant fleet sizes
        each (model, phase) pool independently.  Defaults to the
        distinct (model, role) combinations the fleet's replicas
        advertise — a homogeneous single-model fleet scales its one
        ``both`` pool exactly as before.
    policy / policies : one shared :class:`AutoscalePolicy` or a
        per-pool dict.
    """

    def __init__(self, fleet, replica_factory: Callable[[str, str],
                                                        object],
                 policy: Optional[AutoscalePolicy] = None,
                 policies: Optional[Dict[str, AutoscalePolicy]] = None,
                 pools: Optional[Sequence[str]] = None,
                 signal_source: str = "slo",
                 clock: Callable[[], float] = time.monotonic):
        if signal_source not in ("raw", "slo"):
            raise ValueError(f"signal_source {signal_source!r} not "
                             f"raw|slo")
        self.fleet = fleet
        self.replica_factory = replica_factory
        if pools is None:
            combos = set()
            for s in fleet.servers.values():
                role = getattr(s, "role", "both")
                m = getattr(s, "model_name", None)
                combos.add(role if m is None else f"{m}:{role}")
            pools = tuple(sorted(combos))
        self.pools = tuple(pools)
        base = policy or AutoscalePolicy()
        self.policies = {p: (policies or {}).get(p, base)
                         for p in self.pools}
        self._clock = clock
        self._state = {p: _PoolState() for p in self.pools}
        #: structured decision log (every entry also hits the counter
        #: + the process log)
        self.decisions: List[dict] = []
        self._decisions_total = \
            fleet.router.metrics.registry.counter(
                "bigdl_autoscale_decisions_total",
                "autoscaler actions per pool and direction",
                labels=("pool", "direction"))
        #: "slo" (the default) evaluates the breach predicates as SLO
        #: rules over a MetricRecorder — identical thresholds/
        #: hysteresis/cooldown semantics, but every breach/recovery is
        #: a structured Alert + ``bigdl_alerts_total`` transition, and
        #: the per-pool signal history is queryable.  "raw" is the
        #:  pre-SLO inline-comparison path, kept as the fallback.
        self.signal_source = signal_source
        self.slo_engine = None
        self._slo_recorder = None
        self._pool_rules: Dict[str, Tuple[str, ...]] = {}
        if signal_source == "slo":
            self._build_slo_engine()

    # ------------------------------------------------------ slo plumbing
    def _build_slo_engine(self):
        from ..telemetry import metric_names as M
        from ..telemetry.slo import SloEngine, SloRule
        from ..telemetry.timeseries import MetricRecorder

        self._slo_recorder = MetricRecorder(clock=self._clock)
        self.slo_engine = SloEngine(
            self._slo_recorder,
            registry=self.fleet.router.metrics.registry,
            clock=self._clock)
        for pool in self.pools:
            policy = self.policies[pool]
            L = {"pool": pool}
            # one rule per raw breach predicate, SAME thresholds, with
            # for/resolve_intervals=1: the autoscaler's own
            # breach_streak/sustain keeps hysteresis semantics
            # IDENTICAL to the raw path (one firing == one raw
            # breach).  staleness_s=0.0 means ONLY a signal fed this
            # very round yields a verdict — the recorder's staleness
            # gate IS the traffic-activity gate (an inactive pool's
            # p99/queue are simply not refreshed, so their rules
            # render no verdict and the breach list excludes them)
            rules = [
                SloRule(name=f"autoscale/{pool}/p99",
                        family=M.AUTOSCALE_POOL_P99_SECONDS, labels=L,
                        kind="threshold", reduce="last", op=">=",
                        threshold=policy.p99_high_s,
                        window_s=3600.0, staleness_s=0.0,
                        description=f"{pool} p99 >= "
                                    f"{policy.p99_high_s}s"),
                SloRule(name=f"autoscale/{pool}/shed",
                        family=M.AUTOSCALE_POOL_SHED_RATE, labels=L,
                        kind="threshold", reduce="last", op=">=",
                        threshold=policy.shed_high,
                        window_s=3600.0, staleness_s=0.0,
                        description=f"{pool} shed rate >= "
                                    f"{policy.shed_high}"),
                SloRule(name=f"autoscale/{pool}/queue",
                        family=M.AUTOSCALE_POOL_QUEUE_DEPTH, labels=L,
                        kind="threshold", reduce="last", op=">=",
                        threshold=policy.queue_high,
                        window_s=3600.0, staleness_s=0.0,
                        description=f"{pool} queue >= "
                                    f"{policy.queue_high}"),
                SloRule(name=f"autoscale/{pool}/kv",
                        family=M.AUTOSCALE_POOL_KV_OCCUPANCY,
                        labels=L, kind="threshold", reduce="last",
                        op=">=",
                        threshold=policy.kv_occupancy_high,
                        window_s=3600.0, staleness_s=0.0,
                        description=f"{pool} kv occupancy >= "
                                    f"{policy.kv_occupancy_high}"),
            ]
            for rule in rules:
                self.slo_engine.add_rule(rule)
            self._pool_rules[pool] = tuple(r.name for r in rules)

    def _slo_feed(self, pool: str, sig: dict, active: bool,
                  now: float):
        """Feed this round's pool signals into the recorder.  The
        traffic-activity gate becomes the recorder's STALENESS gate:
        over no fresh traffic the windowed p99/queue are stale
        history, so they are simply not refreshed and their rules
        render no verdict (never a breach).  Shed/KV are refreshed
        unconditionally — a quiet pool's shed rate is honestly 0 and
        occupancy is held state, not history."""
        from ..telemetry import metric_names as M

        r = self._slo_recorder
        L = {"pool": pool}
        if active:
            r.observe(M.AUTOSCALE_POOL_P99_SECONDS, sig["p99_s"],
                      labels=L, now=now)
            r.observe(M.AUTOSCALE_POOL_QUEUE_DEPTH,
                      sig["queue_depth"], labels=L, now=now)
        # the raw predicate is (shed_rate >= high AND shed_delta > 0):
        # a window with no shed events reads 0.0, never a breach
        r.observe(M.AUTOSCALE_POOL_SHED_RATE,
                  sig["shed_rate"] if sig["shed_delta"] > 0 else 0.0,
                  labels=L, now=now)
        r.observe(M.AUTOSCALE_POOL_KV_OCCUPANCY, sig["kv_occupancy"],
                  labels=L, now=now)
        # cumulative pool counters: the error-budget burn-rate view
        # (default_serving_rules) and any scraper ride these
        st = self._state[pool]
        r.observe(M.AUTOSCALE_POOL_SHED_TOTAL,
                  float(sum(st.last_shed.values())), labels=L,
                  kind="counter", now=now)
        r.observe(M.AUTOSCALE_POOL_REQUESTS_TOTAL,
                  float(sum(st.last_total.values())), labels=L,
                  kind="counter", now=now)

    def _slo_breaches(self, pool: str, now: float) -> List[str]:
        """The pool's firing rules WITH a verdict this round, as
        breach descriptions — the SLO verdicts the control logic
        consumes in place of the raw comparisons.  A rule frozen by
        the staleness gate (inactive pool: p99/queue not refreshed)
        contributes nothing, exactly the raw activity gate."""
        out = []
        for a in self.slo_engine.firing(self._pool_rules[pool]):
            if a.get("last_verdict_at") is None \
                    or a["last_verdict_at"] < now:
                continue
            if isinstance(a["value"], (int, float)):
                out.append(f"{a['rule']}: {a['description']} "
                           f"(value={a['value']:.4g})")
            else:
                out.append(f"{a['rule']}: {a['description']}")
        return out

    # ------------------------------------------------------------ signals
    def _pool_health(self, pool: str) -> Dict[str, dict]:
        """Health snapshots of the replicas serving ``pool`` — the
        SAME view the router routes on.  A replica with no snapshot
        yet contributes nothing (it is not routable either)."""
        model, role = split_pool(pool)
        out = {}
        for rid in self.fleet.servers:
            h = self.fleet.router.health_of(rid)
            if h is not None and serves_phase(h.get("role"), role) \
                    and (model is None or h.get("model") == model):
                out[rid] = h
        return out

    def pool_signals(self, pool: str) -> dict:
        """Aggregate one pool's control signals from published health:
        worst p99, shed count/rate over the window since the last
        evaluation, summed queue depth, worst KV occupancy."""
        st = self._state[pool]
        health = self._pool_health(pool)
        p99 = max((h.get("p99_s") or 0.0 for h in health.values()),
                  default=0.0)
        queue = sum(int(h.get("queue_depth", 0))
                    for h in health.values())
        kv_occ = max((h.get("kv_occupancy") or 0.0
                      for h in health.values()), default=0.0)
        shed_d = total_d = 0
        for rid, h in health.items():
            shed_d += max(0, int(h.get("shed_total", 0))
                          - st.last_shed.get(rid, 0))
            total_d += max(0, int(h.get("requests_total", 0))
                           - st.last_total.get(rid, 0))
            st.last_shed[rid] = int(h.get("shed_total", 0))
            st.last_total[rid] = int(h.get("requests_total", 0))
        return {
            "pool": pool,
            "replicas": self.pool_size(pool),
            "p99_s": p99,
            "queue_depth": queue,
            "kv_occupancy": kv_occ,
            "shed_delta": shed_d,
            "requests_delta": total_d,
            "shed_rate": (shed_d / total_d) if total_d else 0.0,
        }

    def pool_size(self, pool: str) -> int:
        """Replicas whose EXACT role (and model, for a tenant-scoped
        pool) matches ``pool`` — what scaling actuates (a ``both``
        member is never retired by a phase pool's scale-down, and one
        model's pool never retires another model's replica)."""
        model, role = split_pool(pool)
        return sum(
            1 for s in self.fleet.servers.values()
            if getattr(s, "role", "both") == role
            and (model is None
                 or getattr(s, "model_name", None) == model))

    def replica_counts(self) -> Dict[str, int]:
        """{pool: replica count} — one timeline sample for the bench."""
        return {p: self.pool_size(p) for p in self.pools}

    # ------------------------------------------------------------ control
    def _record(self, pool: str, direction: str, replica: str,
                reason: str, signals: dict):
        event = {"at": self._clock(), "pool": pool,
                 "direction": direction, "replica": replica,
                 "reason": reason, "signals": signals}
        self.decisions.append(event)
        self._decisions_total.labels(pool=pool,
                                     direction=direction).inc()
        _record_change(f"autoscale_{direction}", str(reason),
                       source="serving.autoscale", pool=pool,
                       replica=replica)
        log.info("autoscale: %s %s (%s) — %s", direction, replica,
                 pool, reason)

    def _scale_up(self, pool: str, reason: str, signals: dict):
        st = self._state[pool]
        st.spawned += 1
        # "model:role" pools keep the fleet's dash-separated replica
        # naming ("alpha:decode" spawns "alpha-decode-as1")
        rid = f"{pool.replace(':', '-')}-as{st.spawned}"
        server = self.replica_factory(rid, pool)
        self.fleet.add_replica(rid, server)
        st.last_action_t = self._clock()
        st.last_direction = "up"
        st.breach_streak = st.idle_streak = 0
        self._record(pool, "up", rid, reason, signals)

    def _retire_candidate(self, pool: str) -> Optional[str]:
        """Last-in-first-out: prefer autoscaler-spawned replicas (the
        capacity this loop added), newest name first."""
        model, role = split_pool(pool)
        exact = sorted(
            rid for rid, s in self.fleet.servers.items()
            if getattr(s, "role", "both") == role
            and (model is None
                 or getattr(s, "model_name", None) == model))
        if not exact:
            return None
        marker = f"{pool.replace(':', '-')}-as"
        spawned = [r for r in exact if marker in r]
        return (spawned or exact)[-1]

    def _scale_down(self, pool: str, reason: str, signals: dict):
        rid = self._retire_candidate(pool)
        if rid is None:
            return
        st = self._state[pool]
        policy = self.policies[pool]
        self.fleet.remove_replica(
            rid, timeout=policy.drain_timeout_s, drain=True)
        st.last_action_t = self._clock()
        st.last_direction = "down"
        st.breach_streak = st.idle_streak = 0
        self._record(pool, "down", rid, reason, signals)

    def evaluate_once(self) -> List[dict]:
        """One control round over every managed pool.  Returns the
        decisions taken this round (possibly empty — sustained-breach
        hysteresis and cooldowns mean MOST rounds act on nothing).

        With ``signal_source="slo"`` (the default) the breach
        predicates are SLO rules: signals feed the recorder (gated —
        an inactive pool's p99/queue are not refreshed, so their
        rules render no verdict), ONE engine evaluation fires/resolves
        the per-pool rules as structured alerts, and the breach list
        is the pool's fresh firing set — identical decisions to the
        raw path, now alert-visible.  Scale-down idleness stays a raw
        capacity read in both modes (quiet is not an SLO breach)."""
        now = self._clock()
        signals: Dict[str, dict] = {}
        actives: Dict[str, bool] = {}
        for pool in self.pools:
            policy = self.policies[pool]
            sig = signals[pool] = self.pool_signals(pool)
            gate = policy.idle_requests_delta
            actives[pool] = (gate is None
                             or sig["requests_delta"] > gate)
            if self.slo_engine is not None:
                self._slo_feed(pool, sig, actives[pool], now)
        if self.slo_engine is not None:
            self.slo_engine.evaluate(now=now)
        taken = []
        for pool in self.pools:
            policy = self.policies[pool]
            st = self._state[pool]
            sig = signals[pool]
            active = actives[pool]
            if self.slo_engine is not None:
                breaches = self._slo_breaches(pool, now)
            else:
                breaches = []
                if active and sig["p99_s"] >= policy.p99_high_s:
                    breaches.append(f"p99 {sig['p99_s']:.3f}s >= "
                                    f"{policy.p99_high_s}s")
                if sig["shed_rate"] >= policy.shed_high \
                        and sig["shed_delta"] > 0:
                    breaches.append(
                        f"shed rate {sig['shed_rate']:.3f} >= "
                        f"{policy.shed_high}")
                if active and sig["queue_depth"] >= policy.queue_high:
                    breaches.append(f"queue {sig['queue_depth']} >= "
                                    f"{policy.queue_high}")
                if sig["kv_occupancy"] >= policy.kv_occupancy_high:
                    breaches.append(
                        f"kv occupancy {sig['kv_occupancy']:.2f} >= "
                        f"{policy.kv_occupancy_high}")
            idle = (sig["shed_delta"] == 0
                    and sig["queue_depth"] <= policy.queue_idle
                    and sig["kv_occupancy"]
                    <= policy.kv_occupancy_idle
                    and (not active
                         or sig["p99_s"] <= policy.p99_idle_s))
            st.breach_streak = st.breach_streak + 1 if breaches else 0
            st.idle_streak = st.idle_streak + 1 if idle else 0
            if now - st.last_action_t < policy.cooldown_s:
                continue  # hold: the last action is still settling
            before = len(self.decisions)
            if breaches and st.breach_streak >= policy.sustain \
                    and sig["replicas"] < policy.max_replicas:
                self._scale_up(pool, "; ".join(breaches), sig)
            elif idle and st.idle_streak >= policy.idle_sustain \
                    and sig["replicas"] > policy.min_replicas:
                self._scale_down(
                    pool,
                    f"idle: p99 {sig['p99_s']:.3f}s, no shed, "
                    f"queue {sig['queue_depth']}", sig)
            taken.extend(self.decisions[before:])
        return taken
