"""Continuous micro-batching into static bucket shapes.

The compiled eval forward traces one executable per input shape, so a
server that stacked whatever happened to be queued (7 requests now, 13
next tick) would recompile on nearly every batch — the exact failure
mode Parallax warns against (keep the hot path static-shaped, let the
control plane absorb variability).  The batcher therefore owns a
**bucket ladder**: batch sizes double from the mesh multiple up to
``max_batch``, every coalesced batch is padded (by repeating the last
record — ``pad_batch``'s numerically-valid convention) up to the
smallest bucket that holds it, and the padded rows are sliced off the
output.  Worst-case ``len(ladder)`` compiles per feature shape,
ever — regardless of traffic.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from ..optim._sharding_utils import round_up


def bucket_ladder(max_batch: int, multiple: int = 1) -> List[int]:
    """Doubling bucket sizes ending exactly at ``max_batch``, each
    rounded up to ``multiple`` (the mesh data-axis size — shard_map
    needs every batch divisible by it)."""
    if max_batch < 1:
        raise ValueError("max_batch must be >= 1")
    ladder, b = [], 1
    while b < max_batch:
        ladder.append(round_up(b, multiple))
        b *= 2
    ladder.append(round_up(max_batch, multiple))
    # rounding can introduce duplicates (e.g. 1,2,4 all round to 8)
    return sorted(set(ladder))


class MicroBatcher:
    def __init__(self, max_batch: int, multiple: int = 1):
        self.ladder = bucket_ladder(max_batch, multiple)
        self.max_batch = self.ladder[-1]
        #: buckets actually dispatched — the compile-accounting hook:
        #: the jit cache may hold at most one entry per (bucket,
        #: feature-shape) ever dispatched
        self.buckets_dispatched: set = set()

    def bucket_for(self, n: int) -> int:
        for b in self.ladder:
            if n <= b:
                return b
        raise ValueError(f"batch of {n} exceeds max_batch "
                         f"{self.max_batch}")

    def coalesce(self, features: Sequence[np.ndarray]
                 ) -> Tuple[np.ndarray, int]:
        """Stack per-request feature rows and pad up to the bucket by
        repeating the last row.  Returns ``(batch, bucket)``; the
        caller slices outputs back to ``len(features)``."""
        x = np.stack([np.asarray(f) for f in features])
        n = x.shape[0]
        bucket = self.bucket_for(n)
        if n < bucket:
            x = np.concatenate(
                [x, np.repeat(x[-1:], bucket - n, axis=0)], axis=0)
        self.buckets_dispatched.add(bucket)
        return x, bucket
