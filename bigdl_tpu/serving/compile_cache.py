"""Persisted compile cache for fast replica spin-up.

A cold autoscaled replica pays one XLA compile per (bucket,
feature-shape) — worst-case the whole bucket ladder — before it can
take traffic at full readiness.  jax's persistent compilation cache
(``jax.config.jax_compilation_cache_dir``) amortizes that across
process lifetimes: the first replica ever to compile a bucket writes
the executable to disk, and every later spin-up (autoscale scale-up,
crash replacement, rolling restart) loads it instead of recompiling.

``bigdl.serving.compileCache`` (env ``BIGDL_SERVING_COMPILECACHE``)
names the directory; :meth:`~.server.InferenceServer.start` calls
:func:`maybe_set_compile_cache_dir` so every replica start wires it in
without the caller doing anything.  Explicit
:func:`set_compile_cache_dir` wins over the property.  Best-effort by
design: a backend without persistent-cache support (CPU jax versions
vary) must never fail a replica start — the worst case is the old
behavior, a cold compile.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Optional

log = logging.getLogger("bigdl_tpu")

__all__ = ["set_compile_cache_dir", "maybe_set_compile_cache_dir",
           "compile_cache_dir"]

_LOCK = threading.Lock()
_STATE = {"dir": None}


def compile_cache_dir() -> Optional[str]:
    """The directory currently wired into jax, or None."""
    with _LOCK:
        return _STATE["dir"]


def set_compile_cache_dir(path: str) -> str:
    """Point jax's persistent compilation cache at ``path`` (created
    if missing) and drop the min-compile-time/min-entry-size floors so
    serving-scale programs (small, many) are cached too.  Idempotent;
    returns the installed path."""
    import jax

    path = os.path.abspath(str(path))
    with _LOCK:
        if _STATE["dir"] == path:
            return path
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        for knob, val in (
                ("jax_persistent_cache_min_compile_time_secs", 0.0),
                ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(knob, val)
            except (AttributeError, ValueError):  # older jax: keep
                pass                              # that knob's default
        _STATE["dir"] = path
        log.info("serving: persistent compile cache at %s", path)
        return path


def maybe_set_compile_cache_dir() -> Optional[str]:
    """Wire the ``bigdl.serving.compileCache`` property in when set;
    best-effort (a replica start must never fail on cache plumbing)."""
    from ..utils.engine import get_property

    path = get_property("bigdl.serving.compileCache")
    if not path:
        return compile_cache_dir()
    try:
        return set_compile_cache_dir(path)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:
        log.warning("serving: compile cache at %r not enabled: %s",
                    path, e)
        return None
