"""Remote sparse fetch: embedding lookups as guarded serving requests.

DLRM inference through the fleet needs rows from the parameter-server-
scale table (:class:`~bigdl_tpu.nn.embedding_store.EmbeddingStore`) —
a vocabulary that dwarfs HBM never rides along with the dense model's
params, so every lookup is a remote fetch against the live store legs.
This module gives that fetch the SAME machinery every other serving
request already rides (docs/serving.md):

* **deadline budget** — a fetch carries a deadline; rows that cannot
  be gathered in time are shed with the typed ``DEADLINE_EXCEEDED``,
  never served late or guessed;
* **retry within the budget** — a leg that is mid-repartition raises
  the retryable :class:`~bigdl_tpu.nn.embedding_store.StoreMigrating`;
  the fetch retries while budget remains, then sheds ``UNAVAILABLE``;
* **circuit breaker per leg** — a leg that keeps failing trips its
  breaker and is rejected fast (half-open probes ride the next fetch);
* **hot-row cache** — Zipf-skewed lookups hit the version-stamped
  :class:`~bigdl_tpu.nn.embedding_store.HotRowCache`; a repartition's
  version bump retires every cached row in O(1), so a mid-migration
  lookup either serves a row verified at the live version or sheds
  typed.  ``bad_rows_served`` counts rows handed out at a retired
  version — the audit every chaos test pins at **zero**.

The table version rides health snapshots
(``bigdl_embed_table_version``) exactly like replica health does, so
the fleet's monitors see a stuck or runaway migration as a plain
metric series.
"""
from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

import numpy as np

from ..nn.embedding_store import (EmbeddingStore, HotRowCache,
                                  StoreMigrating)
from ..telemetry import metric_names as mn
from .breaker import ADMIT, PROBE, CircuitBreaker
from .status import ServeResult, Status

__all__ = ["SparseFetchClient", "FetchResult"]


class FetchResult:
    """Terminal outcome of one sparse fetch (the lookup-shaped
    :class:`~bigdl_tpu.serving.status.ServeResult`)."""

    __slots__ = ("status", "rows", "version", "shed_rows", "error",
                 "latency_s", "cache_hits")

    def __init__(self, status: Status, rows=None, version=None,
                 shed_rows=(), error=None, latency_s=0.0,
                 cache_hits=0):
        self.status = status
        self.rows = rows
        self.version = version
        self.shed_rows = tuple(shed_rows)
        self.error = error
        self.latency_s = latency_s
        self.cache_hits = cache_hits

    @property
    def ok(self) -> bool:
        return self.status is Status.OK


class SparseFetchClient:
    """Deadline-budgeted, breaker-guarded row fetch against the live
    store legs, with a version-stamped hot-row cache in front.

    ``stores`` maps host → that host's :class:`EmbeddingStore` leg (the
    in-process resolver; a networked deployment resolves to RPC stubs
    with the same ``read_rows`` contract).  The member list — and with
    it row routing — follows the legs' own consistent assignment, so
    the client needs no ownership directory either.
    """

    def __init__(self, stores: Dict[str, EmbeddingStore], *,
                 cache: Optional[HotRowCache] = None,
                 cache_capacity: int = 4096,
                 default_deadline_s: float = 1.0,
                 retry_backoff_s: float = 0.002,
                 breaker_kw: Optional[dict] = None,
                 registry=None,
                 clock=time.monotonic,
                 sleep=time.sleep):
        if not stores:
            raise ValueError("SparseFetchClient needs at least one "
                             "store leg")
        self.stores = dict(stores)
        ref = next(iter(self.stores.values()))
        self.table = ref.table
        self.cache = cache if cache is not None else HotRowCache(
            cache_capacity)
        self.default_deadline_s = float(default_deadline_s)
        self.retry_backoff_s = float(retry_backoff_s)
        self._clock = clock
        self._sleep = sleep
        self.breakers = {
            h: CircuitBreaker(**(breaker_kw or {
                "failure_threshold": 5, "reset_timeout": 0.25}))
            for h in self.stores}
        # the audit counters: served rows, typed sheds, and the
        # must-stay-zero bad-rows count (a row handed out at a retired
        # version)
        self.rows_served = 0
        self.rows_shed = 0
        self.bad_rows_served = 0
        self._bad_reported = 0
        self.retries = 0
        self._registry = registry
        if registry is not None:
            self._g_version = registry.gauge(
                mn.EMBED_TABLE_VERSION,
                "live embedding table version", ("table",))
            self._c_hits = registry.counter(
                mn.EMBED_CACHE_HITS_TOTAL,
                "hot-row cache hits", ("table",))
            self._c_misses = registry.counter(
                mn.EMBED_CACHE_MISSES_TOTAL,
                "hot-row cache misses", ("table",))
            self._c_shed = registry.counter(
                mn.EMBED_ROWS_SHED_TOTAL,
                "rows shed typed instead of served unverified",
                ("table",))
            self._c_bad = registry.counter(
                mn.EMBED_BAD_ROWS_TOTAL,
                "rows served at a retired version (must stay 0)",
                ("table",))

    # ------------------------------------------------------------------
    def _live_version(self) -> int:
        return max(s.version for s in self.stores.values())

    def _sync_cache_version(self) -> int:
        """Adopt the legs' live version into the cache (monotonic) —
        the invalidation edge every repartition publishes."""
        v = self._live_version()
        self.cache.bump_version(v)
        return v

    def fetch(self, rows: Sequence[int],
              deadline_s: Optional[float] = None) -> FetchResult:
        """Gather ``rows`` → ``FetchResult``.  OK carries the full
        ``[len(rows), dim]`` matrix verified at one table version;
        any other status carries ``shed_rows`` — the caller sheds or
        retries, it never receives a partially-verified matrix."""
        t0 = self._clock()
        budget = (self.default_deadline_s if deadline_s is None
                  else float(deadline_s))
        deadline = t0 + budget
        version = self._sync_cache_version()
        rows = [int(r) for r in rows]
        ref = next(iter(self.stores.values()))
        out = np.empty((len(rows), ref.dim), dtype=ref.dtype)

        # cache pass
        missing: Dict[str, list] = {}
        cache_hits = 0
        for i, r in enumerate(rows):
            vec = self.cache.get(r)
            if vec is not None:
                out[i] = vec
                cache_hits += 1
            else:
                owner = ref.owner_of_row(r)
                missing.setdefault(owner, []).append(i)
        if self._registry is not None:
            self._c_hits.labels(table=self.table).inc(cache_hits)
            self._c_misses.labels(table=self.table).inc(
                len(rows) - cache_hits)

        # owner-grouped fetch with retry inside the deadline budget
        for owner, idxs in missing.items():
            res = self._fetch_leg(owner, [rows[i] for i in idxs],
                                  deadline)
            if isinstance(res, FetchResult):   # typed shed
                self.rows_shed += sum(len(v) for v in missing.values())
                if self._registry is not None:
                    self._c_shed.labels(table=self.table).inc(
                        sum(len(v) for v in missing.values()))
                res.shed_rows = tuple(
                    rows[i] for v in missing.values() for i in v)
                res.latency_s = self._clock() - t0
                res.cache_hits = cache_hits
                return res
            vecs, leg_version = res
            # verify-before-serve: a row read at a version the table
            # has moved past mid-fetch is never returned — re-read at
            # the live version while budget remains, else shed typed.
            while leg_version < self._live_version():
                version = self._sync_cache_version()
                if self._clock() >= deadline:
                    self.rows_shed += len(idxs)
                    if self._registry is not None:
                        self._c_shed.labels(table=self.table).inc(
                            len(idxs))
                    return FetchResult(
                        Status.DEADLINE_EXCEEDED,
                        shed_rows=tuple(rows[i] for i in idxs),
                        error="table version moved mid-fetch and the "
                              "re-read budget is spent",
                        latency_s=self._clock() - t0,
                        cache_hits=cache_hits)
                retry = self._fetch_leg(
                    owner, [rows[i] for i in idxs], deadline)
                if isinstance(retry, FetchResult):
                    retry.latency_s = self._clock() - t0
                    return retry
                vecs, leg_version = retry
            if leg_version < version:
                # unreachable by construction — counting it is the
                # audit the chaos bar pins at zero
                self.bad_rows_served += len(idxs)
            for j, i in enumerate(idxs):
                out[i] = vecs[j]
                self.cache.put(rows[i], vecs[j], leg_version)
        self.rows_served += len(rows)
        if self._registry is not None:
            self._g_version.labels(table=self.table).set(
                self._live_version())
        return FetchResult(Status.OK, rows=out, version=version,
                           latency_s=self._clock() - t0,
                           cache_hits=cache_hits)

    def _fetch_leg(self, owner: str, row_ids: Sequence[int],
                   deadline: float):
        """One leg's gather under breaker + retry-within-budget.
        Returns ``(vecs, version)`` or a typed :class:`FetchResult`."""
        store = self.stores.get(owner)
        if store is None:
            return FetchResult(
                Status.UNAVAILABLE,
                error=f"no live leg for owner {owner!r}")
        br = self.breakers[owner]
        while True:
            verdict = br.acquire()
            if verdict not in (ADMIT, PROBE):
                return FetchResult(
                    Status.UNAVAILABLE,
                    error=f"breaker open for leg {owner!r}")
            try:
                vecs, version = store.read_rows(row_ids)
            except StoreMigrating as e:
                br.record_failure()
                self.retries += 1
                if self._clock() + self.retry_backoff_s >= deadline:
                    return FetchResult(Status.DEADLINE_EXCEEDED,
                                       error=str(e))
                self._sleep(self.retry_backoff_s)
                continue
            except Exception as e:  # leg fault: typed, never a guess
                br.record_failure()
                return FetchResult(Status.INTERNAL_ERROR,
                                   error=f"{type(e).__name__}: {e}")
            br.record_success()
            return vecs, version

    # ------------------------------------------------------------------
    def embed(self, indices: np.ndarray,
              deadline_s: Optional[float] = None) -> ServeResult:
        """Batch-of-lookups convenience for serving paths: 1-based
        float indices (the :class:`LookupTable` convention the
        clickstream emits) → ``ServeResult`` whose output is the
        ``indices.shape + (dim,)`` embedded block."""
        idx = np.asarray(indices)
        flat = np.clip(idx.astype(np.int64) - 1, 0,
                       next(iter(self.stores.values())).n_rows - 1)
        res = self.fetch(flat.reshape(-1).tolist(),
                         deadline_s=deadline_s)
        if not res.ok:
            return ServeResult(status=res.status, error=res.error,
                               latency_s=res.latency_s)
        out = res.rows.reshape(idx.shape + (res.rows.shape[-1],))
        return ServeResult(status=Status.OK, output=out,
                           latency_s=res.latency_s)

    def health_snapshot(self) -> dict:
        """What a replica publishes about its sparse-fetch dependency
        — the table version gauge plus the audit counters, shaped like
        every other ``srvhealth`` payload field."""
        snap = {
            "table": self.table,
            "table_version": self._live_version(),
            "rows_served": self.rows_served,
            "rows_shed": self.rows_shed,
            "bad_rows_served": self.bad_rows_served,
            "retries": self.retries,
            "cache": self.cache.snapshot(),
            "breakers": {h: b.snapshot()["state"]
                         for h, b in self.breakers.items()},
        }
        if self._registry is not None:
            self._g_version.labels(table=self.table).set(
                snap["table_version"])
            bad = self.bad_rows_served - self._bad_reported
            if bad > 0:
                self._c_bad.labels(table=self.table).inc(bad)
                self._bad_reported = self.bad_rows_served
        return snap
