"""Hardened online serving subsystem.

The offline surface (``optim.Predictor`` walking a dataset,
``models.generate`` as a library call) serves nobody under live
traffic: the first bad request, stuck device, or queue pile-up takes
the whole process down.  This package is the serving-side counterpart
of :mod:`bigdl_tpu.resilience` — the same discipline (typed failure
classification, preemption hooks, deterministic fault injection,
verified checkpoints) applied to an in-process request path:

* :mod:`.server`  — :class:`InferenceServer`: bounded request queue +
  a worker thread that coalesces requests into **static bucket
  shapes** (continuous micro-batching through the same cached compiled
  eval forward the Predictor uses, and the KV-cache decode generator
  for token generation), so variable traffic never triggers a
  recompile.  SIGTERM (via :mod:`bigdl_tpu.resilience.preemption`)
  stops admission, finishes everything already admitted, and exits
  cleanly.
* :mod:`.status`  — the status taxonomy: every request resolves to a
  :class:`ServeResult` (``OK`` / ``DEADLINE_EXCEEDED`` / ``OVERLOADED``
  / ``UNAVAILABLE`` / ``INTERNAL_ERROR`` / ``CANCELLED``) — never a
  silent drop, never an unbounded wait.
* :mod:`.breaker` — :class:`CircuitBreaker` around the compiled step:
  consecutive failures (classified retryable vs fatal by
  :class:`bigdl_tpu.resilience.retry.RetryPolicy`) trip it open; while
  open the server rejects fast instead of crashing; a half-open probe
  admits one batch to test recovery.
* :mod:`.batcher` — :class:`MicroBatcher`: bucket ladder + tail
  padding (``optim._sharding_utils.pad_batch``) + compile accounting.
* :mod:`.swap`    — hot model swap: new params load through the
  crc32c-verified checkpoint path, pass a canary batch, and swap
  atomically between batches — rolling back if the canary fails.
* :mod:`.metrics` — per-request counters + latency quantiles
  (p50/p99) backed by the unified telemetry registry
  (:mod:`bigdl_tpu.telemetry` — Prometheus text export, mergeable
  histograms), exported through ``visualization.summary``.
* :mod:`.fleet` / :mod:`.router` — the replica fleet layer:
  :class:`ServingFleet` runs N replicas whose membership rides the
  elastic KV transport (heartbeats + health snapshots + incarnation
  numbers, exactly like training gangs) and rolls verified deploys
  one replica at a time with fleet-wide rollback;
  :class:`FleetRouter` dispatches least-loaded with deadline-budget
  failover retries, optional p99-derived hedging, and per-replica
  circuit breakers.

* :mod:`.kvpool` / :mod:`.pools` / :mod:`.autoscale` — the serving
  scale-out control plane: :class:`KVPagePool` pages the decode
  KV-cache (requests hold pages for the positions they actually fill,
  pool exhaustion sheds typed OVERLOADED), replicas advertise a
  prefill/decode/both **role** so the router can disaggregate the two
  phases into separately-sized pools (KV pages travel between them as
  crc-verified handoff blobs), and :class:`Autoscaler` scales each
  pool independently on the router's aggregated telemetry (p99, shed
  rate, queue depth, KV occupancy) with hysteresis, cooldowns, and
  drain-before-retire.  :mod:`.compile_cache` persists XLA
  executables (``bigdl.serving.compileCache``) so cold autoscaled
  replicas skip per-bucket compiles.

Deterministic serving fault injectors (fail-next-N steps, injected
step latency, poisoned params, replica kill/partition) live with the
training injectors in :mod:`bigdl_tpu.resilience.faults`.
"""
from .autoscale import AutoscalePolicy, Autoscaler
from .batcher import MicroBatcher
from .breaker import CircuitBreaker
from .compile_cache import set_compile_cache_dir
from .fleet import FleetQuorumError, ReplicaAgent, ServingFleet
from .health import FleetHealthMonitor, ReplicaHealthPolicy
from .kvpool import KVPagePool, PageLease, PoolExhausted
from .metrics import ServingMetrics
from .pools import HandoffCorrupt
from .request_trace import (ReplicaTraceSink, RequestTracer,
                            trace_attribution, trace_coverage)
from .router import FleetRouter
from .server import InferenceServer
from .sparse_fetch import FetchResult, SparseFetchClient
from .status import ServeFuture, ServeResult, Status
from .swap import load_verified_params

__all__ = [
    "AutoscalePolicy", "Autoscaler", "CircuitBreaker",
    "FleetHealthMonitor", "FleetQuorumError", "FleetRouter",
    "HandoffCorrupt",
    "FetchResult",
    "InferenceServer", "KVPagePool", "MicroBatcher", "PageLease",
    "PoolExhausted", "ReplicaAgent", "ReplicaHealthPolicy",
    "ReplicaTraceSink",
    "RequestTracer", "ServeFuture", "ServeResult",
    "ServingFleet", "ServingMetrics", "SparseFetchClient", "Status",
    "load_verified_params", "set_compile_cache_dir",
    "trace_attribution", "trace_coverage",
]
