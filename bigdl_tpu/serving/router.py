"""Failover router over a fleet of serving replicas.

One :class:`FleetRouter` fronts N :class:`~.server.InferenceServer`
replicas and owns the three cluster behaviors a single server cannot
have:

* **Live-set maintenance** — replica membership rides the same
  machinery as training gangs (:class:`~bigdl_tpu.resilience.elastic
  .ElasticCoordinator`): every replica heartbeats and publishes a
  health snapshot (ready, queue depth, breaker state, p99) through the
  elastic KV transport, membership is versioned by incarnation
  numbers, and every reconfiguration is an incarnation bump.  The
  router ejects a replica that misses heartbeats or reports its
  breaker open (eviction marker + membership proposal, exactly the
  shrink path training takes on a dead host) and re-admits it when its
  beats resume and it reports ready again.
* **Failover dispatch** — requests go to the *least-loaded* ready
  replica (router-tracked in-flight count + the replica's published
  queue depth).  A request that comes back with a retryable status
  (INTERNAL_ERROR / UNAVAILABLE / OVERLOADED / CANCELLED) retries on a
  *different* replica with the **remaining** deadline budget — the
  deadline is propagated, never reset — until the budget or the
  attempt bound runs out.  Per-replica circuit breakers
  (:class:`~.breaker.CircuitBreaker`, the same state machine the
  server wraps its compiled step in) stop the router from hammering a
  replica that keeps failing, independent of membership.
* **Tail-latency hedging** — optionally, when the primary has not
  answered within a p99-derived delay, the request is *duplicated* to
  a second replica; the first usable response wins and the loser is
  abandoned (its result is discarded on arrival — a dispatched device
  batch is not interruptible).  ``hedges_fired`` / ``hedges_won``
  count it in the router's :class:`~.metrics.ServingMetrics`.

Every request resolves to exactly one typed
:class:`~.status.ServeResult`, same contract as the single server —
the fleet adds failure *routing*, never failure *hiding*.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional, Tuple

from ..telemetry.events import record_change as _record_change
from .breaker import CircuitBreaker, REJECT
from .metrics import ServingMetrics
from .status import ServeFuture, ServeResult, Status

log = logging.getLogger("bigdl_tpu")

#: KV key prefix for replica health snapshots (next to the
#: coordinator's ``hb/`` beats; the payload carries the incarnation it
#: was published under)
HEALTH_PREFIX = "srvhealth/"

#: statuses worth retrying on a different replica — the *replica*
#: failed or refused, the request itself is fine
RETRYABLE_STATUSES = frozenset((
    Status.INTERNAL_ERROR, Status.UNAVAILABLE, Status.OVERLOADED,
    Status.CANCELLED,
))


def read_health(transport, replica: str) -> Optional[dict]:
    """The newest health snapshot ``replica`` published, or None."""
    raw = transport.get(HEALTH_PREFIX + str(replica))
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return None


class FleetRouter:
    """Health-aware failover router — see the module docstring.

    Parameters
    ----------
    replicas : id → the local server handle to dispatch to (in a
        multi-process fleet these are RPC stubs; the contract is just
        ``submit`` / ``submit_generate`` returning a ServeFuture).
    coordinator : the router's own ElasticCoordinator over the fleet
        transport (membership reads + eject/readmit proposals).
    max_attempts : dispatch attempts per request (primary + retries).
    default_deadline_s : per-request deadline when ``submit`` gives
        none (None = no deadline; retries then bound only by attempts).
    hedge : enable tail-latency hedging.
    hedge_delay_s : fixed hedge delay; None derives it from the
        router's own observed p99 (clamped to ``hedge_min_delay_s``,
        with ``hedge_default_delay_s`` before any sample exists).
    breaker_factory : per-replica router-side breaker constructor.
    max_workers : router dispatch pool size (each in-flight request
        occupies one worker while it waits).
    """

    def __init__(self, replicas: Dict[str, object], coordinator, *,
                 metrics: Optional[ServingMetrics] = None,
                 max_attempts: int = 3,
                 default_deadline_s: Optional[float] = None,
                 hedge: bool = False,
                 hedge_delay_s: Optional[float] = None,
                 hedge_min_delay_s: float = 0.005,
                 hedge_default_delay_s: float = 0.050,
                 hedge_decode: bool = False,
                 disaggregate: bool = False,
                 breaker_factory: Optional[Callable[[], CircuitBreaker]]
                 = None,
                 max_workers: int = 16,
                 tracing=None,
                 model_registry=None,
                 admission=None,
                 default_model: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.replicas = dict(replicas)
        self.coordinator = coordinator
        self.metrics = metrics or ServingMetrics()
        #: multi-tenant routing (serving.registry): ``model_registry``
        #: answers "does this model exist" at admission and per attempt
        #: (a miss is typed NOT_FOUND — no queue slot, no retry burn);
        #: ``admission`` enforces per-tenant weighted quotas with fair
        #: shedding before global shedding.  Both None = single-model
        #: fleet, zero new cost on the request path.
        self.model_registry = model_registry
        self.admission = admission
        self.default_model = default_model
        self.max_attempts = max(1, int(max_attempts))
        self.default_deadline_s = default_deadline_s
        self.hedge = bool(hedge)
        self.hedge_delay_s = hedge_delay_s
        self.hedge_min_delay_s = float(hedge_min_delay_s)
        self.hedge_default_delay_s = float(hedge_default_delay_s)
        #: hedging a DECODE-phase request duplicates a long
        #: HBM-bandwidth-bound stream and doubles its KV-pool hold for
        #: a tail win that belongs to prefill — suppressed by default;
        #: suppressions are counted
        #: (``bigdl_serving_hedges_total{event="suppressed"}``)
        self.hedge_decode = bool(hedge_decode)
        #: split ``submit_generate`` into a prefill dispatch (returns
        #: the KV handoff + first token) and a decode dispatch
        #: (streams the rest), each least-loaded within its own role
        #: pool under the same deadline-budget/retry/breaker machinery
        self.disaggregate = bool(disaggregate)
        self._breaker_factory = breaker_factory or CircuitBreaker
        #: distributed request tracing (serving.request_trace
        #: .RequestTracer): mints a TraceContext per request, records
        #: the root/attempt spans, tail-samples at completion and
        #: stitches kept traces from the replicas' KV fragments.
        #: None = tracing off, zero per-request cost.
        self.tracing = tracing
        self._clock = clock
        self._lock = threading.Lock()
        # optimistic until the first refresh: every configured replica
        # is a member (matches the fleet's bootstrap membership)
        self._members: Tuple[str, ...] = tuple(sorted(self.replicas))
        self._health: Dict[str, dict] = {}
        self._inflight: Dict[str, int] = {r: 0 for r in self.replicas}
        self._breakers: Dict[str, CircuitBreaker] = {}
        # replica -> reason: marked by the SLO health monitor on a
        # per-replica rule breach.  A degraded replica is unroutable
        # and EJECTED at the next refresh (the breaker-open path), and
        # is not re-admitted until the mark clears
        self._degraded: Dict[str, str] = {}
        self._dispatch_total = self.metrics.registry.counter(
            "bigdl_fleet_dispatch_total",
            "router dispatches per replica and terminal status",
            labels=("replica", "status"))
        self._tenant_dispatch = self.metrics.registry.counter(
            "bigdl_tenant_dispatch_total",
            "router dispatches per tenant, replica and terminal "
            "status", labels=("tenant", "replica", "status"))
        self._tenant_admission = self.metrics.registry.counter(
            "bigdl_tenant_admission_total",
            "admission decisions per tenant (admitted | tenant_quota "
            "| global | not_found)", labels=("tenant", "decision"))
        self._tenant_inflight = self.metrics.registry.gauge(
            "bigdl_tenant_inflight",
            "admitted requests currently in flight per tenant",
            labels=("tenant",))
        self.ejections = 0
        self.readmissions = 0
        self._pool = ThreadPoolExecutor(
            max_workers=int(max_workers),
            thread_name_prefix="bigdl-fleet-router")
        self._closed = False

    # ------------------------------------------------------------ membership
    @property
    def members(self) -> Tuple[str, ...]:
        with self._lock:
            return self._members

    def live(self) -> Tuple[str, ...]:
        """Members currently routable: health known-ready (or not yet
        reported), not SLO-degraded, and router-side breaker not
        rejecting."""
        with self._lock:
            members, health = self._members, dict(self._health)
            degraded = set(self._degraded)
        out = []
        for r in members:
            if r in degraded:
                continue
            h = health.get(r)
            if h is not None and not h.get("ready", True):
                continue
            if self._breaker(r).state == "open":
                continue
            out.append(r)
        return tuple(out)

    # -------------------------------------------------- SLO degradation
    def mark_degraded(self, replica: str, reason: str = "") -> None:
        """An SLO rule breached on this replica (serving/health.py):
        stop routing to it NOW and eject it from membership at the
        next refresh — the same machinery a reported-open breaker
        rides.  Idempotent."""
        with self._lock:
            known = replica in self.replicas
            already = replica in self._degraded
            self._degraded[str(replica)] = str(reason)
        if known and not already:
            log.warning("fleet: replica %s marked DEGRADED (%s)",
                        replica, reason or "slo breach")

    def clear_degraded(self, replica: str) -> None:
        """The breaching rule resolved: the replica may re-admit
        through the normal returner path (beats + reports ready)."""
        with self._lock:
            was = self._degraded.pop(str(replica), None)
        if was is not None:
            log.info("fleet: replica %s degradation cleared", replica)

    @property
    def degraded(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._degraded)

    def health_of(self, replica: str) -> Optional[dict]:
        with self._lock:
            return self._health.get(replica)

    def refresh(self):
        """One membership-maintenance round: re-read beats + health,
        eject members that missed heartbeats or report breaker-open,
        re-admit returners that beat again and report ready.  Called by
        the fleet's pump loop; idempotent and safe to call anytime."""
        c = self.coordinator
        n, members = c.membership()
        beats = c.beats()
        alive = c.alive(beats)
        health: Dict[str, dict] = {}
        for r in self.replicas:
            h = read_health(c.transport, r)
            if h is not None:
                health[r] = h
        with self._lock:
            degraded_marks = set(self._degraded)
        dead = [m for m in members if m not in alive]
        breaker_open = [
            m for m in members if m in alive
            and (health.get(m) or {}).get("breaker_state") == "open"]
        degraded = [m for m in members
                    if m in alive and m not in breaker_open
                    and m in degraded_marks]
        out = dead + breaker_open + degraded
        if out:
            survivors = [m for m in members if m not in out]
            if survivors:
                n2 = c.propose(
                    survivors,
                    f"fleet eject: dead={dead} "
                    f"breaker_open={breaker_open} "
                    f"degraded={degraded}", expect=n)
                if n2 is not None:
                    for m in out:
                        c.evict(m, "missed heartbeats" if m in dead
                                else ("slo degraded" if m in degraded
                                      else "breaker open"))
                    self.ejections += len(out)
                    log.warning(
                        "fleet: ejected %s (dead=%s breaker_open=%s "
                        "degraded=%s), incarnation %d members=%s",
                        out, dead, breaker_open, degraded, n2,
                        survivors)
                n, members = c.membership()
        rejoiners = [
            r for r in sorted(alive)
            if r not in members and r in self.replicas
            and r not in degraded_marks
            and (health.get(r) or {}).get("ready")]
        if rejoiners:
            grown = sorted(set(members) | set(rejoiners))
            n2 = c.propose(grown, f"fleet readmit: {rejoiners}",
                           expect=n)
            if n2 is not None:
                for r in rejoiners:
                    c.readmit(r)
                self.readmissions += len(rejoiners)
                log.warning("fleet: re-admitted %s, incarnation %d "
                            "members=%s", rejoiners, n2, grown)
                n, members = c.membership()
        with self._lock:
            self._members = tuple(sorted(members))
            self._health = health

    def add_replica(self, replica: str, handle) -> None:
        """Register a new dispatch target (autoscale scale-up): the
        replica joins the routable set once its agent beats and its
        health reports ready (the normal re-admission path)."""
        with self._lock:
            self.replicas[replica] = handle
            self._inflight.setdefault(replica, 0)

    def remove_replica(self, replica: str) -> None:
        """Deregister a retired replica and retire it from membership
        NOW (a planned retire must not wait out the heartbeat timeout
        like a death would)."""
        with self._lock:
            self.replicas.pop(replica, None)
            self._health.pop(replica, None)
            self._breakers.pop(replica, None)
            self._degraded.pop(replica, None)
        c = self.coordinator
        n, members = c.membership()
        if replica in members:
            survivors = [m for m in members if m != replica]
            if survivors:
                n2 = c.propose(survivors, f"fleet retire: {replica}",
                               expect=n)
                if n2 is not None:
                    c.evict(replica, "retired (scale-down)")
                    log.info("fleet: retired %s, incarnation %d "
                             "members=%s", replica, n2, survivors)
        with self._lock:
            self._members = tuple(m for m in self._members
                                  if m != replica)

    # ------------------------------------------------------------ dispatch
    def _breaker(self, replica: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(replica)
            if br is None:
                br = self._breakers[replica] = self._breaker_factory()
                # stamp the guarded replica so journal events from
                # this breaker's transitions carry a replica scope
                br.owner = replica
            return br

    def _pick(self, exclude=(), phase: Optional[str] = None,
              model: Optional[str] = None) -> Optional[str]:
        """Least-loaded ready member outside ``exclude`` whose router-
        side breaker admits traffic, optionally restricted to the
        replicas serving ``phase`` (``prefill`` | ``decode`` — role
        advertised in the health snapshot, unreported roles count as
        ``both``) and/or advertising ``model`` (multi-tenant routing:
        only replicas whose health snapshot names the model are
        candidates — a replica that has not reported cannot prove it
        serves the model and is skipped).  The breaker is only
        ``acquire``d on the replica actually chosen, so a half-open
        probe slot is never burned on a replica we don't dispatch to."""
        from .pools import serves_phase

        with self._lock:
            members = self._members
            health = dict(self._health)
            inflight = dict(self._inflight)
            degraded = set(self._degraded)
        ranked = []
        for r in members:
            if r in exclude or r not in self.replicas \
                    or r in degraded:
                continue
            h = health.get(r)
            if h is not None and not h.get("ready", True):
                continue
            if phase is not None and not serves_phase(
                    (h or {}).get("role"), phase):
                continue
            if model is not None \
                    and (h or {}).get("model") != model:
                continue
            load = inflight.get(r, 0) + int(
                (h or {}).get("queue_depth", 0))
            ranked.append((load, r))
        for _, r in sorted(ranked):
            if self._breaker(r).acquire() != REJECT:
                return r
        return None

    def pool_members(self, phase: str) -> Tuple[str, ...]:
        """Current members of one role pool (from the health view) —
        what the autoscaler sizes."""
        from .pools import serves_phase

        with self._lock:
            members = self._members
            health = dict(self._health)
        return tuple(sorted(
            r for r in members
            if serves_phase((health.get(r) or {}).get("role"), phase)))

    def _resolve(self, fut: ServeFuture, result: ServeResult,
                 t0: float, trace=None, tenant: Optional[str] = None):
        result.latency_s = self._clock() - t0
        kept = None
        if trace is not None:
            # tail sampling runs HERE, when the outcome is known: the
            # p99 reference excludes this sample (it is about to land;
            # amortized-cached — an exact sort per request would tax
            # the hot path O(window log window))
            p99 = self.metrics.latency_p99()
            kept = self.tracing.finish(
                trace, result.status.value,
                result.status is Status.OK, result.latency_s, p99)
            result.trace_id = trace.ctx.trace_id
        self.metrics.record(
            result.status, result.latency_s, result.queued_s,
            trace_id=(result.trace_id if kept else None),
            tenant=tenant)
        fut._resolve(result)

    def submit(self, feature,
               deadline_s: Optional[float] = None,
               model: Optional[str] = None,
               tenant: Optional[str] = None) -> ServeFuture:
        """Route one classification request across the fleet.  Returns
        a future that resolves to the winning replica's ServeResult
        (or a typed router-level failure).  ``model`` routes over the
        replicas advertising it (typed NOT_FOUND when unregistered);
        ``tenant`` names the quota the request admits under (defaults
        to the model name)."""
        return self._enqueue("classify", feature, None, deadline_s,
                             model=model, tenant=tenant)

    def submit_generate(self, prompt_ids, max_new: int,
                        eos_id: Optional[int] = None,
                        pad_id: Optional[int] = None,
                        deadline_s: Optional[float] = None,
                        model: Optional[str] = None,
                        tenant: Optional[str] = None
                        ) -> ServeFuture:
        """Route one generation request across the fleet."""
        return self._enqueue("generate", prompt_ids,
                             (int(max_new), eos_id, pad_id), deadline_s,
                             model=model, tenant=tenant)

    def _enqueue(self, kind, payload, opts, deadline_s,
                 model: Optional[str] = None,
                 tenant: Optional[str] = None) -> ServeFuture:
        fut = ServeFuture()
        now = self._clock()
        model = model if model is not None else self.default_model
        tenant = tenant if tenant is not None else model
        # admission-order contract: registry miss resolves typed
        # NOT_FOUND before any queue slot or quota charge; then the
        # tenant's deadline budget clamps; then the weighted quota
        # check admits or sheds — all before the dispatch pool sees
        # the request
        version = None
        if self.model_registry is not None and model is not None:
            version = self.model_registry.lookup(model)
            if version is None:
                if tenant is not None:
                    self._tenant_admission.labels(
                        tenant=tenant, decision="not_found").inc()
                    self.metrics.record_shed(tenant, "not_found")
                self._resolve(fut, ServeResult(
                    Status.NOT_FOUND,
                    error=f"model {model!r} is not registered"),
                    now, tenant=tenant)
                return fut
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        if self.admission is not None and tenant is not None:
            deadline_s = self.admission.deadline_for(tenant, deadline_s)
        deadline = None if deadline_s is None \
            else now + float(deadline_s)
        if self._closed:
            self._resolve(fut, ServeResult(
                Status.UNAVAILABLE, error="router closed"), now,
                tenant=tenant)
            return fut
        if self.admission is not None and tenant is not None:
            ok, decision = self.admission.try_admit(tenant)
            self._tenant_admission.labels(
                tenant=tenant, decision=decision).inc()
            if not ok:
                # weighted fair shedding: "tenant_quota" sheds ONLY the
                # over-quota tenant; "global" is fleet-wide exhaustion
                self.metrics.record_shed(tenant, decision)
                # journaled throttled per (tenant, reason): a flood
                # must not evict the deploy that explains it out of
                # the bounded ring
                _record_change("tenant_shed", str(decision),
                               source="serving.router", tenant=tenant,
                               throttle_key=f"{tenant}/{decision}")
                self._resolve(fut, ServeResult(
                    Status.OVERLOADED,
                    error=f"tenant {tenant!r} admission refused "
                          f"({decision})"), now, tenant=tenant)
                return fut
            self._tenant_inflight.labels(tenant=tenant).set(
                float(self.admission.inflight(tenant)))

            def _release(_f, _tenant=tenant):
                self.admission.release(_tenant)
                self._tenant_inflight.labels(tenant=_tenant).set(
                    float(self.admission.inflight(_tenant)))

            # the slot returns exactly when the single-assignment
            # future resolves — typed shed, OK, cancel, all paths
            fut.add_done_callback(_release)
        # the TraceContext is minted HERE — at submit, before any
        # dispatch — so router-pool wait is part of the trace too
        trace = self.tracing.begin(kind, deadline_s) \
            if self.tracing is not None else None
        if trace is not None and (tenant is not None
                                  or model is not None):
            trace.ctx.tenant = tenant
            trace.ctx.model = model
            trace.ctx.model_version = version
        drive = self._drive
        if kind == "generate" and self.disaggregate:
            drive = self._drive_disagg
        try:
            self._pool.submit(drive, kind, payload, opts,
                              deadline, fut, now, trace, model, tenant)
        except RuntimeError:  # closed between the check and the submit
            self._resolve(fut, ServeResult(
                Status.UNAVAILABLE, error="router closed"), now,
                trace, tenant=tenant)
        return fut

    def _dispatch(self, replica: str, kind, payload, opts,
                  remaining: Optional[float],
                  trace=None, tenant: Optional[str] = None) -> ServeFuture:
        with self._lock:
            client = self.replicas.get(replica)
            if client is None:
                # retired (autoscale scale-down) between _pick and
                # here: resolve typed-retryable, never KeyError in the
                # drive thread (which would leave the future hanging)
                inner = ServeFuture()
                inner._resolve(ServeResult(
                    Status.UNAVAILABLE,
                    error=f"replica {replica} retired"))
                return inner
            self._inflight[replica] = self._inflight.get(replica, 0) + 1

        def on_done(f, _replica=replica):
            with self._lock:
                self._inflight[_replica] -= 1
            res = f._result
            br = self._breaker(_replica)
            if res is not None and res.status is Status.OK:
                br.record_success()
            else:
                # anything else — failure, shed, cancel, blown deadline
                # — reads as "stop preferring this replica"; the
                # breaker's half-open probe re-tests it later
                br.record_failure()
            if res is not None:
                self._dispatch_total.labels(
                    replica=_replica, status=res.status.value).inc()
                if tenant is not None:
                    self._tenant_dispatch.labels(
                        tenant=tenant, replica=_replica,
                        status=res.status.value).inc()

        # the forked context rides the dispatch only when tracing is
        # on — untraced dispatch keeps the pre-trace call signature
        # (third-party replica stubs need not know the kwarg)
        tkw = {} if trace is None else {"trace": trace.to_wire()}
        try:
            if kind == "classify":
                inner = client.submit(payload, deadline_s=remaining,
                                      **tkw)
            elif kind == "prefill":
                inner = client.submit_prefill(payload,
                                              deadline_s=remaining,
                                              **tkw)
            elif kind == "decode":
                max_new, eos_id, pad_id = opts
                inner = client.submit_decode(
                    payload, max_new, eos_id=eos_id, pad_id=pad_id,
                    deadline_s=remaining, **tkw)
            else:
                max_new, eos_id, pad_id = opts
                inner = client.submit_generate(
                    payload, max_new, eos_id=eos_id, pad_id=pad_id,
                    deadline_s=remaining, **tkw)
        except Exception as e:
            # a submit() that raises (malformed request, stopped
            # handle) resolves typed instead of leaking out of the
            # router pool
            inner = ServeFuture()
            with self._lock:
                self._inflight[replica] -= 1
            self._breaker(replica).record_failure()
            inner._resolve(ServeResult(
                Status.INTERNAL_ERROR,
                error=f"submit to {replica} raised "
                      f"{type(e).__name__}: {e}"))
            return inner
        inner.add_done_callback(on_done)
        return inner

    def _hedge_delay(self) -> float:
        if self.hedge_delay_s is not None:
            return float(self.hedge_delay_s)
        # amortized-cached p99 (metrics.latency_p99): the exact-window
        # quantile sorts up to 8192 samples — per-dispatch that tax
        # compounds exactly on the latency path hedging exists to cut
        p99 = self.metrics.latency_p99()
        if p99 is None or p99 <= 0:
            return self.hedge_default_delay_s
        return max(self.hedge_min_delay_s, float(p99))

    def _await_first_usable(self, pending: Dict[str, ServeFuture],
                            deadline: Optional[float],
                            hedge_replica: Optional[str],
                            on_result=None
                            ) -> Tuple[Optional[ServeResult],
                                       Optional[str]]:
        """Wait until one pending future resolves OK (first usable
        response wins; a failed one keeps the wait going while others
        are still out), all of them fail (return the last failure), or
        the deadline passes (return ``(None, None)``).  ``on_result``
        observes every resolved (replica, result) as it lands — the
        tracer closes attempt spans through it."""
        event = threading.Event()
        for f in pending.values():
            f.add_done_callback(lambda _f: event.set())
        last: Optional[ServeResult] = None
        last_replica: Optional[str] = None
        while pending:
            for r in [r for r, f in pending.items() if f.done()]:
                res = pending.pop(r)._result
                if on_result is not None:
                    on_result(r, res)
                if res.status is Status.OK:
                    if hedge_replica is not None \
                            and r == hedge_replica:
                        self.metrics.record_hedge(won=True)
                    return res, r
                last, last_replica = res, r
            if not pending:
                break
            now = self._clock()
            if deadline is not None and now >= deadline:
                return None, None
            timeout = 0.05 if deadline is None \
                else min(0.05, deadline - now)
            event.wait(timeout)
            event.clear()
        return last, last_replica

    #: which role pool each dispatch kind routes within (classify and
    #: whole generates go anywhere)
    _KIND_PHASE = {"prefill": "prefill", "decode": "decode"}

    def _attempt_loop(self, kind, payload, opts,
                      deadline: Optional[float],
                      trace=None, model: Optional[str] = None,
                      tenant: Optional[str] = None) -> ServeResult:
        """The failover core: least-loaded dispatch within the kind's
        role pool, retryable outcomes retried on a different replica
        with the REMAINING deadline budget, optional hedging.  Always
        returns a typed ServeResult — the disaggregated drive chains
        two of these (prefill, then decode) under one budget.

        ``model`` restricts every pick to replicas advertising it and
        re-checks the registry each attempt, so an entry that vanishes
        mid-flight (unregister_model_mid_flight) converts the request
        to typed NOT_FOUND instead of retrying forever against a pool
        that no longer serves it.

        With ``trace``, every dispatch (primary, retry, hedge) forks
        the request's TraceContext with the budget that remains at
        fork time; attempt spans close with their terminal status, a
        hedge's discarded duplicate closes ``hedge_outcome=lost`` AT
        DISCARD (never an orphan), and the winner is labeled ``won``.
        """
        tr = self.tracing if trace is not None else None
        phase = self._KIND_PHASE.get(kind)
        hedge_ok = self.hedge and (kind != "decode"
                                   or self.hedge_decode)
        tried = set()
        attempts = 0
        last: Optional[ServeResult] = None
        while True:
            now = self._clock()
            if deadline is not None and now >= deadline:
                return ServeResult(
                    Status.DEADLINE_EXCEEDED,
                    error=f"deadline budget exhausted after "
                          f"{attempts} attempt(s)")
            if attempts >= self.max_attempts:
                return last or ServeResult(
                    Status.UNAVAILABLE,
                    error=f"no attempt succeeded in "
                          f"{self.max_attempts}")
            if model is not None and self.model_registry is not None \
                    and self.model_registry.lookup(model) is None:
                # the registry entry vanished with this request in
                # flight: typed NOT_FOUND, no further retry burn
                if tenant is not None:
                    self.metrics.record_shed(tenant, "not_found")
                return ServeResult(
                    Status.NOT_FOUND,
                    error=f"model {model!r} unregistered mid-flight "
                          f"after {attempts} attempt(s)")
            primary = self._pick(exclude=tried, phase=phase,
                                 model=model)
            if primary is None:
                # nothing routable outside the tried set: degrade
                # typed (the single-server OVERLOADED/UNAVAILABLE
                # discipline, fleet-wide)
                return last or ServeResult(
                    Status.UNAVAILABLE,
                    error="no ready replica"
                          + (f" in the {phase} pool" if phase else "")
                          + (f" advertising model {model!r}"
                             if model else ""))
            if attempts > 0:
                self.metrics.record_retry()
            attempts += 1
            remaining = None if deadline is None else deadline - now
            ctxs: Dict[str, object] = {}
            if tr is not None:
                ctxs[primary] = tr.attempt_begin(
                    trace, primary, kind, remaining)
            pending = {primary: self._dispatch(
                primary, kind, payload, opts, remaining,
                trace=ctxs.get(primary), tenant=tenant)}
            hedge_replica = None
            if self.hedge and not pending[primary].done():
                delay = self._hedge_delay()
                if remaining is None or delay < remaining:
                    done_early = threading.Event()
                    pending[primary].add_done_callback(
                        lambda _f: done_early.set())
                    if not done_early.wait(delay):
                        if not hedge_ok:
                            # the hedge WOULD have fired — a decode
                            # duplicate doubles HBM + KV-pool hold, so
                            # count the suppression and carry on
                            self.metrics.record_hedge_suppressed()
                        else:
                            rem2 = None if deadline is None \
                                else deadline - self._clock()
                            if rem2 is None or rem2 > 0:
                                hedge_replica = self._pick(
                                    exclude=tried | {primary},
                                    phase=phase, model=model)
                            if hedge_replica is not None:
                                self.metrics.record_hedge(won=False)
                                if tr is not None:
                                    ctxs[hedge_replica] = \
                                        tr.attempt_begin(
                                            trace, hedge_replica,
                                            kind, rem2, hedge=True)
                                pending[hedge_replica] = \
                                    self._dispatch(
                                        hedge_replica, kind, payload,
                                        opts, rem2,
                                        trace=ctxs.get(hedge_replica),
                                        tenant=tenant)
            statuses: Dict[str, str] = {}
            on_result = None
            if tr is not None:
                def on_result(r, res, _st=statuses):
                    _st[r] = res.status.value if res is not None \
                        else "abandoned"
            result, via = self._await_first_usable(
                pending, deadline, hedge_replica, on_result=on_result)
            if tr is not None:
                hedged_race = len(ctxs) > 1
                for r, ctx in ctxs.items():
                    if result is not None \
                            and result.status is Status.OK:
                        if r == via:
                            tr.attempt_end(
                                trace, ctx, statuses.get(r),
                                hedge_outcome=("won" if hedged_race
                                               else None))
                        elif r in statuses:
                            # resolved before the winner: a real
                            # outcome, not a discard
                            tr.attempt_end(trace, ctx, statuses[r])
                        else:
                            # still in flight: its response will be
                            # discarded on arrival — mark now, close
                            # the span AT the discard
                            tr.mark_lost(trace, ctx)
                            pending[r].add_done_callback(
                                lambda f, c=ctx: tr.attempt_end(
                                    trace, c,
                                    (f._result.status.value
                                     if f._result else "abandoned"),
                                    hedge_outcome="lost"))
                    else:
                        tr.attempt_end(trace, ctx,
                                       statuses.get(r, "abandoned"))
            if result is None:
                return ServeResult(
                    Status.DEADLINE_EXCEEDED,
                    error=f"deadline passed waiting on "
                          f"{sorted(pending)}")
            if result.status is Status.OK:
                return result
            if result.status is Status.DEADLINE_EXCEEDED:
                # the budget died at the replica — propagate, don't
                # burn another attempt on a dead budget
                return result
            if result.status in RETRYABLE_STATUSES:
                tried.add(via)
                if hedge_replica is not None:
                    tried.add(hedge_replica)
                last = result
                continue
            return result

    def _drive(self, kind, payload, opts, deadline: Optional[float],
               fut: ServeFuture, t0: float, trace=None,
               model: Optional[str] = None,
               tenant: Optional[str] = None):
        if trace is not None:
            self.tracing.router_queue(trace, t0, self._clock())
        self._resolve(fut, self._attempt_loop(kind, payload, opts,
                                              deadline, trace=trace,
                                              model=model,
                                              tenant=tenant),
                      t0, trace, tenant=tenant)

    def _drive_disagg(self, kind, payload, opts,
                      deadline: Optional[float], fut: ServeFuture,
                      t0: float, trace=None,
                      model: Optional[str] = None,
                      tenant: Optional[str] = None):
        """Disaggregated generate: a prefill dispatch (routed within
        the prefill pool; returns the crc-sealed KV handoff + first
        token) then a decode dispatch (routed within the decode pool)
        under the SAME deadline budget.  The handoff blob is retained
        router-side across decode retries, so a decode replica killed
        mid-stream replays on a survivor within the remaining budget.
        The TraceContext crosses the pool boundary INSIDE the sealed
        blob (handoff extras) as well as on the dispatch itself.
        """
        import numpy as np

        from .pools import deserialize_handoff

        if trace is not None:
            self.tracing.router_queue(trace, t0, self._clock())
        pre = self._attempt_loop("prefill", payload, (), deadline,
                                 trace=trace, model=model,
                                 tenant=tenant)
        if pre.status is not Status.OK:
            self._resolve(fut, pre, t0, trace, tenant=tenant)
            return
        t_hand = self._clock()
        try:
            first = int(deserialize_handoff(pre.output)["first_token"])
        except Exception as e:
            self._resolve(fut, ServeResult(
                Status.INTERNAL_ERROR,
                error=f"prefill handoff unusable: "
                      f"{type(e).__name__}: {e}"), t0, trace,
                tenant=tenant)
            return
        self.metrics.record_ttft(self._clock() - t0, tenant=tenant)
        max_new = opts[0]
        if max_new <= 1:
            self._resolve(fut, ServeResult(
                Status.OK, output=np.asarray([first], np.int32),
                queued_s=pre.queued_s), t0, trace, tenant=tenant)
            return
        if trace is not None:
            # the router-side handoff hop: blob verify + re-dispatch
            self.tracing.handoff(trace, t_hand,
                                 self._clock() - t_hand,
                                 blob_bytes=len(pre.output))
        dec = self._attempt_loop("decode", pre.output, opts, deadline,
                                 trace=trace, model=model,
                                 tenant=tenant)
        if dec.status is not Status.OK:
            self._resolve(fut, dec, t0, trace, tenant=tenant)
            return
        dec.output = np.concatenate(
            [np.asarray([first], np.int32),
             np.asarray(dec.output, np.int32)])
        self._resolve(fut, dec, t0, trace, tenant=tenant)

    # ------------------------------------------------------------ lifecycle
    def close(self, wait: bool = True):
        """Stop accepting new requests and wind down the dispatch
        pool (in-flight drives finish — every accepted request still
        resolves)."""
        self._closed = True
        self._pool.shutdown(wait=wait)
        if self.tracing is not None:
            self.tracing.close()

    def snapshot(self) -> dict:
        with self._lock:
            members = list(self._members)
            inflight = dict(self._inflight)
        return {
            "members": members,
            "live": list(self.live()),
            "degraded": self.degraded,
            "inflight": inflight,
            "registry": (self.model_registry.models()
                         if self.model_registry is not None else None),
            "admission": (self.admission.snapshot()
                          if self.admission is not None else None),
            "pools": {"prefill": list(self.pool_members("prefill")),
                      "decode": list(self.pool_members("decode"))},
            "disaggregate": self.disaggregate,
            "ejections": self.ejections,
            "readmissions": self.readmissions,
            "breakers": {r: b.snapshot()
                         for r, b in sorted(self._breakers.items())},
            "metrics": self.metrics.snapshot(),
            "tracing": (self.tracing.snapshot()
                        if self.tracing is not None else None),
        }
