"""Paged KV-cache arena — pages instead of whole static buckets.

The unpaged decode path (`models/generate.py`) gives every in-flight
generate request a dense ``[B, Hkv, T_max, Dh]`` cache: a request that
will emit 40 tokens still pins ``T_max`` positions of HBM for its
whole lifetime, so the number of concurrent long decodes is bounded by
the *worst-case* window, not the *actual* one.  A :class:`KVPagePool`
preallocates ONE arena of fixed-size pages::

    arena_k / arena_v : [num_pages, layers, Hkv, page_size, Dh]

and each request holds a **page table** (a short list of page ids)
covering only the positions it has actually filled, extending one page
at a time as the decode grows.  At equal arena bytes the pool
therefore sustains ``T_max / T_actual`` times the concurrent requests
of the static-bucket path — the vLLM observation, at serving-control-
plane scale.

Allocation is host-side and O(1) (a free list under a lock); the
arena itself is a pair of device arrays updated *functionally* by the
paged decode programs (`models.generate.PagedDecoder`) — the pool
hands out page ids, the decoder gathers/scatters through them at
static shapes.  One writer at a time: the pool's ``arena_lock``
serializes read-modify-write of the arena reference (the serving
worker thread is the single writer in practice).

Exhaustion is an admission-control event, not an error: ``alloc``
raises :class:`PoolExhausted` and the server sheds the request with a
typed ``OVERLOADED`` — an un-servable decode must never be admitted.
Every lease is release-idempotent and the pool counts allocs/frees/
exhaustions plus a high-water mark, so leak detection is one
``free_pages == num_pages`` assert after drain.
"""
from __future__ import annotations

import threading
from typing import List, Optional

__all__ = ["KVPagePool", "PageLease", "PoolExhausted",
           "page_bucket_ladder", "page_bucket_for"]


class PoolExhausted(RuntimeError):
    """No free pages — the caller must shed (typed OVERLOADED), not
    block: a decode admitted without backing pages can never finish."""


def page_bucket_ladder(max_pages: int) -> List[int]:
    """Doubling page-table sizes ending exactly at ``max_pages`` —
    the compile ladder: one decode program per bucket, ever."""
    if max_pages < 1:
        raise ValueError("max_pages must be >= 1")
    ladder, b = [], 1
    while b < max_pages:
        ladder.append(b)
        b *= 2
    ladder.append(max_pages)
    return sorted(set(ladder))


def page_bucket_for(n: int, max_pages: int) -> int:
    """Smallest ladder bucket holding ``n`` pages."""
    for b in page_bucket_ladder(max_pages):
        if n <= b:
            return b
    raise PoolExhausted(
        f"page table of {n} exceeds max_pages {max_pages}")


class PageLease:
    """One request's hold on a set of pages.  ``extend`` grows it one
    allocation at a time as the decode crosses page boundaries;
    ``release`` is idempotent (the exhaustion/cancel/kill paths may
    race a finally-block release)."""

    __slots__ = ("pool", "pages", "owner", "_released")

    def __init__(self, pool: "KVPagePool", pages: List[int],
                 owner: Optional[str] = None):
        self.pool = pool
        self.pages = list(pages)
        self.owner = owner
        self._released = False

    def extend(self, n: int = 1) -> None:
        """Grow by ``n`` pages (raises :class:`PoolExhausted` — the
        already-held pages stay held; the caller decides whether to
        shed and release).  Growth is charged to the lease's owner, so
        a long decode keeps paying against its tenant's page budget."""
        if self._released:
            raise RuntimeError("lease already released")
        self.pages.extend(self.pool._take(n, self.owner))

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self.pool._give(self.pages, self.owner)

    @property
    def released(self) -> bool:
        return self._released

    def __len__(self) -> int:
        return len(self.pages)


class KVPagePool:
    """Preallocated paged KV arena + free-list allocator.

    Parameters mirror the decode cache geometry: ``layers`` transformer
    blocks, ``num_kv_heads`` KV heads (GQA: may be fewer than query
    heads), ``page_size`` positions per page, ``head_dim`` features.
    ``dtype`` is the cache dtype (the paged path is full-precision
    only; the int8 cache stays a dense-path knob).

    The arena is built lazily on first use so constructing a pool (for
    sizing math, tests of the allocator) costs no device memory.
    """

    def __init__(self, num_pages: int, layers: int, num_kv_heads: int,
                 page_size: int, head_dim: int, dtype=None):
        if num_pages < 1:
            raise ValueError("num_pages must be >= 1")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.num_pages = int(num_pages)
        self.layers = int(layers)
        self.num_kv_heads = int(num_kv_heads)
        self.page_size = int(page_size)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self._lock = threading.Lock()
        #: serializes functional read-modify-write of the arena
        #: reference by decode programs (single-writer contract)
        self.arena_lock = threading.RLock()
        self._free = list(range(self.num_pages))
        self._arena_k = None
        self._arena_v = None
        # accounting (leak detection + the occupancy gauge family)
        self.allocs = 0
        self.frees = 0
        self.exhaustions = 0
        self.high_water = 0
        # owner-scoped accounting (multi-tenant fleets): pages held and
        # optional hard budgets per owner.  An owner over its budget is
        # refused (PoolExhausted → typed OVERLOADED shed) even while
        # the free list could cover it — one tenant's long decodes can
        # never exhaust the shared arena for everyone else.
        self._held = {}
        self._budgets = {}
        #: owner charged for allocations that don't name one — set to
        #: the serving model's name so decoder-internal allocs (the
        #: paged decode path allocates from inside models.generate)
        #: land on the right tenant without plumbing owner through the
        #: decoder
        self.default_owner: Optional[str] = None

    # ------------------------------------------------------------ sizing
    @classmethod
    def for_model(cls, model, num_pages: int, page_size: int = 16,
                  dtype=None) -> "KVPagePool":
        """Size a pool from a ``TransformerLM``'s own geometry."""
        from ..models.generate import _check_model

        first, count = _check_model(model)
        mha = model.modules[first].modules[1]
        return cls(num_pages, count,
                   getattr(mha, "num_kv_heads", mha.num_heads),
                   page_size, mha.head_dim, dtype=dtype)

    def arena_bytes(self) -> int:
        """Bytes the full K+V arena occupies (itemsize from dtype;
        default float32)."""
        import numpy as np

        itemsize = np.dtype(self.dtype or np.float32).itemsize
        per = (self.layers * self.num_kv_heads * self.page_size
               * self.head_dim * itemsize)
        return 2 * self.num_pages * per

    def pages_for_tokens(self, tokens: int) -> int:
        return -(-int(tokens) // self.page_size)

    @property
    def max_positions(self) -> int:
        return self.num_pages * self.page_size

    # ------------------------------------------------------------ arena
    def _ensure_arena(self):
        if self._arena_k is None:
            import jax.numpy as jnp

            shape = (self.num_pages, self.layers, self.num_kv_heads,
                     self.page_size, self.head_dim)
            dt = self.dtype or jnp.float32
            self._arena_k = jnp.zeros(shape, dt)
            self._arena_v = jnp.zeros(shape, dt)

    @property
    def arena(self):
        """(arena_k, arena_v) — built on first access."""
        self._ensure_arena()
        return self._arena_k, self._arena_v

    def set_arena(self, arena_k, arena_v):
        """Install the functionally-updated arena (decoder-side; call
        under ``arena_lock``)."""
        self._arena_k = arena_k
        self._arena_v = arena_v

    def read_pages(self, page_ids):
        """Host copies of the given pages: (k, v) each
        ``[n, layers, Hkv, page_size, Dh]`` — the prefill→decode
        handoff export."""
        import numpy as np

        self._ensure_arena()
        idx = np.asarray(list(page_ids), np.int32)
        with self.arena_lock:
            return (np.asarray(self._arena_k[idx]),
                    np.asarray(self._arena_v[idx]))

    def write_pages(self, page_ids, k_pages, v_pages):
        """Scatter handed-off page contents into this pool's arena
        (decode-side import)."""
        import jax.numpy as jnp
        import numpy as np

        self._ensure_arena()
        idx = np.asarray(list(page_ids), np.int32)
        if k_pages.shape[0] != idx.shape[0]:
            raise ValueError(
                f"{k_pages.shape[0]} pages of data for {idx.shape[0]} "
                f"page ids")
        with self.arena_lock:
            dt = self._arena_k.dtype
            self._arena_k = self._arena_k.at[idx].set(
                jnp.asarray(k_pages, dt))
            self._arena_v = self._arena_v.at[idx].set(
                jnp.asarray(v_pages, dt))

    # ------------------------------------------------------------ alloc
    def set_owner_budget(self, owner: str, pages: int) -> None:
        """Cap ``owner`` at ``pages`` held pages — allocations past the
        cap raise :class:`PoolExhausted` even with free pages, so the
        over-budget owner sheds typed while other owners keep the
        arena."""
        with self._lock:
            self._budgets[str(owner)] = int(pages)

    def owner_held(self, owner: str) -> int:
        with self._lock:
            return self._held.get(str(owner), 0)

    def _take(self, n: int, owner: Optional[str] = None) -> List[int]:
        if owner is None:
            owner = self.default_owner
        with self._lock:
            if owner is not None:
                held = self._held.get(owner, 0)
                budget = self._budgets.get(owner)
                if budget is not None and held + n > budget:
                    self.exhaustions += 1
                    raise PoolExhausted(
                        f"owner {owner!r} needs {n} page(s) but holds "
                        f"{held} of its {budget}-page budget")
            if n > len(self._free):
                self.exhaustions += 1
                raise PoolExhausted(
                    f"need {n} page(s), {len(self._free)} free of "
                    f"{self.num_pages}")
            pages, self._free = self._free[:n], self._free[n:]
            self.allocs += n
            if owner is not None:
                self._held[owner] = self._held.get(owner, 0) + n
            in_use = self.num_pages - len(self._free)
            self.high_water = max(self.high_water, in_use)
            return pages

    def _give(self, pages: List[int],
              owner: Optional[str] = None) -> None:
        with self._lock:
            self._free.extend(pages)
            self.frees += len(pages)
            if owner is not None and owner in self._held:
                self._held[owner] = max(
                    0, self._held[owner] - len(pages))

    def alloc(self, n: int, owner: Optional[str] = None) -> PageLease:
        """Lease ``n`` pages (raises :class:`PoolExhausted` when the
        free list cannot cover it — shed, don't wait).  ``owner``
        (default: the pool's ``default_owner``) is charged for the
        pages against its optional budget."""
        if owner is None:
            owner = self.default_owner
        return PageLease(self, self._take(n, owner), owner)

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def occupancy(self) -> float:
        return 1.0 - self.free_pages / self.num_pages

    def stats(self) -> dict:
        with self._lock:
            free = len(self._free)
            by_owner = {o: h for o, h in self._held.items() if h}
        return {
            "by_owner": by_owner,
            "num_pages": self.num_pages,
            "free_pages": free,
            "in_use": self.num_pages - free,
            "occupancy": 1.0 - free / self.num_pages,
            "page_size": self.page_size,
            "allocs": self.allocs,
            "frees": self.frees,
            "exhaustions": self.exhaustions,
            "high_water": self.high_water,
            "arena_bytes": self.arena_bytes(),
        }
