"""In-process inference server — the hardened online request path.

One worker thread owns the device: it pulls admitted requests off a
bounded queue, coalesces them into static bucket shapes
(:class:`.batcher.MicroBatcher`), and dispatches ONE compiled program
per batch — classification through the same cached compiled eval
forward the Predictor uses (``optim.evaluator._cached_eval_fwd``,
shard_mapped when a mesh is given), token generation through the
KV-cache decode generator (``models.generate.cached_generate``).
Requests never touch the device individually and the device never
sees a shape it hasn't seen before — variable traffic changes *which
bucket* runs, not *what compiles*.

Request lifecycle (every path ends in a typed
:class:`~.status.ServeResult`; nothing hangs, nothing drops silently)::

    submit ──► admission ──► queue ──► batch ──► compiled step ──► OK
                  │            │         │            │
                  │ full       │ expired │ breaker    │ step raised
                  ▼            ▼         ▼ open       ▼
              OVERLOADED   DEADLINE_  UNAVAILABLE  INTERNAL_ERROR
              (shed)       EXCEEDED   (reject fast) (+ breaker count)

Failures at the step are classified retryable-vs-fatal by the
:class:`resilience.retry.RetryPolicy`; consecutive failures trip the
:class:`.breaker.CircuitBreaker` open (fatal ones immediately), a
half-open probe admits one request to test recovery, and while open
the server degrades to fast UNAVAILABLE rejections instead of
crashing.  SIGTERM (or ``resilience.preemption.request_preemption()``)
stops admission, finishes everything already admitted, and exits the
worker cleanly; a hard ``stop()`` resolves still-queued requests as
CANCELLED.  New params install atomically between batches via
:meth:`InferenceServer.swap_params` (crc32c-verified load + canary
batch + rollback — see :mod:`.swap`).
"""
from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..resilience import faults as _faults
from ..resilience.guards import tree_finite
from ..resilience.preemption import PreemptionHandler
from ..resilience.retry import RetryPolicy
from .batcher import MicroBatcher
from .breaker import OPEN, PROBE, REJECT, CircuitBreaker
from .metrics import ServingMetrics
from .status import Request, ServeFuture, ServeResult, Status
from .swap import SwapRejected, load_verified_params

log = logging.getLogger("bigdl_tpu")


class _BoundedQueue:
    """Deque + condition: reject-fast ``try_put``, front requeue for
    the breaker's half-open probe leftovers, and atomic drain."""

    def __init__(self, maxsize: int):
        self.maxsize = int(maxsize)
        self._d: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def try_put(self, item) -> bool:
        with self._lock:
            if len(self._d) >= self.maxsize:
                return False
            self._d.append(item)
            self._not_empty.notify()
            return True

    def put_front(self, items) -> None:
        """Requeue in original order ahead of newer arrivals (bound
        intentionally not enforced — these were already admitted)."""
        with self._lock:
            for item in reversed(list(items)):
                self._d.appendleft(item)
            self._not_empty.notify()

    def get(self, timeout: float):
        with self._lock:
            if not self._d:
                self._not_empty.wait(timeout)
            return self._d.popleft() if self._d else None

    def get_nowait(self):
        with self._lock:
            return self._d.popleft() if self._d else None

    def drain_all(self) -> list:
        with self._lock:
            items = list(self._d)
            self._d.clear()
            return items


class InferenceServer:
    """See the module docstring for the full request lifecycle.

    Parameters
    ----------
    model : the module to serve.  Classification rides its cached
        compiled eval forward; ``submit_generate`` additionally
        requires a ``TransformerLM``.
    mesh : optional Mesh — the forward shard_maps over its data axis
        (bucket sizes are rounded to the axis size).
    max_batch : largest micro-batch (top of the bucket ladder).
    max_queue : admission bound; a full queue sheds with OVERLOADED.
    batch_window_s : how long the worker waits to coalesce more
        requests after the first one arrives.
    default_deadline_s : per-request deadline when ``submit`` gives
        none (``None`` = no deadline).
    breaker / policy / metrics : injectable for tests; defaults are a
        3-failure threshold breaker and ``RetryPolicy.from_properties``
        classification.
    generate_dtype : compute dtype for the generation path (e.g.
        ``jnp.bfloat16``); ``None`` serves in the params' dtype.
    """

    def __init__(self, model, mesh=None, max_batch: int = 32,
                 max_queue: int = 256, batch_window_s: float = 0.002,
                 default_deadline_s: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 policy: Optional[RetryPolicy] = None,
                 metrics: Optional[ServingMetrics] = None,
                 generate_dtype=None, name: Optional[str] = None,
                 kv_pool=None, role: str = "both",
                 kv_page_window: Optional[int] = None,
                 kv_page_globals: int = 1, trace_sink=None,
                 model_name: Optional[str] = None,
                 model_version: str = "v1"):
        from ..optim._sharding_utils import data_mesh
        from .pools import ROLES

        #: replica identity — the fleet layer names its servers so the
        #: per-replica fault injectors (``delay_replica`` et al.) can
        #: target one member; anonymous servers match only unscoped
        #: faults
        self.name = name
        self.model = model
        #: multi-tenant identity: which registered model (and version)
        #: this replica serves — advertised in the health snapshot so
        #: the FleetRouter's ModelRegistry routing dispatches on it.
        #: None = single-model fleet (pre-registry behavior unchanged)
        self.model_name = model_name
        self.model_version = str(model_version)
        #: paged KV arena (``serving.kvpool.KVPagePool``): when set,
        #: generation serves through the paged decode path — each
        #: request holds pages for the positions it actually fills
        #: instead of a whole static T_max bucket, and pool exhaustion
        #: sheds typed OVERLOADED
        self.kv_pool = kv_pool
        #: page-granular block mask for long paged decodes (the BLaST
        #: sparsity story on the serving path): attend only the first
        #: ``kv_page_globals`` anchor pages + the last
        #: ``kv_page_window`` pages; None = dense over the page table
        self.kv_page_window = kv_page_window
        self.kv_page_globals = int(kv_page_globals)
        if role not in ROLES:
            raise ValueError(f"role {role!r} not in {ROLES}")
        #: which generation phase(s) this replica serves — advertised
        #: in the health snapshot so the FleetRouter can route prefill
        #: and decode to separately-sized pools
        self.role = role
        #: distributed request tracing (serving.request_trace
        #: .ReplicaTraceSink): when set, traced requests' queue wait,
        #: batch formation, compiled-step execution, KV-page gathers
        #: and swap/canary windows record as children of the request's
        #: remote span and publish as trace fragments over the fleet
        #: KV transport.  None = zero tracing overhead.
        self.trace_sink = trace_sink
        if role != "both" and kv_pool is None:
            raise ValueError(
                f"role {role!r} requires a kv_pool (the prefill/"
                f"decode split moves KV pages between pools)")
        if kv_pool is not None and model_name is not None \
                and kv_pool.default_owner is None:
            # decoder-internal page allocs charge this model's tenant
            kv_pool.default_owner = model_name
        self.mesh = data_mesh(mesh)
        self._n_dev = self.mesh.shape["data"] if self.mesh is not None \
            else 1
        self.batcher = MicroBatcher(max_batch, multiple=self._n_dev)
        self.metrics = metrics or ServingMetrics()
        self.breaker = breaker or CircuitBreaker()
        self.policy = policy or RetryPolicy.from_properties(
            prefix="bigdl.serving")
        self.generate_dtype = generate_dtype
        self._queue = _BoundedQueue(max_queue)
        self._batch_window_s = float(batch_window_s)
        self._default_deadline_s = default_deadline_s
        self._poll_s = 0.02

        self._model_lock = threading.Lock()
        self._params = model.param_tree()
        self._buffers = model.buffer_tree()
        self._canary_x = None  # last good classify batch (padded)

        self._feature_shape = None  # pinned by the first classify submit
        self._worker: Optional[threading.Thread] = None
        self._started = False
        self._draining = False
        self._hard_stop = False
        self._drained = threading.Event()
        self._preemption: Optional[PreemptionHandler] = None
        self._fwd = None
        # classify buckets whose compiled-forward cost was already
        # analyzed (one XLA cost-model lowering per bucket, ever)
        self._costed_buckets: set = set()

    # ------------------------------------------------------------ lifecycle
    def start(self, install_signal_handler: bool = False
              ) -> "InferenceServer":
        """Compile-cache the eval forward and start the worker.
        ``install_signal_handler=True`` additionally routes SIGTERM/
        SIGINT to a graceful drain (main thread only; off the main
        thread the process-wide ``request_preemption()`` flag still
        drains — PreemptionHandler's degrade contract)."""
        if self._started:
            raise RuntimeError("server already started")
        from ..optim.evaluator import _cached_eval_fwd
        from .compile_cache import maybe_set_compile_cache_dir

        # persisted compile cache (bigdl.serving.compileCache): a cold
        # autoscaled replica loads per-bucket executables instead of
        # recompiling them — best-effort, never fails a start
        maybe_set_compile_cache_dir()
        self.model.evaluate()
        self._fwd = _cached_eval_fwd(self.model, self.mesh)
        # on_request flips readiness the instant the signal lands (the
        # worker would only notice at its next batch boundary)
        signals = None if install_signal_handler else ()
        self._preemption = PreemptionHandler(
            **({} if signals is None else {"signals": signals}),
            on_request=self._note_drain)
        self._preemption.__enter__()
        self._started = True
        self._draining = False
        self._hard_stop = False
        self._drained.clear()
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="bigdl-serving-worker")
        self._worker.start()
        return self

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: stop admission, finish everything already
        admitted, then stop the worker.  Returns True when the worker
        exited within ``timeout``."""
        self._draining = True
        done = self._drained.wait(timeout) if self._worker else True
        if self._worker is not None:
            self._worker.join(timeout)
            done = done and not self._worker.is_alive()
        if self._preemption is not None:
            self._preemption.__exit__(None, None, None)
            self._preemption = None
        self._started = False
        return done

    def stop(self, timeout: Optional[float] = None) -> bool:
        """Hard shutdown: still-queued requests resolve CANCELLED (the
        in-flight batch, if any, completes first — the device step is
        not interruptible)."""
        self._hard_stop = True
        return self.drain(timeout)

    # ------------------------------------------------------------ health
    def healthy(self) -> bool:
        """Liveness: the worker thread is running."""
        return bool(self._started and self._worker
                    and self._worker.is_alive())

    def ready(self) -> bool:
        """Readiness: accepting requests with headroom — started, not
        draining, breaker not open, queue below its bound."""
        return (self.healthy() and not self._draining
                and not self._should_drain()
                and self.breaker.state != OPEN
                and len(self._queue) < self._queue.maxsize)

    def health(self) -> dict:
        out = {
            "healthy": self.healthy(),
            "ready": self.ready(),
            "draining": bool(self._draining or self._should_drain()),
            "queue_depth": len(self._queue),
            "breaker": self.breaker.snapshot(),
            "role": self.role,
        }
        if self.model_name is not None:
            out["model"] = self.model_name
            out["model_version"] = self.model_version
        if self.kv_pool is not None:
            out["kv"] = self.kv_pool.stats()
        return out

    def compile_stats(self) -> dict:
        """Compile accounting for the static-shape contract: the jit
        cache of the shared eval forward may hold at most one entry per
        (bucket, feature-shape) ever dispatched."""
        cache_size = None
        if self._fwd is not None and hasattr(self._fwd, "_cache_size"):
            cache_size = int(self._fwd._cache_size())
        return {
            "jit_cache_size": cache_size,
            "buckets_dispatched":
                sorted(self.batcher.buckets_dispatched),
        }

    # ------------------------------------------------------------ admission
    def _admit(self, req: Request) -> ServeFuture:
        now = time.monotonic()
        if not self._started or self._draining or self._should_drain():
            self._resolve(req, ServeResult(
                Status.UNAVAILABLE,
                error="server draining" if self._started
                else "server not started"))
            return req.future
        if req.expired(now):
            self._resolve(req, ServeResult(
                Status.DEADLINE_EXCEEDED, error="expired on arrival"))
            return req.future
        self.metrics.record_depth(len(self._queue))
        if not self._queue.try_put(req):
            # load shedding: reject fast, count it, never queue forever
            self._resolve(req, ServeResult(
                Status.OVERLOADED,
                error=f"queue full ({self._queue.maxsize})"))
        return req.future

    def _deadline(self, deadline_s: Optional[float],
                  now: float) -> Optional[float]:
        if deadline_s is None:
            deadline_s = self._default_deadline_s
        return None if deadline_s is None else now + float(deadline_s)

    def _fast_fail_expired(self, deadline: Optional[float],
                           now: float) -> Optional[ServeFuture]:
        """A request whose remaining budget is already <= 0 resolves
        DEADLINE_EXCEEDED right here — before admission, before the
        queue, before metrics see a depth sample.  The fleet router
        retries with the *remaining* deadline budget, so a dead budget
        arriving here is the common case under failover, and queueing
        it would waste a batch slot on an answer nobody is waiting
        for."""
        if deadline is None or deadline > now:
            return None
        fut = ServeFuture()
        result = ServeResult(Status.DEADLINE_EXCEEDED,
                             error="deadline budget exhausted before "
                                   "admission")
        self.metrics.record(result.status, 0.0, 0.0)
        fut._resolve(result)
        return fut

    @staticmethod
    def _parse_trace(trace):
        """Wire dict (or TraceContext) → TraceContext; malformed
        contexts degrade to untraced, never fail the request."""
        if trace is None:
            return None
        from ..telemetry.trace_context import TraceContext

        return TraceContext.from_wire(trace)

    def _trace(self, req: Request, name: str, category: str,
               start: float, duration: float, **args):
        """Record one request-phase span for a traced request (no-op
        without a sink or context — the untraced hot path pays one
        None check)."""
        if self.trace_sink is not None and req.trace is not None:
            self.trace_sink.record(req.trace, name, category, start,
                                   duration, **args)

    def submit(self, feature,
               deadline_s: Optional[float] = None,
               trace=None) -> ServeFuture:
        """One classification/regression request: ``feature`` is a
        single record (no batch dim); the result's ``output`` is the
        model's output row for it."""
        feature = np.asarray(feature)
        # shape-check at admission: one malformed request must fail ITS
        # caller synchronously, not poison whole batches (and trip the
        # breaker) once coalesced
        if self._feature_shape is None:
            self._feature_shape = feature.shape
        elif feature.shape != self._feature_shape:
            raise ValueError(
                f"feature shape {feature.shape} does not match this "
                f"server's pinned shape {self._feature_shape}")
        now = time.monotonic()
        deadline = self._deadline(deadline_s, now)
        fast = self._fast_fail_expired(deadline, now)
        if fast is not None:
            return fast
        return self._admit(Request(
            kind="classify", payload=feature,
            future=ServeFuture(), submitted_at=now, deadline=deadline,
            trace=self._parse_trace(trace)))

    def submit_generate(self, prompt_ids, max_new: int,
                        eos_id: Optional[int] = None,
                        pad_id: Optional[int] = None,
                        deadline_s: Optional[float] = None,
                        trace=None) -> ServeFuture:
        """One greedy-decode generation request; the result's
        ``output`` is the generated id row (``max_new`` tokens,
        eos-then-pad per ``models.generate``).  Requests are micro-
        batched with others sharing (prompt_len, max_new, eos, pad) —
        the compiled decode program's static signature."""
        prompt = np.asarray(prompt_ids, np.int32)
        if prompt.ndim != 1:
            raise ValueError(f"prompt_ids must be 1-D, got shape "
                             f"{prompt.shape}")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        now = time.monotonic()
        deadline = self._deadline(deadline_s, now)
        fast = self._fast_fail_expired(deadline, now)
        if fast is not None:
            return fast
        return self._admit(Request(
            kind="generate", payload=prompt, future=ServeFuture(),
            submitted_at=now, deadline=deadline,
            opts=(int(max_new), eos_id, pad_id),
            trace=self._parse_trace(trace)))

    def _require_pool(self, what: str):
        if self.kv_pool is None:
            raise RuntimeError(
                f"{what} requires a kv_pool (paged serving); this "
                f"server has none")

    def submit_prefill(self, prompt_ids,
                       deadline_s: Optional[float] = None,
                       trace=None) -> ServeFuture:
        """Prefill-only dispatch for the disaggregated path: run the
        prompt pass, produce the first token, and return a crc-sealed
        KV handoff blob (``result.output``) a decode-pool replica can
        continue from.  The prefill replica's pages are released as
        soon as the blob is exported — prefill holds pages only for
        the duration of the prompt pass."""
        self._require_pool("submit_prefill")
        prompt = np.asarray(prompt_ids, np.int32)
        if prompt.ndim != 1:
            raise ValueError(f"prompt_ids must be 1-D, got shape "
                             f"{prompt.shape}")
        now = time.monotonic()
        deadline = self._deadline(deadline_s, now)
        fast = self._fast_fail_expired(deadline, now)
        if fast is not None:
            return fast
        return self._admit(Request(
            kind="prefill", payload=prompt, future=ServeFuture(),
            submitted_at=now, deadline=deadline,
            trace=self._parse_trace(trace)))

    def submit_decode(self, handoff: bytes, max_new: int,
                      eos_id: Optional[int] = None,
                      pad_id: Optional[int] = None,
                      deadline_s: Optional[float] = None,
                      trace=None) -> ServeFuture:
        """Decode-only dispatch for the disaggregated path: verify
        ``handoff`` (crc32c + geometry), import its pages into this
        replica's pool, and stream the remaining ``max_new - 1``
        tokens (the first one was produced by prefill and rides the
        handoff).  The result's ``output`` holds those remaining
        tokens; a corrupt blob resolves INTERNAL_ERROR, a full pool
        sheds OVERLOADED."""
        self._require_pool("submit_decode")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        now = time.monotonic()
        deadline = self._deadline(deadline_s, now)
        fast = self._fast_fail_expired(deadline, now)
        if fast is not None:
            return fast
        ctx = self._parse_trace(trace)
        if ctx is None:
            # belt-and-braces: the context also rides the sealed blob
            # itself (handoff extras), so a decode dispatched outside
            # the router still joins its trace
            from .pools import peek_handoff_trace

            ctx = self._parse_trace(peek_handoff_trace(handoff))
        return self._admit(Request(
            kind="decode", payload=handoff, future=ServeFuture(),
            submitted_at=now, deadline=deadline,
            opts=(int(max_new), eos_id, pad_id), trace=ctx))

    # ------------------------------------------------------------ hot swap
    def swap_params(self, params: Any = None, path: Optional[str] = None,
                    buffers: Any = None,
                    outcome: str = "installed",
                    version: Optional[str] = None) -> bool:
        """Install new params atomically between batches.

        ``path`` loads through the crc32c-verified checkpoint path
        (:func:`.swap.load_verified_params`); corrupt files quarantine
        and the swap is refused.  Candidates then face a canary batch
        on the live compiled forward (the last good batch's input; a
        params-finiteness check before any traffic has flowed) — a
        canary that raises or emits non-finite outputs raises
        :class:`SwapRejected` and the server keeps serving the prior
        params.  Returns True on install.

        ``outcome`` names the success leg of the swap counter —
        ``"installed"`` for a deploy, ``"rolled_back"`` when a fleet
        rollback re-installs captured prior params (the rollback rides
        this exact verified canary path; only its accounting differs).
        """
        if (params is None) == (path is None):
            raise ValueError("pass exactly one of params/path")
        t_swap = time.monotonic()

        def note_swap(outcome: str):
            # traced requests overlapping this window see it as a
            # swap_window span in their stitched timeline
            if self.trace_sink is not None:
                self.trace_sink.record_swap_window(
                    t_swap, time.monotonic() - t_swap, outcome)

        try:
            if path is not None:
                params = load_verified_params(path)
            with self._model_lock:
                canary = self._canary_x
                bufs = buffers if buffers is not None else self._buffers
            # the canary rides the same injection point as live batches
            # (scoped by replica name), so a fleet test can fail ONE
            # replica's canary deterministically mid-rolling-deploy
            _faults.check_serving_fault(self.name)
            if canary is not None and self._fwd is not None:
                out = self._fwd(params, bufs, canary)
                if not bool(tree_finite(out)):
                    raise SwapRejected(
                        "canary batch produced non-finite outputs")
            elif not bool(tree_finite(params)):
                raise SwapRejected("candidate params are non-finite")
        except SwapRejected:
            self.metrics.record_swap(installed=False)
            note_swap("rejected")
            raise
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            self.metrics.record_swap(installed=False)
            note_swap("rejected")
            raise SwapRejected(f"canary batch failed "
                               f"({type(e).__name__}: {e})")
        with self._model_lock:
            self._params = params
            if buffers is not None:
                self._buffers = buffers
        if version is not None:
            # the advertised (model, version) pair tracks the install —
            # a rollback passes the prior version back in
            self.model_version = str(version)
        self.metrics.record_swap(outcome=outcome)
        note_swap(outcome)
        log.info("serving params hot-swapped%s%s",
                 f" from {path}" if path else "",
                 " (rollback)" if outcome == "rolled_back" else "")
        return True

    def current_params(self):
        """The (params, buffers) pair currently serving — what a fleet
        rollback re-installs on the already-swapped replicas when a
        later replica rejects the deploy."""
        with self._model_lock:
            return self._params, self._buffers

    # ------------------------------------------------------------ worker
    def _note_drain(self):
        self._draining = True

    def _should_drain(self) -> bool:
        return self._preemption is not None \
            and self._preemption.should_stop

    def _tenant_of(self, req: Request) -> Optional[str]:
        """The tenant a request's phase/latency samples attribute to:
        the trace's tenant when the router stamped one, else this
        replica's model (one model ≈ one tenant), else None (untagged
        single-model fleets pay no tenant series)."""
        tenant = getattr(req.trace, "tenant", None) \
            if req.trace is not None else None
        return tenant if tenant is not None else self.model_name

    def _resolve(self, req: Request, result: ServeResult):
        now = time.monotonic()
        result.latency_s = now - req.submitted_at
        self.metrics.record(result.status, result.latency_s,
                            result.queued_s,
                            tenant=self._tenant_of(req))
        if req.trace is not None:
            result.trace_id = req.trace.trace_id
            if result.status is not Status.OK:
                # typed failure span: the stitched trace shows WHAT
                # failed on WHICH replica, not just a missing interval
                self._trace(req, f"fail:{req.kind}", "error",
                            req.submitted_at, result.latency_s,
                            status=result.status.value,
                            error=(result.error or "")[:200])
            if self.trace_sink is not None:
                self.trace_sink.finish(req.trace)
        req.future._resolve(result)

    def _gather(self, limit: int) -> list:
        """Block briefly for the first request, then coalesce whatever
        arrives inside the batch window (continuous micro-batching:
        the window bounds added latency, the ladder bounds compiles)."""
        first = self._queue.get(timeout=self._poll_s)
        if first is None:
            return []
        batch = [first]
        window_end = time.monotonic() + self._batch_window_s
        while len(batch) < limit:
            remaining = window_end - time.monotonic()
            nxt = self._queue.get_nowait() if remaining <= 0 else \
                self._queue.get(timeout=remaining)
            if nxt is None:
                break
            batch.append(nxt)
        return batch

    def _run(self):
        try:
            while True:
                if self._hard_stop:
                    break
                if self._draining or self._should_drain():
                    self._draining = True
                    if len(self._queue) == 0:
                        break
                batch = self._gather(self.batcher.max_batch)
                if not batch:
                    continue
                # expired-in-queue requests resolve typed, pre-device
                now = time.monotonic()
                live = []
                for r in batch:
                    if r.expired(now):
                        self._resolve(r, ServeResult(
                            Status.DEADLINE_EXCEEDED,
                            error="deadline expired in queue",
                            queued_s=now - r.submitted_at))
                    else:
                        live.append(r)
                if not live:
                    continue
                verdict = self.breaker.acquire()
                if verdict == REJECT:
                    for r in live:
                        self._resolve(r, ServeResult(
                            Status.UNAVAILABLE,
                            error="circuit breaker open"))
                    continue
                if verdict == PROBE and len(live) > 1:
                    # half-open admits ONE request; the rest requeue
                    # (ahead of newer arrivals) pending the verdict
                    self._queue.put_front(live[1:])
                    live = live[:1]
                for kind, group in self._group(live):
                    self._run_group(kind, group)
        finally:
            # hard stop (or a worker crash — nothing may hang): every
            # queued request resolves
            leftover = self._queue.drain_all()
            for r in leftover:
                self._resolve(r, ServeResult(
                    Status.CANCELLED, error="server stopped"))
            self._drained.set()

    @staticmethod
    def _group(reqs):
        """Split a gathered batch into runnable groups: classify
        requests coalesce together; generate requests group by their
        compiled signature (prompt_len, opts); the paged kinds
        (prefill / decode) each form one group — they are driven
        per-request by the continuous paged loop, which interleaves
        them regardless of shape."""
        groups: dict = {}
        for r in reqs:
            if r.kind == "classify":
                key = ("classify",)
            elif r.kind in ("prefill", "decode"):
                key = (r.kind,)
            else:
                key = ("generate", r.payload.shape[0], r.opts)
            groups.setdefault(key, []).append(r)
        for key, group in groups.items():
            yield key[0], group

    def _run_group(self, kind: str, reqs: list):
        if kind in ("prefill", "decode") or (
                kind == "generate" and self.kv_pool is not None):
            return self._run_paged_group(kind, reqs)
        t_batch = time.monotonic()
        queued = [t_batch - r.submitted_at for r in reqs]
        for r, q in zip(reqs, queued):
            self._trace(r, "admission_queue", "queue", r.submitted_at,
                        q)
        with self._model_lock:
            params, buffers = self._params, self._buffers
        try:
            _faults.check_serving_fault(self.name)
            if kind == "classify":
                x, bucket = self.batcher.coalesce(
                    [r.payload for r in reqs])
                xj = jnp.asarray(x)
                t_exec = time.monotonic()
                self._account_bucket_cost(bucket, params, buffers, xj)
                out = self._fwd(params, buffers, xj)
                # host transfer doubles as the execution barrier —
                # device-side failures surface here, inside the try
                out_np = jax.tree_util.tree_map(np.asarray, out)
                with self._model_lock:
                    self._canary_x = xj  # freshest known-good canary
            else:
                t_exec = time.monotonic()
                out_np, bucket = self._run_generate(params, reqs)
            t_done = time.monotonic()
            for r in reqs:
                self._trace(r, "batch_form", "batch", t_batch,
                            t_exec - t_batch, batch=len(reqs),
                            bucket=bucket)
                self._trace(r, f"execute:{kind}", "execute", t_exec,
                            t_done - t_exec, bucket=bucket,
                            batch=len(reqs))
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            fatal = self.policy.classify(e) == "fatal"
            self.breaker.record_failure(fatal=fatal)
            err = f"{type(e).__name__}: {e}"
            log.warning("serving step failed (%s, %s): %s",
                        "fatal" if fatal else "retryable",
                        self.breaker.state, err)
            for r, q in zip(reqs, queued):
                self._resolve(r, ServeResult(
                    Status.INTERNAL_ERROR, error=err, queued_s=q))
            return
        self.breaker.record_success()
        self.metrics.record_batch(len(reqs), bucket)
        for i, (r, q) in enumerate(zip(reqs, queued)):
            self._resolve(r, ServeResult(
                Status.OK, output=jax.tree_util.tree_map(
                    lambda a: a[i], out_np),
                queued_s=q, bucket=bucket))

    def _account_bucket_cost(self, bucket: int, params, buffers, xj):
        """Per-bucket FLOP accounting: one XLA cost-model lowering the
        first time each classify bucket dispatches, installed into the
        metrics so `snapshot()` can report goodput-per-chip (served
        model-FLOP/s over the chip peak).  Best-effort: cost analysis
        failing must never fail the batch."""
        key = (int(bucket), tuple(xj.shape[1:]))
        if key in self._costed_buckets or self._fwd is None:
            return
        self._costed_buckets.add(key)
        try:
            from ..telemetry.perf import cost_from_analysis

            lowered = self._fwd.lower(params, buffers, xj)
            cost = cost_from_analysis(lowered.cost_analysis())
            if cost.flops > 0:
                self.metrics.record_bucket_cost(bucket, cost.flops)
        except Exception as e:  # non-lowerable fwd, analysis quirks
            log.debug("serving: bucket %d cost analysis skipped: %s",
                      bucket, e)

    # ------------------------------------------------------- paged decode
    def _import_handoff(self, decoder, blob):
        """Verify a KV handoff blob and materialize it as a live
        PagedSequence in THIS replica's pool (crc + geometry checked;
        pages leased here, scattered from the blob)."""
        from ..models.generate import PagedSequence
        from .pools import HandoffCorrupt, deserialize_handoff

        h = deserialize_handoff(blob)
        pool = self.kv_pool
        geometry = (h["layers"], h["num_kv_heads"], h["page_size"],
                    h["head_dim"])
        expect = (pool.layers, pool.num_kv_heads, pool.page_size,
                  pool.head_dim)
        if geometry != expect:
            raise HandoffCorrupt(
                f"handoff geometry {geometry} does not match this "
                f"pool {expect}")
        lease = pool.alloc(int(h["k_pages"].shape[0]))
        try:
            pool.write_pages(lease.pages, h["k_pages"], h["v_pages"])
        except BaseException:
            lease.release()
            raise
        return PagedSequence(lease, pos=int(h["pos"]),
                             last=int(h["first_token"]),
                             prompt_len=int(h["pos"]))

    def _run_paged_group(self, kind: str, reqs: list):
        """Continuous paged generation: one host loop interleaves
        every in-flight sequence a token at a time, so a long decode
        never blocks a short one and a kill/drain/deadline mid-stream
        resolves typed WITH its pages released.  Outcomes:

        * pool exhaustion (at start or on a mid-decode page
          extension) → typed OVERLOADED shed;
        * a corrupt handoff → INTERNAL_ERROR (refused before any K/V
          byte is trusted);
        * deadline mid-decode → DEADLINE_EXCEEDED;
        * hard stop mid-decode → CANCELLED;
        * everything else finishes OK with the unpaged path's exact
          eos-then-pad emission convention.
        """
        from ..models.generate import (_eos_pad, cached_paged_decoder)
        from .kvpool import PoolExhausted
        from .pools import HandoffCorrupt, serialize_handoff

        pool = self.kv_pool
        decoder = cached_paged_decoder(
            self.model, pool, compute_dtype=self.generate_dtype,
            page_window=self.kv_page_window,
            page_globals=self.kv_page_globals)
        with self._model_lock:
            params = self._params

        def fail(req, queued_s, exc, status=Status.INTERNAL_ERROR):
            fatal = self.policy.classify(exc) == "fatal"
            self.breaker.record_failure(fatal=fatal)
            err = f"{type(exc).__name__}: {exc}"
            log.warning("paged serving %s failed (%s, %s): %s",
                        req.kind, "fatal" if fatal else "retryable",
                        self.breaker.state, err)
            self._resolve(req, ServeResult(status, error=err,
                                           queued_s=queued_s))

        live = []
        for req in reqs:
            now = time.monotonic()
            queued_s = now - req.submitted_at
            self._trace(req, "admission_queue", "queue",
                        req.submitted_at, queued_s)
            try:
                _faults.check_serving_fault(self.name)
                if req.kind == "decode":
                    max_new, eos_id, pad_id = req.opts
                    eos, pad = map(int, _eos_pad(self.model, eos_id,
                                                 pad_id))
                    t_g = time.monotonic()
                    seq = self._import_handoff(decoder, req.payload)
                    self._trace(req, "kv_import", "kv_gather", t_g,
                                time.monotonic() - t_g,
                                pages=len(seq.lease.pages))
                    # the first token rode the handoff: this dispatch
                    # owes the remaining max_new - 1
                    entry = {
                        "req": req, "seq": seq, "toks": [],
                        "target": max_new - 1, "eos": eos, "pad": pad,
                        "queued_s": queued_s,
                        "done": eos > 0 and seq.last == eos,
                        "t_decode": time.monotonic(), "steps": 0,
                    }
                    live.append(entry)
                else:
                    t0 = time.monotonic()
                    seq = decoder.start(params, req.payload)
                    prefill_s = time.monotonic() - t0
                    self.metrics.record_phase("prefill", prefill_s,
                                              tenant=self._tenant_of(req))
                    self.metrics.record_ttft(
                        time.monotonic() - req.submitted_at,
                        tenant=self._tenant_of(req))
                    self._trace(req, "prefill", "prefill", t0,
                                prefill_s,
                                prompt_len=int(req.payload.shape[0]),
                                pages=len(seq.lease.pages))
                    if req.kind == "prefill":
                        t_g = time.monotonic()
                        k_pages, v_pages = pool.read_pages(
                            seq.lease.pages)
                        extras = None
                        if req.trace is not None:
                            from ..telemetry.trace_context import \
                                TRACE_WIRE_KEY

                            # the context rides the sealed blob: the
                            # decode replica joins the trace even when
                            # the dispatch path loses the kwarg
                            extras = {TRACE_WIRE_KEY:
                                      req.trace.to_wire()}
                        blob = serialize_handoff(
                            k_pages, v_pages, seq.last, seq.pos,
                            pool.page_size, extras=extras)
                        self._trace(req, "kv_export", "kv_gather", t_g,
                                    time.monotonic() - t_g,
                                    pages=len(seq.lease.pages),
                                    blob_bytes=len(blob))
                        seq.release()
                        self.breaker.record_success()
                        self.metrics.record_batch(1, 1)
                        self._resolve(req, ServeResult(
                            Status.OK, output=blob,
                            queued_s=queued_s, bucket=1))
                    else:  # paged full generate
                        max_new, eos_id, pad_id = req.opts
                        eos, pad = map(int, _eos_pad(
                            self.model, eos_id, pad_id))
                        live.append({
                            "req": req, "seq": seq,
                            "toks": [seq.last], "target": max_new,
                            "eos": eos, "pad": pad,
                            "queued_s": queued_s,
                            "done": eos > 0 and seq.last == eos,
                            "t_decode": time.monotonic(), "steps": 0,
                        })
            except PoolExhausted as e:
                # admission control, not failure: shed typed (the
                # breaker must not trip on a full pool)
                self._resolve(req, ServeResult(
                    Status.OVERLOADED, error=f"KV pool exhausted: {e}",
                    queued_s=queued_s))
            except (KeyboardInterrupt, SystemExit):
                raise
            except HandoffCorrupt as e:
                fail(req, queued_s, e)
            except Exception as e:
                fail(req, queued_s, e)
        self.metrics.set_kv_pool(pool.stats())

        def finish(entry):
            seq, req = entry["seq"], entry["req"]
            seq.release()
            decode_s = time.monotonic() - entry["t_decode"]
            self.metrics.record_phase("decode", decode_s,
                                      tenant=self._tenant_of(req))
            if entry["steps"]:
                self.metrics.record_tpot(decode_s / entry["steps"],
                                         tenant=self._tenant_of(req))
            self._trace(req, "decode", "decode", entry["t_decode"],
                        decode_s, steps=entry["steps"],
                        tokens=len(entry["toks"]))
            self.breaker.record_success()
            self.metrics.record_batch(1, 1)
            self._resolve(req, ServeResult(
                Status.OK,
                output=np.asarray(entry["toks"], np.int32),
                queued_s=entry["queued_s"], bucket=1))

        def abort(entry, result: ServeResult):
            entry["seq"].release()
            decode_s = time.monotonic() - entry["t_decode"]
            self._trace(entry["req"], "decode", "decode",
                        entry["t_decode"], decode_s,
                        steps=entry["steps"], aborted=True)
            result.queued_s = entry["queued_s"]
            self._resolve(entry["req"], result)

        # round-robin continuous decode: every live sequence advances
        # one token per round, so a long decode never starves a short
        # one and page pressure tracks actual lengths
        while live:
            if self._hard_stop:
                for entry in live:
                    abort(entry, ServeResult(
                        Status.CANCELLED,
                        error="server stopped mid-decode"))
                break
            nxt = []
            for entry in live:
                req, seq = entry["req"], entry["seq"]
                if len(entry["toks"]) >= entry["target"]:
                    finish(entry)
                    continue
                if entry["done"]:
                    # eos already emitted: pad-fill (the unpaged
                    # path's static-shape convention) without burning
                    # device steps
                    entry["toks"].extend(
                        [entry["pad"]]
                        * (entry["target"] - len(entry["toks"])))
                    nxt.append(entry)
                    continue
                if req.expired(time.monotonic()):
                    abort(entry, ServeResult(
                        Status.DEADLINE_EXCEEDED,
                        error="deadline expired mid-decode"))
                    continue
                try:
                    _faults.check_serving_fault(self.name)
                    tok = decoder.step(params, seq)
                except PoolExhausted as e:
                    abort(entry, ServeResult(
                        Status.OVERLOADED,
                        error=f"KV pool exhausted mid-decode: {e}"))
                    continue
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    entry["seq"].release()
                    fail(req, entry["queued_s"], e)
                    continue
                entry["steps"] += 1
                entry["toks"].append(tok)
                if entry["eos"] > 0 and tok == entry["eos"]:
                    entry["done"] = True
                nxt.append(entry)
            live = nxt
        self.metrics.set_kv_pool(pool.stats())

    def _run_generate(self, params, reqs):
        """One compiled decode program per (bucket, prompt_len,
        max_new): prompts stack along the batch dim and pad up to the
        bucket by repeating the last row (same ladder as classify, so
        generation traffic can't recompile per batch count either)."""
        from ..models.generate import cached_generate

        max_new, eos_id, pad_id = reqs[0].opts
        prompts = np.stack([r.payload for r in reqs])
        n = prompts.shape[0]
        bucket = self.batcher.bucket_for(n)
        if n < bucket:
            prompts = np.concatenate(
                [prompts, np.repeat(prompts[-1:], bucket - n, axis=0)],
                axis=0)
        self.batcher.buckets_dispatched.add(
            ("gen", bucket, prompts.shape[1], max_new))
        gen = cached_generate(self.model,
                              compute_dtype=self.generate_dtype)
        ids = gen(params, prompts, max_new, eos_id=eos_id,
                  pad_id=pad_id)
        out = np.asarray(ids)[:, prompts.shape[1]:]  # generated tail
        return out, bucket
