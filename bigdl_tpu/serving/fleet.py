"""Replica fleet: membership, health publishing, rolling verified deploys.

This is the integration layer the ROADMAP's planet-scale-serving item
asks for: N hardened :class:`~.server.InferenceServer` replicas become
ONE serving surface with the same fault story training got in the
robustness arc.

* :class:`ReplicaAgent` — one per replica: heartbeats + a health
  snapshot (``ready``, queue depth, breaker state, p99) published
  through the **elastic KV transport**
  (:class:`~bigdl_tpu.resilience.elastic.ElasticCoordinator` — the
  identical membership protocol training gangs run, incarnation
  numbers included).  The agent is also the fleet chaos surface:
  :func:`~bigdl_tpu.resilience.faults.kill_replica` hard-stops its
  server at the next pump, :func:`~bigdl_tpu.resilience.faults
  .partition_kv` silences its publishing.
* :class:`~.router.FleetRouter` — maintained by the fleet's pump
  loop: health-aware least-loaded dispatch, deadline-budget failover
  retries, optional p99-derived hedging, per-replica breakers, and
  membership ejection/re-admission.
* **Rolling verified deploys** — :meth:`ServingFleet.rolling_swap`
  rolls new params through the fleet ONE replica at a time, each
  through the existing crc32c-verified load + canary
  (:meth:`~.server.InferenceServer.swap_params`).  The first
  :class:`~.swap.SwapRejected` halts the deploy and rolls every
  already-swapped replica back to its prior params, and the deploy
  never proceeds while the rest of the fleet is below the configured
  **ready quorum** — a poisoned artifact can never serve a user
  request, fleet-wide.

The fleet's merged telemetry rides the existing cross-host fold
(:func:`~bigdl_tpu.telemetry.aggregate.merge_metrics`): per-replica
registries sum into one cluster view, ``write_snapshots`` drops the
per-replica payloads ``tools/run_report.py`` renders, and
:meth:`goodput_per_chip` reports served model-FLOP/s per chip — the
serving analogue of cluster MFU.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from typing import Callable, Dict, Optional

from ..resilience import faults as _faults
from ..resilience.elastic import ElasticCoordinator, InMemoryKV
from ..telemetry.events import record_change as _record_change
from .metrics import ServingMetrics
from .router import FleetRouter, HEALTH_PREFIX
from .server import InferenceServer
from .swap import DeployInFlight, SwapRejected, load_verified_params

log = logging.getLogger("bigdl_tpu")


class FleetQuorumError(RuntimeError):
    """A rolling deploy (or other fleet-wide operation) would drop the
    ready replica count below the configured quorum — refused."""


class ReplicaAgent:
    """The publisher side of fleet membership for ONE replica.

    ``pump()`` — called by the fleet's heartbeat loop (or directly by
    tests) — consults the fleet fault injectors, acks any new
    incarnation, heartbeats through the coordinator, and publishes the
    health snapshot the router routes on.  A killed agent stays
    silent; a partitioned one stays alive but invisible.
    """

    def __init__(self, replica_id: str, server: InferenceServer,
                 transport, heartbeat_timeout: float = 2.0,
                 clock: Callable[[], float] = time.monotonic):
        self.replica_id = str(replica_id)
        self.server = server
        self.coordinator = ElasticCoordinator(
            self.replica_id, transport,
            heartbeat_timeout=heartbeat_timeout, clock=clock)
        self._clock = clock
        self._beats = 0
        self._acked: Optional[int] = None
        self.killed = False

    def health_snapshot(self) -> dict:
        h = self.server.health()
        m = self.server.metrics
        snap = {
            "replica": self.replica_id,
            "ready": h["ready"],
            "healthy": h["healthy"],
            "draining": h["draining"],
            "queue_depth": h["queue_depth"],
            "breaker_state": h["breaker"]["state"],
            "role": h.get("role", "both"),
            # multi-tenant fleets: which (model, version) this replica
            # advertises — the router routes model-addressed requests
            # over the advertising subset only
            "model": h.get("model"),
            "model_version": h.get("model_version"),
            "p99_s": m._lat.quantile(0.99),
            "served_ok": int(m.counts["ok"]),
            # shed/total ride along so the autoscaler can derive a
            # per-pool shed RATE from published signals alone
            "shed_total": int(m.counts["overloaded"]),
            "requests_total": int(sum(m.counts.values())),
            "ts": self._clock(),
        }
        kv = h.get("kv")
        if kv:
            snap["kv_occupancy"] = kv["occupancy"]
            snap["kv_free_pages"] = kv["free_pages"]
            snap["kv_pages"] = kv["num_pages"]
            # keep the replica's pool gauges fresh at heartbeat cadence
            m.set_kv_pool(kv)
        return snap

    def pump(self):
        """One heartbeat round.  No-op once killed; silent while
        partitioned (beats age out and the router presumes us dead —
        exactly a dead training host's signature)."""
        if self.killed:
            return
        fault = _faults.check_fleet_fault(self.replica_id)
        if fault == "kill":
            self.kill()
            return
        if fault == "partition":
            return
        c = self.coordinator
        n, members = c.membership()
        if n != self._acked:
            c.ack(n)
            self._acked = n
        self._beats += 1
        # a healed partition (or an ejected replica coming back) beats
        # with rejoin=True until the membership includes it again
        c.heartbeat(step=self._beats,
                    rejoin=self.replica_id not in members)
        snap = self.health_snapshot()
        snap["incarnation"] = n
        c.transport.put(HEALTH_PREFIX + self.replica_id,
                        json.dumps(snap))

    def kill(self):
        """Injected replica death: hard-stop the server (queued
        requests resolve CANCELLED — typed, never silent) and stop
        heartbeating."""
        self.killed = True
        log.warning("fleet: replica %s killed", self.replica_id)
        self.server.stop(timeout=0.5)


class ServingFleet:
    """N replicas + agents + router behind one lifecycle.

    Build one either from pre-constructed servers
    (``ServingFleet(servers={...})``) or with :meth:`build`, which
    stamps out ``n_replicas`` named servers over one model.  ``start``
    launches every server, runs one synchronous pump round (so the
    router has a live view before the first request), then starts the
    background pump thread.
    """

    def __init__(self, servers: Dict[str, InferenceServer],
                 transport=None, *, heartbeat_timeout: float = 2.0,
                 pump_interval_s: Optional[float] = None,
                 ready_quorum: Optional[int] = None,
                 router_kw: Optional[dict] = None,
                 tracing: bool = False,
                 trace_kw: Optional[dict] = None,
                 health: bool = False,
                 health_kw: Optional[dict] = None,
                 clock: Callable[[], float] = time.monotonic):
        if not servers:
            raise ValueError("a fleet needs at least one replica")
        self.transport = transport if transport is not None \
            else InMemoryKV()
        self.servers = dict(servers)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.pump_interval_s = (heartbeat_timeout / 4.0
                                if pump_interval_s is None
                                else float(pump_interval_s))
        # quorum default: strict majority of the configured fleet
        self.ready_quorum = (len(self.servers) // 2 + 1
                             if ready_quorum is None
                             else int(ready_quorum))
        self._clock = clock
        self.agents = {
            rid: ReplicaAgent(rid, srv, self.transport,
                              heartbeat_timeout=heartbeat_timeout,
                              clock=clock)
            for rid, srv in self.servers.items()}
        coordinator = ElasticCoordinator(
            "fleet-router", self.transport,
            heartbeat_timeout=heartbeat_timeout, clock=clock)
        coordinator.bootstrap(sorted(self.servers))
        router_kw = dict(router_kw or {})
        router_kw.setdefault("clock", clock)
        # distributed request tracing: one router-side RequestTracer
        # (context minting, tail sampling, stitching) + one
        # ReplicaTraceSink bound into every replica, publishing
        # fragments under trc/<incarnation>/<trace_id>/<host> on the
        # SAME KV transport membership rides
        if tracing and "tracing" not in router_kw:
            from ..telemetry.trace_context import TailSampler
            from .request_trace import RequestTracer

            trace_kw = dict(trace_kw or {})
            sampler = trace_kw.pop("sampler", None) or TailSampler(
                **{k: trace_kw.pop(k) for k in
                   ("keep_per_s", "burst", "ok_prob")
                   if k in trace_kw})
            tracer = RequestTracer(
                transport=self.transport,
                incarnation_of=lambda c=coordinator: c.membership()[0],
                sampler=sampler, clock=clock, **trace_kw)
            # publish-on-keep: replica fragments stay buffered until
            # the router's TAIL decision keeps the trace — dropped
            # traces never touch the transport (the <=3% overhead
            # budget), errors/hedges/retries always publish
            tracer.on_keep = self._publish_kept_trace
            router_kw["tracing"] = tracer
            for rid, srv in self.servers.items():
                if srv.trace_sink is None:
                    srv.trace_sink = self._make_sink(rid)
        self.router = FleetRouter(self.servers, coordinator,
                                  **router_kw)
        # per-replica SLO health (serving/health.py): each pump round
        # evaluates the per-replica rule pack over published health
        # and marks breaching replicas degraded on the router —
        # answering-but-answering-badly replicas leave rotation
        # through the same eject machinery silence does
        self.health_monitor = None
        if health:
            from .health import FleetHealthMonitor

            self.health_monitor = FleetHealthMonitor(
                self, clock=clock, **(health_kw or {}))
        self.deploys = 0
        self.deploy_rollbacks = 0
        # deploy-in-flight mutual exclusion, PER REPLICA: a roll
        # acquires (non-blocking, sorted — no deadlock) the lock of
        # every replica it will touch, so two model-scoped deploys on
        # disjoint replica sets proceed concurrently while any overlap
        # — including two fleet-wide rolls — is refused typed
        # (DeployInFlight), never queued, before any replica is touched
        self._deploy_table_lock = threading.Lock()
        self._deploy_locks: Dict[str, threading.Lock] = {}
        # the last completed roll per deploy scope (model name, or
        # None for a fleet-wide roll): [(rid, prior, prior_version)]
        # — what an alert-driven rollback_last_deploy() re-installs
        self._last_deploy: Dict[Optional[str], list] = {}
        self._pump_thread: Optional[threading.Thread] = None
        self._stop_pump = threading.Event()

    def _make_sink(self, rid: str):
        """One replica's trace sink, incarnation-stamped by its agent
        (fragments published under a dead membership still stitch —
        the reader scans across incarnations).  Lazy publishing: the
        router's keep decision pulls the fragment."""
        from .request_trace import ReplicaTraceSink

        agent = self.agents.get(rid)
        return ReplicaTraceSink(
            rid, transport=self.transport,
            incarnation_of=(lambda a=agent: (a._acked or 0))
            if agent is not None else None,
            eager_publish=False, clock=self._clock)

    def _publish_kept_trace(self, trace_id: str):
        for srv in list(self.servers.values()):
            sink = getattr(srv, "trace_sink", None)
            if sink is not None:
                sink.publish_trace(trace_id)

    @property
    def tracing(self):
        """The router-side RequestTracer (None when tracing is off)."""
        return self.router.tracing

    def kept_traces(self):
        return self.router.tracing.kept_traces() \
            if self.router.tracing is not None else []

    def stitch_trace(self, trace_id: str, skew=None):
        """One kept request's cross-replica Perfetto timeline (replica
        sinks flushed first so freshly resolved fragments are
        visible)."""
        if self.router.tracing is None:
            return None
        sinks = [srv.trace_sink for srv in self.servers.values()
                 if getattr(srv, "trace_sink", None) is not None]
        return self.router.tracing.stitch(trace_id, skew=skew,
                                          flush_sinks=sinks)

    @classmethod
    def build(cls, model, n_replicas: int = 4, transport=None,
              server_kw: Optional[dict] = None, roles=None,
              kv_pages: Optional[int] = None, kv_page_size: int = 16,
              **fleet_kw) -> "ServingFleet":
        """Stamp out ``n_replicas`` named servers (``r0``…) over one
        model.  Each replica pins its own param copy at start, so a
        per-replica swap/rollback never bleeds across replicas.

        ``roles`` (a sequence per index or dict per replica id) builds
        a disaggregated fleet — e.g. ``roles=("prefill", "decode",
        "decode")``; ``kv_pages`` gives every replica its OWN
        ``kv_page_size``-paged KV pool (required for non-``both``
        roles; with role ``both`` it switches generation to the paged
        path)."""
        servers = {}
        for i in range(int(n_replicas)):
            rid = f"r{i}"
            kw = dict(server_kw or {})
            if roles is not None:
                kw["role"] = roles[rid] if isinstance(roles, dict) \
                    else roles[i]
            if kv_pages:
                from .kvpool import KVPagePool

                kw["kv_pool"] = KVPagePool.for_model(
                    model, kv_pages, page_size=kv_page_size)
            servers[rid] = InferenceServer(model, name=rid, **kw)
        return cls(servers, transport, **fleet_kw)

    @classmethod
    def build_multi(cls, models: Dict[str, object],
                    n_replicas_each: int = 2, transport=None,
                    server_kw: Optional[dict] = None,
                    versions: Optional[Dict[str, str]] = None,
                    quotas: Optional[Dict[str, float]] = None,
                    admission_capacity: Optional[int] = None,
                    deadline_budgets: Optional[Dict[str, float]] = None,
                    kv_pages: Optional[int] = None,
                    kv_page_size: int = 16,
                    **fleet_kw) -> "ServingFleet":
        """Stamp out a multi-tenant fleet: ``n_replicas_each`` replicas
        per model (named ``<model>-r<i>``), each advertising its
        (model, version) through the health snapshot the router routes
        on, behind one pre-wired
        :class:`~.registry.ModelRegistry` and
        :class:`~.registry.AdmissionController`.

        ``quotas`` are per-tenant admission weights (default: equal
        weight per model), ``admission_capacity`` the fleet-wide
        inflight ceiling the weights slice (default: 4 × replicas),
        ``deadline_budgets`` optional per-tenant deadline ceilings.
        ``kv_pages`` gives every replica its own paged pool whose
        ``default_owner`` is the replica's model, so decoder-internal
        page allocations are charged to the right tenant."""
        from .registry import AdmissionController, ModelRegistry

        registry = ModelRegistry()
        servers: Dict[str, InferenceServer] = {}
        for model_name in sorted(models):
            model = models[model_name]
            version = (versions or {}).get(model_name, "v1")
            registry.register(model_name, version)
            for i in range(int(n_replicas_each)):
                rid = f"{model_name}-r{i}"
                kw = dict(server_kw or {})
                if kv_pages:
                    from .kvpool import KVPagePool

                    kw["kv_pool"] = KVPagePool.for_model(
                        model, kv_pages, page_size=kv_page_size)
                servers[rid] = InferenceServer(
                    model, name=rid, model_name=model_name,
                    model_version=version, **kw)
        admission = AdmissionController(
            admission_capacity if admission_capacity is not None
            else 4 * len(servers),
            quotas=quotas if quotas is not None
            else {m: 1.0 for m in models},
            deadline_budgets=deadline_budgets)
        router_kw = dict(fleet_kw.pop("router_kw", None) or {})
        router_kw.setdefault("model_registry", registry)
        router_kw.setdefault("admission", admission)
        return cls(servers, transport, router_kw=router_kw, **fleet_kw)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ServingFleet":
        for srv in self.servers.values():
            if not srv.healthy():
                srv.start()
        self.pump_once()
        if self.pump_interval_s > 0:
            self._stop_pump.clear()
            self._pump_thread = threading.Thread(
                target=self._pump_loop, daemon=True,
                name="bigdl-fleet-pump")
            self._pump_thread.start()
        return self

    def pump_once(self):
        """One synchronous membership round: every agent beats, then
        the router refreshes its view.  Tests drive this directly for
        deterministic membership transitions."""
        for agent in list(self.agents.values()):
            agent.pump()
        if self.health_monitor is not None:
            # evaluate BEFORE the refresh so a fresh degradation mark
            # is acted on (ejected) in this same round
            self.health_monitor.observe()
        self.router.refresh()

    def _pump_loop(self):
        while not self._stop_pump.wait(self.pump_interval_s):
            try:
                self.pump_once()
            except Exception:
                log.exception("fleet: pump round failed")

    def stop(self, timeout: Optional[float] = 10.0) -> bool:
        """Stop the pump, close the router (in-flight requests still
        resolve), and hard-stop every replica."""
        self._stop_pump.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout)
            self._pump_thread = None
        self.router.close()
        ok = True
        for srv in list(self.servers.values()):
            ok = srv.stop(timeout=timeout) and ok
            sink = getattr(srv, "trace_sink", None)
            if sink is not None:
                sink.close()
        return ok

    # ------------------------------------------------------------ routing
    def submit(self, feature, deadline_s=None, **kw):
        return self.router.submit(feature, deadline_s=deadline_s, **kw)

    def submit_generate(self, prompt_ids, max_new, **kw):
        return self.router.submit_generate(prompt_ids, max_new, **kw)

    def ready_count(self, exclude=()) -> int:
        return sum(1 for rid, srv in self.servers.items()
                   if rid not in exclude and srv.ready())

    def pool_replicas(self, role: str) -> Dict[str, InferenceServer]:
        """Servers whose advertised role serves ``role`` (``both``
        members serve every pool)."""
        from .pools import serves_phase

        return {rid: srv for rid, srv in self.servers.items()
                if serves_phase(getattr(srv, "role", "both"), role)}

    # ------------------------------------------------------- elasticity
    def add_replica(self, rid: str,
                    server: InferenceServer) -> InferenceServer:
        """Join one new replica to the running fleet (the autoscaler's
        scale-up actuator): start it, give it an agent, register it
        with the router, and run one pump round so it is routable
        before this returns."""
        if rid in self.servers:
            raise ValueError(f"replica {rid!r} already in the fleet")
        self.servers[rid] = server
        if not server.healthy():
            server.start()
        agent = ReplicaAgent(rid, server, self.transport,
                             heartbeat_timeout=self.heartbeat_timeout,
                             clock=self._clock)
        self.agents[rid] = agent
        if self.router.tracing is not None \
                and server.trace_sink is None:
            server.trace_sink = self._make_sink(rid)
        self.router.add_replica(rid, server)
        agent.pump()            # beats with rejoin=True
        self.router.refresh()   # ... and is re-admitted here
        _record_change("replica_added",
                       f"role={getattr(server, 'role', 'both')}",
                       source="serving.fleet", replica=rid,
                       model=getattr(server, "model_name", None))
        log.info("fleet: added replica %s (role=%s)", rid,
                 getattr(server, "role", "both"))
        return server

    def remove_replica(self, rid: str, timeout: float = 10.0,
                       drain: bool = True) -> bool:
        """Retire one replica (the autoscaler's scale-down actuator):
        **drain before retire** — admission stops via the graceful-
        preemption path and everything already admitted finishes
        (in-flight paged decodes resolve and release their pages) —
        then hard-stop, deregister from the router, and retire from
        membership immediately.  Returns True when the worker exited
        within ``timeout``."""
        srv = self.servers.pop(rid, None)
        if srv is None:
            return False
        self.agents.pop(rid, None)     # stops heartbeating this rid
        ok = True
        if drain and srv.healthy():
            ok = srv.drain(timeout)
        ok = srv.stop(timeout) and ok
        self.router.remove_replica(rid)
        _record_change("replica_removed", f"drained={drain}",
                       source="serving.fleet", replica=rid,
                       model=getattr(srv, "model_name", None))
        log.info("fleet: removed replica %s (drained=%s)", rid, drain)
        return ok

    def restart_replica(self, rid: str) -> InferenceServer:
        """Revive a killed or stopped replica in place (crash
        replacement): restart its server, clear the agent's killed
        latch, and run one pump round so it beats with ``rejoin=True``
        and re-admits through the normal returner path."""
        srv = self.servers[rid]
        agent = self.agents[rid]
        if not srv.healthy():
            srv.start()
        agent.killed = False
        agent.pump()
        self.router.refresh()
        _record_change("replica_restarted", source="serving.fleet",
                       replica=rid,
                       model=getattr(srv, "model_name", None))
        log.info("fleet: restarted replica %s", rid)
        return srv

    # ------------------------------------------------------------ deploys
    def _acquire_deploy_locks(self, rids):
        """Non-blocking, sorted acquisition of the per-replica deploy
        locks for ``rids``.  Any lock already held means another
        deploy/rollback is touching an overlapping replica set —
        everything taken so far is released and the whole operation is
        refused typed (:class:`~.swap.DeployInFlight`) before any
        replica is touched.  Sorted order keeps two overlapping
        acquisitions deadlock-free."""
        acquired = []
        for rid in sorted(set(rids)):
            with self._deploy_table_lock:
                lk = self._deploy_locks.setdefault(
                    rid, threading.Lock())
            if not lk.acquire(blocking=False):
                for got in reversed(acquired):
                    got.release()
                raise DeployInFlight(
                    f"a deploy is already in flight on replica {rid} "
                    f"— refused before touching any replica")
            acquired.append(lk)
        return acquired

    def rolling_swap(self, params=None, path: Optional[str] = None,
                     order=None, model: Optional[str] = None,
                     version: Optional[str] = None) -> int:
        """Verified deploy, one replica at a time.

        ``model`` scopes the roll to the replicas serving that model
        (a tenant-scoped deploy on a multi-tenant fleet — replicas of
        other models are never locked, never touched); ``model=None``
        rolls the whole fleet.  ``version`` stamps the installed
        params' advertised model version (health snapshots and the
        model registry pick it up), and a rollback re-installs the
        prior version alongside the prior params.

        ``path`` loads ONCE through the crc32c-verified checkpoint
        path (corrupt bytes refuse the whole deploy before any replica
        is touched).  Each replica then runs its own canary via
        :meth:`~.server.InferenceServer.swap_params`; the first
        :class:`SwapRejected` halts the roll and **rolls back every
        already-swapped replica** to its captured prior params.
        Before each replica swaps, the deploy scope must hold its
        ready quorum (fleet-wide: ``ready_quorum``; model-scoped: a
        strict majority of that model's replicas) — otherwise
        :class:`FleetQuorumError` (and rollback of anything already
        swapped).  Returns the number of replicas deployed.

        Replicas that are not healthy (killed, draining) are skipped —
        they pick up current params through the normal swap path when
        they come back.

        Mutual exclusion is per replica: a concurrent deploy/rollback
        touching ANY overlapping replica raises
        :class:`~.swap.DeployInFlight` immediately, before any replica
        is touched, while deploys on disjoint models proceed
        concurrently.
        """
        if (params is None) == (path is None):
            raise ValueError("pass exactly one of params/path")
        if model is not None:
            targets = sorted(
                rid for rid, srv in self.servers.items()
                if getattr(srv, "model_name", None) == model)
            if not targets:
                raise ValueError(
                    f"no replica serves model {model!r}")
        else:
            targets = sorted(self.servers)
        if order is not None:
            known = set(targets)
            order = [rid for rid in order if rid in known]
        else:
            order = targets
        locks = self._acquire_deploy_locks(targets)
        try:
            if path is not None:
                params = load_verified_params(path)
            _record_change(
                "deploy_started",
                f"version={version} targets={len(targets)}",
                source="serving.fleet", model=model)
            quorum = (self.ready_quorum if model is None
                      else len(targets) // 2 + 1)
            done = []  # [(rid, (prior_params, prior_bufs), prior_ver)]
            for rid in order:
                srv = self.servers.get(rid)
                if srv is None or not srv.healthy():
                    log.warning("fleet: deploy skipping unhealthy "
                                "replica %s", rid)
                    continue
                ready = (self.ready_count() if model is None else
                         sum(1 for r in targets
                             if self.servers[r].ready()))
                if ready < quorum:
                    self._rollback(done)
                    self.deploy_rollbacks += 1
                    _record_change(
                        "deploy_rolled_back",
                        f"quorum lost before {rid}",
                        source="serving.fleet", model=model)
                    raise FleetQuorumError(
                        f"deploy halted before {rid}: only {ready} "
                        f"replica(s) ready, quorum is {quorum} — "
                        f"rolled back")
                prior = srv.current_params()
                prior_version = getattr(srv, "model_version", None)
                try:
                    srv.swap_params(params=params, version=version)
                except SwapRejected as e:
                    self._rollback(done)
                    self.deploy_rollbacks += 1
                    _record_change(
                        "deploy_rolled_back",
                        f"canary rejected at {rid}",
                        source="serving.fleet", replica=rid,
                        model=model)
                    raise SwapRejected(
                        f"rolling deploy halted at {rid}: {e} — "
                        f"{len(done)} already-swapped replica(s) "
                        f"rolled back")
                done.append((rid, prior, prior_version))
                log.info("fleet: deployed to %s (%d/%d)", rid,
                         len(done), len(order))
            self.deploys += 1
            _record_change(
                "deploy_confirmed",
                f"version={version} replicas={len(done)}",
                source="serving.fleet", model=model)
            with self._deploy_table_lock:
                self._last_deploy[model] = done
            if (model is not None and version is not None
                    and self.router.model_registry is not None):
                # advertise the new version fleet-wide (per-replica
                # health snapshots catch up at the next pump)
                self.router.model_registry.register(model, version)
            return len(done)
        finally:
            for lk in reversed(locks):
                lk.release()

    def rollback_last_deploy(self, model: Optional[str] = None) -> int:
        """Roll every replica of the last completed deploy (for
        ``model``'s scope; ``None`` = the last fleet-wide roll) back
        to its captured prior params — the alert-driven entry point
        the continuous-learning loop fires when the post-swap
        burn-rate watch trips.  The rollback rides the same verified
        canary install path as a deploy (each re-install records
        ``outcome="rolled_back"``), holds the same per-replica deploy
        locks, and consumes the captured set: a second call with
        nothing newer deployed is a no-op returning 0."""
        with self._deploy_table_lock:
            pending = list(self._last_deploy.get(model, ()))
        if not pending:
            return 0
        locks = self._acquire_deploy_locks(e[0] for e in pending)
        try:
            with self._deploy_table_lock:
                done = self._last_deploy.pop(model, [])
            if not done:
                return 0
            self._rollback(done)
            self.deploy_rollbacks += 1
            _record_change(
                "deploy_rolled_back",
                f"alert-driven rollback of {len(done)} replica(s)",
                source="serving.fleet", model=model)
            if (model is not None
                    and self.router.model_registry is not None
                    and done[0][2] is not None):
                # re-advertise the prior version alongside the prior
                # params
                self.router.model_registry.register(model, done[0][2])
            log.warning("fleet: alert-driven rollback re-installed "
                        "prior params on %d replica(s)", len(done))
            return len(done)
        finally:
            for lk in reversed(locks):
                lk.release()

    def _rollback(self, done):
        for rid, (prior_params, prior_buffers), prior_version \
                in reversed(done):
            try:
                # the rollback rides the full verified install path
                # (canary included) — only its counter outcome differs
                self.servers[rid].swap_params(params=prior_params,
                                              buffers=prior_buffers,
                                              version=prior_version,
                                              outcome="rolled_back")
            except SwapRejected:
                # the prior params were serving seconds ago; a canary
                # refusing them now means something else is injecting
                # failures — keep rolling back the rest, loudly
                log.exception("fleet: rollback canary failed on %s",
                              rid)

    # ------------------------------------------------------------ telemetry
    def goodput_per_chip(self) -> dict:
        """Served model-FLOP/s per chip over the fleet's first→last
        batch window, and that rate as a fraction of one chip's peak —
        one replica is assumed to own one chip (the in-process fleet's
        mesh story; a sharded replica would scale ``chips``)."""
        total = 0.0
        t0 = t1 = None
        for srv in self.servers.values():
            g = srv.metrics.goodput_per_chip()
            total += g["flops_total"]
            w0, w1 = srv.metrics.batch_window()
            if w0 is not None:
                t0 = w0 if t0 is None else min(t0, w0)
                t1 = w1 if t1 is None else max(t1, w1)
        chips = max(1, len(self.servers))
        wall = (t1 - t0) if (t0 is not None and t1 is not None) else 0.0
        rate = total / wall / chips if wall > 0 else 0.0
        out = {"flops_total": total, "wall_s": wall, "chips": chips,
               "model_flops_per_sec_per_chip": rate, "mfu": None}
        if rate > 0:
            try:
                from ..telemetry.device_info import current_device_spec

                spec = current_device_spec()
                if spec.peak_flops_per_sec:
                    out["mfu"] = rate / spec.peak_flops_per_sec
                    out["nominal_device"] = spec.nominal
            except Exception:
                pass
        return out

    #: router registry families folded into the fleet view — the ones
    #: only the router populates.  Its *request* families share names
    #: with the replicas' (it records fleet-level outcomes, they
    #: record per-attempt outcomes); folding both would double-count,
    #: so the router's copies of shared names stay in its own
    #: ``router`` section.
    _ROUTER_FOLD_FAMILIES = (
        "bigdl_serving_hedges_total", "bigdl_serving_retries_total",
        "bigdl_fleet_dispatch_total",
        "bigdl_autoscale_decisions_total",
        "bigdl_alerts_total", "bigdl_alerts_active",
        # the continuous-learning loop registers its deploy outcomes
        # in the router registry, so they fold into the fleet view too
        "bigdl_loop_deploys_total",
        # multi-tenant families only the router populates (admission
        # decisions, per-tenant dispatch, inflight gauge, typed sheds)
        "bigdl_tenant_dispatch_total", "bigdl_tenant_admission_total",
        "bigdl_tenant_inflight", "bigdl_tenant_sheds_total",
    )

    def _router_fold_metrics(self) -> dict:
        snap = self.router.metrics.registry.snapshot()["metrics"]
        return {name: fam for name, fam in snap.items()
                if name in self._ROUTER_FOLD_FAMILIES}

    def snapshot(self) -> dict:
        """The fleet view: per-replica snapshots, the router's, the
        membership state, fleet goodput-per-chip, and the per-replica
        metric registries folded into one cluster view by the existing
        cross-host merge (:func:`telemetry.aggregate.merge_metrics` —
        counters sum, histogram buckets add)."""
        from ..telemetry.aggregate import merge_metrics

        per_replica = {rid: srv.metrics.snapshot()
                       for rid, srv in sorted(self.servers.items())}
        registries = [srv.metrics.registry.snapshot()["metrics"]
                      for _, srv in sorted(self.servers.items())]
        registries.append(self._router_fold_metrics())
        n, members = self.router.coordinator.membership()
        return {
            "replicas": per_replica,
            "router": self.router.snapshot(),
            "membership": {
                "incarnation": n,
                "members": list(members),
                "ejections": self.router.ejections,
                "readmissions": self.router.readmissions,
            },
            "deploys": self.deploys,
            "deploy_rollbacks": self.deploy_rollbacks,
            # per-tenant request/shed fold (router-side attribution —
            # one row per tenant, empty dict on single-model fleets)
            "tenants": self.router.metrics.tenants(),
            "goodput_per_chip": self.goodput_per_chip(),
            "health": (self.health_monitor.snapshot()
                       if self.health_monitor is not None else None),
            "metrics": merge_metrics(registries),
        }

    def to_prometheus(self) -> str:
        """Prometheus text of every replica registry plus the
        router's, each series labeled — scrape-ready fleet view."""
        parts = [srv.metrics.to_prometheus()
                 for _, srv in sorted(self.servers.items())]
        parts.append(self.router.metrics.to_prometheus())
        return "\n".join(parts)

    def write_snapshots(self, directory: str) -> list:
        """Drop one ``<replica>.json`` payload per replica (plus the
        router's) into ``directory`` — the snapshot-dir format
        ``tools/run_report.py`` merges and renders."""
        from ..telemetry.aggregate import write_snapshot

        n, _ = self.router.coordinator.membership()
        paths = []
        for rid, srv in sorted(self.servers.items()):
            serving = srv.metrics.snapshot()
            # the router's tenants map is the authoritative per-tenant
            # accounting (it sees every request, including sheds that
            # never reach a replica); the replicas' copies would
            # double-count against it in the merge
            serving.pop("tenants", None)
            payload = {
                "host": rid,
                "incarnation": n,
                "metrics": srv.metrics.registry.snapshot()["metrics"],
                "serving": serving,
            }
            paths.append(write_snapshot(directory, rid, payload))
        paths.append(write_snapshot(directory, "fleet-router", {
            "host": "fleet-router",
            "incarnation": n,
            # only the router-specific families (hedges/retries/
            # dispatch): its copies of the shared request families
            # would double-count against the replicas' in the merge
            "metrics": self._router_fold_metrics(),
            "serving": self.router.metrics.snapshot(),
        }))
        return paths
