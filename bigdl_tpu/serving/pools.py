"""Prefill/decode pool roles + the verified KV handoff between them.

The roofline classifier (PR 6) proves the physical split the fleet
should exploit: **prefill** is one big causal matmul pass —
compute-bound, MXU territory — while **decode** streams the whole KV
cache per token — HBM-bandwidth-bound.  Sizing one homogeneous pool
for both means over-provisioning whichever resource the mix doesn't
stress.  Disaggregation lets the router send each phase to a pool
sized for its own bottleneck: replicas advertise a ``role`` in their
health snapshots (``prefill`` | ``decode`` | ``both``), the router
splits ``submit_generate`` into a prefill dispatch and a decode
dispatch, and the filled KV pages travel between pools as a
**handoff** blob.

The handoff rides the same integrity discipline the verified-swap
machinery uses (``resilience.checkpoint`` / ``swap.py``): the pickled
payload carries a crc32c over its bytes, verified on receipt — a blob
corrupted in flight (or a version-skewed peer) raises
:class:`HandoffCorrupt` and the decode resolves as a typed
INTERNAL_ERROR instead of decoding garbage K/V into user-visible
tokens.
"""
from __future__ import annotations

import pickle
import struct
from typing import Dict, Optional, Tuple

import numpy as np

from ..visualization.crc32c import crc32c

__all__ = ["PREFILL", "DECODE", "BOTH", "ROLES", "HandoffCorrupt",
           "serialize_handoff", "deserialize_handoff",
           "peek_handoff_trace", "serves_phase", "split_pool",
           "pool_members"]

PREFILL = "prefill"
DECODE = "decode"
BOTH = "both"
ROLES = (PREFILL, DECODE, BOTH)

#: wire header: magic + crc32c + payload length
_MAGIC = b"BKVH"
_HEADER = struct.Struct("<4sII")


class HandoffCorrupt(RuntimeError):
    """The KV handoff blob failed its crc32c (or geometry) check —
    refused before any of its bytes reach a decode program."""


def serves_phase(role: Optional[str], phase: str) -> bool:
    """Does a replica advertising ``role`` serve ``phase``?  Unknown /
    unreported roles default to ``both`` (a pre-disaggregation replica
    keeps serving everything)."""
    r = role if role in ROLES else BOTH
    return r == BOTH or r == phase


def split_pool(pool: str) -> Tuple[Optional[str], str]:
    """Parse a pool spec into ``(model, role)``.  A bare role
    (``"decode"``) is the classic fleet-wide phase pool
    (``(None, "decode")``); a ``"model:role"`` spec scopes the pool to
    one tenant's replicas on a multi-tenant fleet — the autoscaler
    sizes each (model, phase) pool independently."""
    if ":" in pool:
        model, role = pool.split(":", 1)
        return model, role
    return None, pool


def pool_members(health: Dict[str, dict], phase: str) -> Tuple[str, ...]:
    """Members of one pool, from the router's health view.  ``phase``
    accepts the same specs :func:`split_pool` does — a bare role or a
    tenant-scoped ``model:role``."""
    model, role = split_pool(phase)
    return tuple(sorted(
        r for r, h in health.items()
        if serves_phase((h or {}).get("role"), role)
        and (model is None or (h or {}).get("model") == model)))


def serialize_handoff(k_pages: np.ndarray, v_pages: np.ndarray,
                      first_token: int, pos: int, page_size: int,
                      extras: Optional[dict] = None) -> bytes:
    """Pack filled KV pages + the first generated token into a
    crc-sealed blob.  ``pos`` is the next write position (the prompt
    length); geometry fields ride along so the importing pool can
    refuse a mismatched arena loudly."""
    n, layers, hkv, ps, dh = k_pages.shape
    if ps != page_size:
        raise ValueError(f"k_pages page dim {ps} != page_size "
                         f"{page_size}")
    payload = pickle.dumps({
        "k_pages": np.asarray(k_pages),
        "v_pages": np.asarray(v_pages),
        "first_token": int(first_token),
        "pos": int(pos),
        "page_size": int(page_size),
        "layers": int(layers),
        "num_kv_heads": int(hkv),
        "head_dim": int(dh),
        **(extras or {}),
    }, protocol=pickle.HIGHEST_PROTOCOL)
    return _HEADER.pack(_MAGIC, crc32c(payload) & 0xFFFFFFFF,
                        len(payload)) + payload


def deserialize_handoff(blob: bytes) -> dict:
    """Verify and unpack a handoff blob (:class:`HandoffCorrupt` on a
    bad magic, length, or crc — the verified-swap refusal, in
    memory)."""
    if not isinstance(blob, (bytes, bytearray)):
        raise HandoffCorrupt(
            f"handoff must be bytes, got {type(blob).__name__}")
    if len(blob) < _HEADER.size:
        raise HandoffCorrupt(f"handoff truncated ({len(blob)} bytes)")
    magic, crc, size = _HEADER.unpack_from(blob)
    payload = bytes(blob[_HEADER.size:])
    if magic != _MAGIC:
        raise HandoffCorrupt(f"bad handoff magic {magic!r}")
    if len(payload) != size:
        raise HandoffCorrupt(
            f"handoff payload {len(payload)} bytes, header says {size}")
    if (crc32c(payload) & 0xFFFFFFFF) != crc:
        raise HandoffCorrupt("handoff failed crc32c verification")
    out = pickle.loads(payload)
    for key in ("k_pages", "v_pages", "first_token", "pos",
                "page_size", "layers", "num_kv_heads", "head_dim"):
        if key not in out:
            raise HandoffCorrupt(f"handoff missing field {key!r}")
    return out


def peek_handoff_trace(blob) -> Optional[dict]:
    """The distributed-trace context a prefill replica sealed into the
    handoff extras (``telemetry.trace_context.TRACE_WIRE_KEY``), or
    None — on an untraced blob AND on a corrupt one.  The crc verdict
    belongs to the decode path; this peek must never preempt it."""
    from ..telemetry.trace_context import TRACE_WIRE_KEY

    try:
        wire = deserialize_handoff(blob).get(TRACE_WIRE_KEY)
        return wire if isinstance(wire, dict) else None
    except Exception:
        return None
