"""Hot model swap: verified load, canary, atomic install, rollback.

New parameters enter the serving path only through the crc32c-verified
checkpoint machinery (:mod:`bigdl_tpu.resilience.checkpoint`) — a
corrupt file quarantines and the swap is refused, exactly like
training restore.  Loaded params then face a **canary batch** on the
same compiled forward the live traffic uses; a canary that raises or
emits non-finite outputs rolls the swap back, so poisoned params (the
:func:`resilience.faults.poison_params` injector) can never reach a
user request.  The install itself happens between batches under the
server's model lock — in-flight batches finish on the old params,
the next batch sees the new ones.
"""
from __future__ import annotations

import logging
from typing import Any

from ..resilience.checkpoint import CorruptCheckpointError, verified_load

log = logging.getLogger("bigdl_tpu")


class SwapRejected(RuntimeError):
    """The candidate params failed verification or the canary batch;
    the server keeps serving the previous params."""


class DeployInFlight(RuntimeError):
    """A rolling deploy (or alert-driven rollback) is already in
    flight on this fleet — the new attempt is refused, typed, before
    any replica is touched.  Two interleaved rolls could leave the
    fleet serving a mix of candidates with no prior-params set that
    rolls either one back cleanly, so the deploy path is mutually
    exclusive fleet-wide."""


def load_verified_params(path: str) -> Any:
    """Load a checkpoint file for serving, refusing corrupt bytes.

    The file must pass its crc32c sidecar check when one exists (a
    mismatch quarantines it, like training restore — via
    ``resilience.checkpoint.verified_load``); it must at least unpickle
    either way.  Checkpoints written by the optimizer hold the whole
    model object — those are unwrapped to their ``param_tree()``; a
    pickled bare param tree passes through as-is."""
    try:
        obj = verified_load(path)
    except CorruptCheckpointError as e:
        raise SwapRejected(str(e))
    if hasattr(obj, "param_tree"):
        return obj.param_tree()
    return obj
