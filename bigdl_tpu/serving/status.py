"""Status taxonomy + per-request future.

Every admitted (or rejected) request resolves to exactly one
:class:`ServeResult`; the server never drops a request silently and
never leaves a caller blocked forever — load shedding, deadline
expiry, breaker rejection, and drain cancellation are all *typed*
outcomes the caller can branch on, mirroring how
``resilience.retry.classify_error`` makes training failures explicit
instead of letting them crash the driver.
"""
from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Any, Optional


class Status(enum.Enum):
    OK = "ok"
    #: deadline elapsed before the request reached a device (or at
    #: admission, when it was already expired on arrival)
    DEADLINE_EXCEEDED = "deadline_exceeded"
    #: admission-control rejection: the bounded queue is full (shed)
    OVERLOADED = "overloaded"
    #: the server cannot take the request right now: circuit breaker
    #: open, server draining, or not started
    UNAVAILABLE = "unavailable"
    #: the compiled step raised; the error string carries the cause
    INTERNAL_ERROR = "internal_error"
    #: the server was hard-stopped with the request still queued
    CANCELLED = "cancelled"
    #: the request named a model/version no replica advertises (or the
    #: registry entry vanished mid-flight) — resolved typed at
    #: admission, never retried, never surfaced as INTERNAL_ERROR
    NOT_FOUND = "not_found"


@dataclass
class ServeResult:
    """Terminal outcome of one request."""
    status: Status
    output: Any = None          # per-request output row(s); OK only
    error: Optional[str] = None
    #: submit → resolve wall time (seconds)
    latency_s: float = 0.0
    #: portion of latency spent queued before batch formation
    queued_s: float = 0.0
    #: padded bucket the request ran in (OK/INTERNAL_ERROR only)
    bucket: Optional[int] = None
    #: distributed-trace id when request tracing is enabled (look the
    #: stitched timeline up via the router's RequestTracer / exemplars)
    trace_id: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.status is Status.OK


class ServeFuture:
    """Single-assignment result slot handed back by ``submit``.

    ``result(timeout)`` blocks until the server resolves the request;
    a ``timeout`` raises ``TimeoutError`` rather than returning a
    placeholder, so a hung server is loud — but under the server's
    contract every admitted request is resolved even on drain, stop,
    or breaker trip.  ``add_done_callback`` lets the fleet router wait
    on several replicas' futures at once (hedging) without polling."""

    __slots__ = ("_event", "_result", "_callbacks", "_cb_lock")

    def __init__(self):
        self._event = threading.Event()
        self._result: Optional[ServeResult] = None
        self._callbacks = []
        self._cb_lock = threading.Lock()

    def done(self) -> bool:
        return self._event.is_set()

    def _resolve(self, result: ServeResult):
        with self._cb_lock:
            if self._event.is_set():  # first resolution wins
                return
            self._result = result
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            try:
                cb(self)
            except Exception:  # a broken observer must not break resolve
                pass

    def add_done_callback(self, fn):
        """Call ``fn(self)`` when the future resolves (immediately if it
        already has).  Callback exceptions are swallowed — resolution
        must never fail because an observer raised."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        try:
            fn(self)
        except Exception:
            pass

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._event.wait(timeout):
            raise TimeoutError("request not resolved within "
                               f"{timeout}s")
        return self._result


@dataclass
class Request:
    """Internal queue entry (kind: ``"classify"`` or ``"generate"``)."""
    kind: str
    payload: Any
    future: ServeFuture
    submitted_at: float
    #: absolute monotonic deadline, or None
    deadline: Optional[float] = None
    #: generate-path options (max_new, eos_id, pad_id)
    opts: tuple = field(default_factory=tuple)
    #: distributed-trace context (telemetry.trace_context.TraceContext)
    #: propagated from the router, or None when untraced
    trace: Optional[object] = None

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline
