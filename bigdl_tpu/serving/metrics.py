"""Per-request serving metrics: counters + latency quantiles, backed
by the unified telemetry registry.

Counts every terminal status (a shed request increments ``shed`` and
nothing else — never a silent drop), tracks queue depth at admission,
and answers p50/p99 from a :class:`~bigdl_tpu.telemetry.Histogram`
whose bounded exact-sample window reproduces numpy-percentile
semantics over the most recent ``window`` requests — the same numbers
the pre-registry deque implementation reported.  The histograms'
log-bucket state additionally merges across hosts in the cross-host
telemetry view (docs/observability.md).

``to_summary`` exports the snapshot through the tensorboard-compatible
``visualization.summary`` writer so serving health lands next to the
training curves; the backing registry (one private registry per
server by default, so two servers in one process never blend their
counts) exports Prometheus text via ``metrics.registry
.to_prometheus()``.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..telemetry.registry import MetricsRegistry, default_buckets
from .status import Status

#: latency window — big enough for stable p99, bounded so a long-lived
#: server never grows without limit
_WINDOW = 8192

#: latency bucket ladder: 100µs … ~100s (log-spaced, mergeable)
_LATENCY_BUCKETS = default_buckets(start=1e-4, factor=2.0, count=21)
#: queue-depth ladder: 1 … 2^15
_DEPTH_BUCKETS = default_buckets(start=1.0, factor=2.0, count=16)


class ServingMetrics:
    def __init__(self, window: int = _WINDOW,
                 registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        self.registry = registry or MetricsRegistry()
        self._requests = self.registry.counter(
            "bigdl_serving_requests_total",
            "terminal request statuses", labels=("status",))
        self._lat = self.registry.histogram(
            "bigdl_serving_latency_seconds",
            "end-to-end latency of OK requests",
            bounds=_LATENCY_BUCKETS, window=window)
        self._queued = self.registry.histogram(
            "bigdl_serving_queued_seconds",
            "queue-wait portion of OK requests",
            bounds=_LATENCY_BUCKETS, window=window)
        self._depth = self.registry.histogram(
            "bigdl_serving_queue_depth",
            "admission-time queue depth",
            bounds=_DEPTH_BUCKETS, window=window)
        self._batches = self.registry.counter(
            "bigdl_serving_batches_total", "compiled batches executed")
        self._padded = self.registry.counter(
            "bigdl_serving_padded_rows_total",
            "bucket-padding rows executed")
        self._flops = self.registry.counter(
            "bigdl_serving_flops_total",
            "XLA cost-model FLOPs dispatched (per-bucket static "
            "cost x batches)", labels=("bucket",))
        # hot-swap outcomes and tail-latency hedging land in the same
        # registry so the fleet fold (telemetry.aggregate.merge_metrics)
        # and to_prometheus() carry them — a rejected deploy or a hedge
        # storm must be visible in the scraped view, not just in
        # python attributes
        self._swaps = self.registry.counter(
            "bigdl_serving_swaps_total",
            "hot param swap outcomes", labels=("outcome",))
        self._hedges = self.registry.counter(
            "bigdl_serving_hedges_total",
            "tail-latency hedges (fired = duplicate sent, won = the "
            "hedge's response was used, suppressed = a decode-phase "
            "hedge the router refused — duplicating a long decode "
            "doubles HBM + KV-pool pressure)", labels=("event",))
        self._retries = self.registry.counter(
            "bigdl_serving_retries_total",
            "failover retries dispatched to another replica")
        # the generation-phase family (paged/disaggregated serving):
        # prefill = prompt pass + first token, decode = the rest.
        # TTFT/TPOT are the two numbers a serving SLO is written in —
        # p50/p99 land in snapshot() next to the request latencies
        self._phase = self.registry.histogram(
            "bigdl_serving_phase_seconds",
            "wall seconds per generation phase",
            labels=("phase",), bounds=_LATENCY_BUCKETS, window=window)
        self._ttft = self.registry.histogram(
            "bigdl_serving_ttft_seconds",
            "submit -> first generated token (time-to-first-token)",
            bounds=_LATENCY_BUCKETS, window=window)
        self._tpot = self.registry.histogram(
            "bigdl_serving_tpot_seconds",
            "decode seconds per generated token "
            "(time-per-output-token)",
            bounds=_LATENCY_BUCKETS, window=window)
        # per-tenant twins of the request/shed/phase families.  The
        # registry pins each family to ONE label tuple, so tenant
        # observability lives in parallel bigdl_tenant_* families
        # (metric_names.py) instead of widening the existing ones;
        # series only appear for requests that actually carry a tenant,
        # so single-model fleets pay nothing
        self._tenant_requests = self.registry.counter(
            "bigdl_tenant_requests_total",
            "terminal request statuses per tenant",
            labels=("tenant", "status"))
        self._tenant_sheds = self.registry.counter(
            "bigdl_tenant_sheds_total",
            "admission rejections per tenant (reason: tenant_quota = "
            "weighted fair shed of the over-quota tenant, global = "
            "fleet-wide exhaustion, not_found = unregistered model)",
            labels=("tenant", "reason"))
        self._tenant_phase = self.registry.histogram(
            "bigdl_tenant_phase_seconds",
            "wall seconds per generation phase per tenant",
            labels=("tenant", "phase"), bounds=_LATENCY_BUCKETS,
            window=window)
        self._tenant_ttft = self.registry.histogram(
            "bigdl_tenant_ttft_seconds",
            "time-to-first-token per tenant",
            labels=("tenant",), bounds=_LATENCY_BUCKETS, window=window)
        self._tenant_tpot = self.registry.histogram(
            "bigdl_tenant_tpot_seconds",
            "time-per-output-token per tenant",
            labels=("tenant",), bounds=_LATENCY_BUCKETS, window=window)
        self._tenant_kv_held = self.registry.gauge(
            "bigdl_tenant_kv_pages_held",
            "KV pages currently held per pool owner",
            labels=("tenant",))
        # KV page-pool occupancy gauges (zero-valued when the server
        # has no pool — the fleet fold may sum them safely)
        self._kv_total = self.registry.gauge(
            "bigdl_serving_kv_pages_total", "KV page-pool capacity")
        self._kv_free = self.registry.gauge(
            "bigdl_serving_kv_pages_free", "KV page-pool free pages")
        self._kv_occupancy = self.registry.gauge(
            "bigdl_serving_kv_occupancy",
            "KV page-pool occupancy fraction (in-use / capacity)")
        # per-bucket static cost (XLA cost model) + the wall window the
        # flops were spent in — what goodput-per-chip divides by
        self._bucket_flops: Dict[int, float] = {}
        self._t_first_batch: Optional[float] = None
        self._t_last_batch: Optional[float] = None
        self.counts: Dict[str, int] = {s.value: 0 for s in Status}
        # amortized p99 for per-request consumers (tail sampler, hedge
        # delay): the exact-window quantile sorts up to `window`
        # samples — at request rate that is an O(n log n) tax per
        # request, so hot-path readers get a value recomputed every
        # `_P99_REFRESH` observations instead
        self._p99_cache: Optional[float] = None
        self._p99_cache_count = -1

    _P99_REFRESH = 64

    def latency_p99(self) -> Optional[float]:
        """The OK-latency p99, recomputed at most every
        ``_P99_REFRESH`` observations — the hot-path spelling of
        ``snapshot()["latency_p99_s"]`` (which stays exact)."""
        count = self._lat.count
        with self._lock:
            if count - self._p99_cache_count < self._P99_REFRESH \
                    and self._p99_cache_count >= 0:
                return self._p99_cache
        p99 = self._lat.quantile(0.99)
        with self._lock:
            self._p99_cache = p99
            self._p99_cache_count = count
        return p99

    # ------------------------------------------------------------------
    def record(self, status: Status, latency_s: float = 0.0,
               queued_s: float = 0.0,
               trace_id: Optional[str] = None,
               tenant: Optional[str] = None):
        """One terminal request outcome.  ``trace_id`` (a KEPT
        distributed trace) attaches as a Prometheus-style exemplar to
        the latency bucket the request landed in — the scraped
        histogram links straight to a stitched timeline.  ``tenant``
        additionally lands the outcome in the per-tenant twin family."""
        with self._lock:
            self.counts[status.value] += 1
        self._requests.labels(status=status.value).inc()
        if tenant is not None:
            self._tenant_requests.labels(
                tenant=str(tenant), status=status.value).inc()
        if status is Status.OK:
            self._lat.observe(latency_s, exemplar=trace_id)
            self._queued.observe(queued_s)

    def record_shed(self, tenant: str, reason: str):
        """One per-tenant admission rejection (``tenant_quota`` |
        ``global`` | ``not_found``) — the series the weighted-shed
        ordering and victim-sheds-zero audits read."""
        self._tenant_sheds.labels(tenant=str(tenant),
                                  reason=str(reason)).inc()

    def record_depth(self, depth: int):
        self._depth.observe(depth)

    #: the hot-swap outcome vocabulary: a normal install, a canary/
    #: verify refusal (prior params keep serving), and a rollback
    #: re-install (a fleet deploy halted or an SLO alert fired and the
    #: captured prior params rode the verified install path back in)
    SWAP_OUTCOMES = ("installed", "rejected", "rolled_back")

    def record_swap(self, installed: bool = True,
                    outcome: Optional[str] = None):
        """One hot-swap outcome.  ``installed=True/False`` is the
        legacy install/reject spelling; ``outcome`` names any member
        of :data:`SWAP_OUTCOMES` directly — a fleet rollback records
        ``rolled_back`` so the scraped counter distinguishes a
        re-verified rollback install from a fresh deploy."""
        if outcome is None:
            outcome = "installed" if installed else "rejected"
        if outcome not in self.SWAP_OUTCOMES:
            raise ValueError(f"unknown swap outcome {outcome!r}; one "
                             f"of {self.SWAP_OUTCOMES}")
        self._swaps.labels(outcome=outcome).inc()

    def record_hedge(self, won: bool = False):
        """One hedging event: ``record_hedge()`` when the duplicate is
        sent (fired), ``record_hedge(won=True)`` when the hedge's
        response beat the primary and was used."""
        self._hedges.labels(event="won" if won else "fired").inc()

    def record_hedge_suppressed(self):
        """A decode-phase hedge the router refused to fire (the
        ``hedge_decode`` knob) — counted so hedge duty stays auditable
        even when the answer is 'no'."""
        self._hedges.labels(event="suppressed").inc()

    def record_retry(self):
        self._retries.inc()

    def record_phase(self, phase: str, seconds: float,
                     tenant: Optional[str] = None):
        """One generation phase's wall time (``prefill`` | ``decode``)."""
        self._phase.labels(phase=phase).observe(seconds)
        if tenant is not None:
            self._tenant_phase.labels(
                tenant=str(tenant), phase=phase).observe(seconds)

    def record_ttft(self, seconds: float, tenant: Optional[str] = None):
        self._ttft.observe(seconds)
        if tenant is not None:
            self._tenant_ttft.labels(tenant=str(tenant)).observe(seconds)

    def record_tpot(self, seconds: float, tenant: Optional[str] = None):
        self._tpot.observe(seconds)
        if tenant is not None:
            self._tenant_tpot.labels(tenant=str(tenant)).observe(seconds)

    def set_kv_pool(self, stats: Optional[dict]):
        """Refresh the KV page-pool gauges from
        ``KVPagePool.stats()`` (no-op on None)."""
        if not stats:
            return
        self._kv_total.set(float(stats.get("num_pages", 0)))
        self._kv_free.set(float(stats.get("free_pages", 0)))
        self._kv_occupancy.set(float(stats.get("occupancy", 0.0)))
        for owner, held in (stats.get("by_owner") or {}).items():
            self._tenant_kv_held.labels(tenant=str(owner)).set(
                float(held))

    def _counter_value(self, name: str, **labels) -> int:
        fam = self.registry.get(name)
        if fam is None:
            return 0
        for got, child in fam.series():
            if all(got.get(k) == v for k, v in labels.items()):
                return int(child.value)
        return 0

    @property
    def swaps(self) -> int:
        return self._counter_value("bigdl_serving_swaps_total",
                                   outcome="installed")

    @property
    def swap_rollbacks(self) -> int:
        return self._counter_value("bigdl_serving_swaps_total",
                                   outcome="rejected")

    @property
    def swaps_rolled_back(self) -> int:
        """Rollback re-installs on this replica (the
        ``outcome="rolled_back"`` leg of the swap counter)."""
        return self._counter_value("bigdl_serving_swaps_total",
                                   outcome="rolled_back")

    @property
    def hedges_fired(self) -> int:
        return self._counter_value("bigdl_serving_hedges_total",
                                   event="fired")

    @property
    def hedges_won(self) -> int:
        return self._counter_value("bigdl_serving_hedges_total",
                                   event="won")

    @property
    def hedges_suppressed(self) -> int:
        return self._counter_value("bigdl_serving_hedges_total",
                                   event="suppressed")

    @property
    def retries(self) -> int:
        return int(self._retries.value)

    def record_bucket_cost(self, bucket: int, flops: float):
        """Install the static cost of one bucket's compiled forward
        (analyzed once per bucket by the server)."""
        with self._lock:
            self._bucket_flops[int(bucket)] = float(flops)

    def record_batch(self, real: int, bucket: int):
        self._batches.inc()
        self._padded.inc(bucket - real)
        now = time.monotonic()
        with self._lock:
            flops = self._bucket_flops.get(int(bucket), 0.0)
            if self._t_first_batch is None:
                self._t_first_batch = now
            self._t_last_batch = now
        if flops:
            self._flops.labels(bucket=str(int(bucket))).inc(flops)

    # ------------------------------------------------------------------
    @property
    def batches(self) -> int:
        return int(self._batches.value)

    @property
    def padded_rows(self) -> int:
        return int(self._padded.value)

    @property
    def flops_total(self) -> float:
        fam = self.registry.get("bigdl_serving_flops_total")
        return float(sum(child.value for _, child in fam.series())) \
            if fam is not None else 0.0

    def batch_window(self):
        """(first, last) batch wall-clock marks — what a fleet fold
        uses to compute one shared serving window; (None, None) before
        any batch."""
        with self._lock:
            return self._t_first_batch, self._t_last_batch

    def goodput_per_chip(self) -> dict:
        """Model-FLOP/s actually served over the first→last batch wall
        window, and that rate as a fraction of the chip's peak — the
        serving analogue of training MFU.  Zeros before any analyzed
        bucket has dispatched (CPU-only servers with no cost analysis
        report flops_total 0, never an error)."""
        with self._lock:
            t0, t1 = self._t_first_batch, self._t_last_batch
        total = self.flops_total
        wall = (t1 - t0) if (t0 is not None and t1 is not None) else 0.0
        rate = total / wall if wall > 0 else 0.0
        out = {"flops_total": total, "wall_s": wall,
               "model_flops_per_sec": rate, "mfu": None}
        if rate > 0:
            try:
                from ..telemetry.device_info import current_device_spec

                spec = current_device_spec()
                if spec.peak_flops_per_sec:
                    out["mfu"] = rate / spec.peak_flops_per_sec
                    out["nominal_device"] = spec.nominal
            except Exception:
                pass
        return out

    def tenants(self) -> dict:
        """Per-tenant request/shed counts folded from the tenant twin
        families — {} on a fleet that never carried a tenant."""
        out: Dict[str, dict] = {}

        def _tenant(name):
            return out.setdefault(
                name, {"requests": {}, "sheds": {}, "total": 0,
                       "served_ok": 0, "shed_total": 0})

        fam = self.registry.get("bigdl_tenant_requests_total")
        if fam is not None:
            for lbl, child in fam.series():
                d = _tenant(lbl.get("tenant"))
                n = int(child.value)
                d["requests"][lbl.get("status")] = n
                d["total"] += n
                if lbl.get("status") == Status.OK.value:
                    d["served_ok"] += n
        fam = self.registry.get("bigdl_tenant_sheds_total")
        if fam is not None:
            for lbl, child in fam.series():
                d = _tenant(lbl.get("tenant"))
                n = int(child.value)
                d["sheds"][lbl.get("reason")] = n
                d["shed_total"] += n
        return out

    def snapshot(self) -> dict:
        gpc = self.goodput_per_chip()
        with self._lock:
            counts = dict(self.counts)
        ok = counts[Status.OK.value]
        total = sum(counts.values())
        return {
            "served_ok": ok,
            "total": total,
            "shed": counts[Status.OVERLOADED.value],
            "deadline_exceeded":
                counts[Status.DEADLINE_EXCEEDED.value],
            "unavailable": counts[Status.UNAVAILABLE.value],
            "internal_error":
                counts[Status.INTERNAL_ERROR.value],
            "cancelled": counts[Status.CANCELLED.value],
            "not_found": counts[Status.NOT_FOUND.value],
            "shed_rate": (counts[Status.OVERLOADED.value]
                          / total) if total else 0.0,
            "latency_p50_s": self._lat.quantile(0.50),
            "latency_p99_s": self._lat.quantile(0.99),
            "queued_mean_s": self._queued.mean,
            "queue_depth_mean": self._depth.mean,
            "queue_depth_max": (int(self._depth.max)
                                if self._depth.count else 0),
            "batches": self.batches,
            "padded_rows": self.padded_rows,
            "swaps": self.swaps,
            "swap_rollbacks": self.swap_rollbacks,
            "swaps_rolled_back": self.swaps_rolled_back,
            "hedges_fired": self.hedges_fired,
            "hedges_won": self.hedges_won,
            "hedges_suppressed": self.hedges_suppressed,
            "retries": self.retries,
            # per-phase generation view (None until the paged /
            # disaggregated path has served a request)
            "ttft_p50_s": self._ttft.quantile(0.50),
            "ttft_p99_s": self._ttft.quantile(0.99),
            "tpot_p50_s": self._tpot.quantile(0.50),
            "tpot_p99_s": self._tpot.quantile(0.99),
            "prefill_p99_s":
                self._phase.labels(phase="prefill").quantile(0.99),
            "decode_p99_s":
                self._phase.labels(phase="decode").quantile(0.99),
            "kv_pages_total": int(self._kv_total.value),
            "kv_pages_free": int(self._kv_free.value),
            "kv_occupancy": float(self._kv_occupancy.value),
            "flops_total": gpc["flops_total"],
            "model_flops_per_sec": gpc["model_flops_per_sec"],
            "serving_mfu": gpc["mfu"],
            "tenants": self.tenants(),
        }

    def to_summary(self, summary, step: int):
        """Write the snapshot's numeric fields as scalar events (tags
        ``serving/<field>``) through a ``visualization.summary.Summary``
        (e.g. :class:`~bigdl_tpu.visualization.summary.ServingSummary`).
        """
        for key, val in self.snapshot().items():
            if not isinstance(val, (int, float)):
                continue
            summary.add_scalar(f"serving/{key}", float(val), step)
        return summary

    def to_prometheus(self) -> str:
        """Prometheus text exposition of the backing registry."""
        return self.registry.to_prometheus()
