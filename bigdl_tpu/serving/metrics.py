"""Per-request serving metrics: counters + latency quantiles.

Counts every terminal status (a shed request increments ``shed`` and
nothing else — never a silent drop), tracks queue depth at admission,
and keeps a bounded window of per-request latencies for p50/p99.
``to_summary`` exports the snapshot through the tensorboard-compatible
``visualization.summary`` writer so serving health lands next to the
training curves.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

import numpy as np

from .status import Status

#: latency window — big enough for stable p99, bounded so a long-lived
#: server never grows without limit
_WINDOW = 8192


class ServingMetrics:
    def __init__(self, window: int = _WINDOW):
        self._lock = threading.Lock()
        self._lat = deque(maxlen=window)       # OK latencies (seconds)
        self._queued = deque(maxlen=window)    # OK queued portions
        self._depth = deque(maxlen=window)     # queue depth at admission
        self.counts: Dict[str, int] = {s.value: 0 for s in Status}
        self.batches = 0
        self.padded_rows = 0
        self.swaps = 0
        self.swap_rollbacks = 0

    # ------------------------------------------------------------------
    def record(self, status: Status, latency_s: float = 0.0,
               queued_s: float = 0.0):
        with self._lock:
            self.counts[status.value] += 1
            if status is Status.OK:
                self._lat.append(latency_s)
                self._queued.append(queued_s)

    def record_depth(self, depth: int):
        with self._lock:
            self._depth.append(depth)

    def record_batch(self, real: int, bucket: int):
        with self._lock:
            self.batches += 1
            self.padded_rows += bucket - real

    # ------------------------------------------------------------------
    def _pct(self, q: float) -> Optional[float]:
        return float(np.percentile(self._lat, q)) if self._lat else None

    def snapshot(self) -> dict:
        with self._lock:
            ok = self.counts[Status.OK.value]
            total = sum(self.counts.values())
            return {
                "served_ok": ok,
                "total": total,
                "shed": self.counts[Status.OVERLOADED.value],
                "deadline_exceeded":
                    self.counts[Status.DEADLINE_EXCEEDED.value],
                "unavailable": self.counts[Status.UNAVAILABLE.value],
                "internal_error":
                    self.counts[Status.INTERNAL_ERROR.value],
                "cancelled": self.counts[Status.CANCELLED.value],
                "shed_rate": (self.counts[Status.OVERLOADED.value]
                              / total) if total else 0.0,
                "latency_p50_s": self._pct(50),
                "latency_p99_s": self._pct(99),
                "queued_mean_s": (float(np.mean(self._queued))
                                  if self._queued else None),
                "queue_depth_mean": (float(np.mean(self._depth))
                                     if self._depth else None),
                "queue_depth_max": (int(max(self._depth))
                                    if self._depth else 0),
                "batches": self.batches,
                "padded_rows": self.padded_rows,
                "swaps": self.swaps,
                "swap_rollbacks": self.swap_rollbacks,
            }

    def to_summary(self, summary, step: int):
        """Write the snapshot's numeric fields as scalar events (tags
        ``serving/<field>``) through a ``visualization.summary.Summary``
        (e.g. :class:`~bigdl_tpu.visualization.summary.ServingSummary`).
        """
        for key, val in self.snapshot().items():
            if val is None:
                continue
            summary.add_scalar(f"serving/{key}", float(val), step)
        return summary
