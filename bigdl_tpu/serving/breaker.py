"""Circuit breaker around the compiled serving step.

States (the classic taxonomy):

* **closed** — requests flow; consecutive failures are counted.
* **open** — tripped: every batch is rejected fast with
  ``Status.UNAVAILABLE`` (degrade, don't crash) until
  ``reset_timeout`` elapses.
* **half-open** — after the timeout, ONE probe batch is admitted to
  test recovery: success closes the breaker, failure re-opens it (and
  restarts the timeout).

Failure classification rides :class:`resilience.retry.RetryPolicy`:
*fatal* errors (the step will fail identically on every replay —
shape errors, OOM) trip the breaker immediately; *retryable* ones
(flaky device, transient runtime error) count toward
``failure_threshold`` first.  The clock is injectable so tests drive
open→half-open transitions deterministically.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


def _count_transition(to_state: str, owner: Optional[str] = None):
    """Breaker state transitions land in the process-wide telemetry
    registry (docs/observability.md) — labeled by destination state —
    and in the change journal, scoped to the owning replica when the
    breaker has one (the router stamps ``owner`` on construction)."""
    from ..telemetry.events import record_change
    from ..telemetry.registry import default_registry

    default_registry().counter(
        "bigdl_breaker_transitions_total",
        "circuit breaker state transitions",
        labels=("to",)).labels(to=to_state).inc()
    record_change(f"breaker_{to_state}", source="serving.breaker",
                  replica=owner)

#: acquire() verdicts
ADMIT = "admit"
PROBE = "probe"
REJECT = "reject"


class CircuitBreaker:
    def __init__(self, failure_threshold: int = 3,
                 reset_timeout: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout = float(reset_timeout)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self.trips = 0        # closed/half-open -> open transitions
        self.recoveries = 0   # half-open probe successes
        #: the replica this breaker guards (the router stamps it so
        #: journal events carry a replica scope); None = anonymous
        self.owner: Optional[str] = None

    # ------------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def acquire(self) -> str:
        """Gate one batch: ``ADMIT`` (closed), ``PROBE`` (half-open,
        single in-flight probe granted), or ``REJECT`` (open, or a
        probe is already out)."""
        with self._lock:
            if self._state == CLOSED:
                return ADMIT
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.reset_timeout:
                    return REJECT
                self._state = HALF_OPEN
                self._probe_in_flight = False
                _count_transition("half_open", self.owner)
            # half-open: one probe at a time
            if self._probe_in_flight:
                return REJECT
            self._probe_in_flight = True
            return PROBE

    def record_success(self):
        with self._lock:
            if self._state == HALF_OPEN:
                self.recoveries += 1
                _count_transition("closed", self.owner)
            self._state = CLOSED
            self._consecutive_failures = 0
            self._probe_in_flight = False

    def record_failure(self, fatal: bool = False):
        """One step failure.  A failed half-open probe re-opens
        immediately; in closed state, ``fatal`` (or reaching
        ``failure_threshold`` consecutive retryables) trips."""
        with self._lock:
            self._consecutive_failures += 1
            trip = (fatal or self._state == HALF_OPEN
                    or self._consecutive_failures >= self.failure_threshold)
            self._probe_in_flight = False
            if trip and self._state != OPEN:
                self._state = OPEN
                self._opened_at = self._clock()
                self.trips += 1
                _count_transition("open", self.owner)

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "trips": self.trips,
                "recoveries": self.recoveries,
            }
