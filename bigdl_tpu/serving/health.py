"""Per-replica SLO health: the fleet-side consumer of the online
health engine.

The router already ejects replicas that go SILENT (missed heartbeats)
or say so themselves (breaker open); what it could not see before
this module is a replica that keeps answering but answers BADLY — a
p99 drifting 10x above its peers, an error rate quietly burning the
budget.  The :class:`FleetHealthMonitor` closes that gap: each pump
round it feeds every replica's published health snapshot into a
:class:`~bigdl_tpu.telemetry.timeseries.MetricRecorder` (per-replica
labeled series), evaluates per-replica SLO rules
(:class:`~bigdl_tpu.telemetry.slo.SloEngine`), and on a firing rule
marks the replica **degraded** on the router —
:meth:`~.router.FleetRouter.mark_degraded`, which feeds the existing
eject machinery (eviction marker + incarnation bump, exactly the
breaker-open path).  When the rule resolves, the mark clears and the
replica re-admits through the normal returner path.

Rules are instantiated per replica from a template
(:class:`ReplicaHealthPolicy`) as replicas join (autoscaler
scale-ups included) and retired with them.  A replica whose health
feed goes DEAD (killed, partitioned) trips the ``absent`` dead-man
rule — alert-visible even before the heartbeat timeout ejects it.
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..telemetry import metric_names as M
from ..telemetry.slo import SloEngine, SloRule
from ..telemetry.timeseries import MetricRecorder

log = logging.getLogger("bigdl_tpu")

__all__ = ["FleetHealthMonitor", "ReplicaHealthPolicy"]


@dataclass
class ReplicaHealthPolicy:
    """Per-replica degradation thresholds (the rule template)."""
    #: p99 above this for ``for_intervals`` pump rounds ⇒ degraded
    p99_high_s: float = 2.0
    #: non-OK fraction of the replica's fresh traffic burning this
    #: error budget at >= ``burn_factor`` in both windows ⇒ degraded
    error_budget: float = 0.05
    burn_factor: float = 2.0
    fast_window_s: float = 15.0
    slow_window_s: float = 120.0
    #: health feed silent this long (while the series exists) ⇒ the
    #: dead-man alert fires (the router's heartbeat timeout still owns
    #: the eject for true deaths — this is alert visibility)
    feed_dead_s: float = 5.0
    window_s: float = 30.0
    for_intervals: int = 2
    resolve_intervals: int = 2


class FleetHealthMonitor:
    """Feeds published replica health into an SLO engine and acts on
    the verdicts — see the module docstring.

    Parameters
    ----------
    fleet : the :class:`~.fleet.ServingFleet` (pump loop calls
        :meth:`observe` once per round).
    policy : the per-replica rule template.
    registry : where alert counters land (defaults to the router's
        metrics registry, so ``bigdl_alerts_total`` folds into the
        fleet view).
    mark_degraded : whether firing rules actuate the router (False =
        observe-only: alerts fire, routing untouched).
    """

    def __init__(self, fleet, policy: Optional[ReplicaHealthPolicy]
                 = None, registry=None, mark_degraded: bool = True,
                 clock: Optional[Callable[[], float]] = None,
                 incidents: bool = False,
                 incident_policy=None):
        self.fleet = fleet
        self.policy = policy or ReplicaHealthPolicy()
        self.mark_degraded = bool(mark_degraded)
        self._clock = clock or getattr(fleet, "_clock", time.monotonic)
        self.recorder = MetricRecorder(clock=self._clock)
        self.engine = SloEngine(
            self.recorder,
            registry=(registry if registry is not None
                      else fleet.router.metrics.registry),
            clock=self._clock)
        #: optional incident engine (telemetry/incidents.py): built
        #: over this monitor's recorder + engine so a firing replica
        #: rule freezes its black box and ranks the change journal
        self.incidents = None
        if incidents:
            from ..telemetry.incidents import IncidentEngine
            self.incidents = IncidentEngine(
                self.recorder, engine=self.engine,
                policy=incident_policy,
                registry=self.engine.registry, clock=self._clock)
        #: replica -> its rule names (installed lazily on first feed)
        self._replica_rules: Dict[str, List[str]] = {}
        #: replica -> the label set its rules/series were installed
        #: under (includes ``model`` on multi-tenant fleets)
        self._replica_labels: Dict[str, Dict[str, str]] = {}
        #: last-seen health publish stamp per replica — a KV snapshot
        #: that stopped CHANGING is a dead feed, however fresh the
        #: router's last read of it looks
        self._last_ts: Dict[str, float] = {}
        #: marks THIS monitor placed (never clear someone else's)
        self._marked: Dict[str, bool] = {}

    # ------------------------------------------------------------ rules
    def _rules_for(self, rid: str,
                   model: Optional[str] = None) -> List[SloRule]:
        p = self.policy
        # multi-tenant fleets label the replica's rules (and therefore
        # its alerts) with the model it advertises, so a firing rule
        # attributes to ONE tenant — and since a replica serves one
        # model, marking it degraded ejects capacity from that tenant
        # only, never unrouting the other tenants' replicas
        L = {"replica": rid}
        if model is not None:
            L = {"replica": rid, "model": str(model)}
        return [
            SloRule(name=f"replica/{rid}/p99",
                    family=M.REPLICA_P99_SECONDS, labels=L,
                    kind="threshold", reduce="last", op=">=",
                    threshold=p.p99_high_s, window_s=p.window_s,
                    for_intervals=p.for_intervals,
                    resolve_intervals=p.resolve_intervals,
                    description=f"replica {rid} p99 >= "
                                f"{p.p99_high_s}s"),
            SloRule(name=f"replica/{rid}/error_budget",
                    family=M.REPLICA_ERRORS_TOTAL, labels=L,
                    total_family=M.REPLICA_REQUESTS_TOTAL,
                    total_labels=L, kind="burn_rate",
                    budget=p.error_budget,
                    fast_window_s=p.fast_window_s,
                    slow_window_s=p.slow_window_s,
                    burn_factor=p.burn_factor,
                    for_intervals=p.for_intervals,
                    resolve_intervals=p.resolve_intervals,
                    description=f"replica {rid} burning its "
                                f"{100 * p.error_budget:g}% error "
                                f"budget"),
            SloRule(name=f"replica/{rid}/health_feed",
                    family=M.REPLICA_P99_SECONDS, labels=L,
                    kind="absent", window_s=p.feed_dead_s,
                    resolve_intervals=1, severity="ticket",
                    description=f"replica {rid} health feed went "
                                f"silent"),
        ]

    def _ensure_rules(self, rid: str, model: Optional[str] = None):
        if rid in self._replica_rules:
            return
        rules = self._rules_for(rid, model=model)
        for rule in rules:
            self.engine.add_rule(rule)
        self._replica_rules[rid] = [r.name for r in rules]
        self._replica_labels[rid] = dict(rules[0].labels)

    def _retire_rules(self, rid: str):
        for name in self._replica_rules.pop(rid, ()):
            self.engine.remove_rule(name)
        self._replica_labels.pop(rid, None)
        self._last_ts.pop(rid, None)
        if self._marked.pop(rid, None):
            self.fleet.router.clear_degraded(rid)

    # ------------------------------------------------------------ observe
    def observe(self, now: Optional[float] = None) -> List[dict]:
        """One pump round: feed fresh health snapshots, evaluate, and
        actuate the router marks.  Returns this round's alert
        transitions (as dicts)."""
        now = self._clock() if now is None else now
        router = self.fleet.router
        live_rids = set(self.fleet.servers)
        for rid in sorted(self._replica_rules.keys() - live_rids):
            self._retire_rules(rid)    # autoscale retire / removal
        for rid in sorted(live_rids):
            h = router.health_of(rid)
            if not h:
                continue
            ts = float(h.get("ts") or 0.0)
            if self._last_ts.get(rid) == ts:
                continue               # feed stopped: let it go stale
            self._last_ts[rid] = ts
            self._ensure_rules(rid, model=h.get("model"))
            L = self._replica_labels[rid]
            r = self.recorder
            if h.get("p99_s") is not None:
                r.observe(M.REPLICA_P99_SECONDS, float(h["p99_s"]),
                          labels=L, now=now)
            r.observe(M.REPLICA_QUEUE_DEPTH,
                      float(h.get("queue_depth") or 0), labels=L,
                      now=now)
            total = float(h.get("requests_total") or 0)
            errors = max(0.0, total - float(h.get("served_ok") or 0))
            r.observe(M.REPLICA_REQUESTS_TOTAL, total, labels=L,
                      kind="counter", now=now)
            r.observe(M.REPLICA_ERRORS_TOTAL, errors, labels=L,
                      kind="counter", now=now)
        emitted = self.engine.evaluate(now=now)
        self._actuate()
        out = [a.to_dict() for a in emitted]
        if self.incidents is not None:
            # chain the incident engine on this round's transitions:
            # a fresh firing opens + freezes its capture window here
            self.incidents.observe(out, now=now)
        return out

    def _actuate(self):
        if not self.mark_degraded:
            return
        firing_by_rid: Dict[str, List[dict]] = {}
        for alert in self.engine.firing():
            rid = alert["labels"].get("replica")
            if rid is not None:
                firing_by_rid.setdefault(rid, []).append(alert)
        router = self.fleet.router
        for rid in list(self._replica_rules):
            firing = firing_by_rid.get(rid)
            if firing and not self._marked.get(rid):
                reason = "; ".join(a["rule"] for a in firing)
                router.mark_degraded(rid, reason)
                self._marked[rid] = True
            elif not firing and self._marked.get(rid):
                router.clear_degraded(rid)
                self._marked[rid] = False

    # ------------------------------------------------------------ reading
    def degraded(self) -> Dict[str, str]:
        """Replicas this monitor currently holds degraded."""
        return {rid: reason
                for rid, reason in self.fleet.router.degraded.items()
                if self._marked.get(rid)}

    def snapshot(self) -> dict:
        return {"engine": self.engine.snapshot(),
                "degraded": self.degraded(),
                "replicas_watched": sorted(self._replica_rules),
                "incidents": (self.incidents.snapshot()
                              if self.incidents is not None else None)}
