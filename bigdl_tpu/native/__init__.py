"""ctypes loader for the C++ host runtime (native/bigdl_tpu_native.cc) —
the TPU build's counterpart of the reference's BigDL-core JNI layer
(SURVEY §2.1): CRC32C, bf16 wire codec with compressed-domain add, and
the multithreaded image batcher.

The .so is built by ``make -C native`` (g++ is in the image).  If it is
missing, the loader builds it once on first import; if that fails (no
toolchain), every entry point falls back to a numpy implementation with
identical semantics — the library is an accelerator, never a hard dep.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

_SO_PATH = os.path.join(os.path.dirname(__file__), "libbigdl_tpu_native.so")
_SRC_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _SRC_DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except Exception as e:  # toolchain absent / build error
        log.debug("native build failed: %s", e)
        return False


def _load() -> Optional[ctypes.CDLL]:
    if not os.path.exists(_SO_PATH) and not _build():
        return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError as e:
        log.warning("could not load %s: %s", _SO_PATH, e)
        return None
    lib.btpu_crc32c.restype = ctypes.c_uint32
    lib.btpu_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                ctypes.c_uint32]
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    u16p = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    lib.btpu_f32_to_bf16.argtypes = [f32p, u16p, ctypes.c_int64]
    lib.btpu_bf16_to_f32.argtypes = [u16p, f32p, ctypes.c_int64]
    lib.btpu_bf16_add.argtypes = [u16p, u16p, ctypes.c_int64]
    lib.btpu_batch_images_u8.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        f32p, f32p, f32p]
    lib.btpu_batch_images_f32.argtypes = [
        f32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        f32p, f32p, f32p]
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.btpu_parse_records.restype = ctypes.c_int64
    lib.btpu_parse_records.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, i64p, i64p, ctypes.c_int64,
        ctypes.c_int]
    lib.btpu_num_threads.restype = ctypes.c_int
    return lib


_lib = None
_load_attempted = False


def _get_lib() -> Optional[ctypes.CDLL]:
    """Lazy load on first use — import of the package must not spawn a
    compiler subprocess or block on disk."""
    global _lib, _load_attempted
    if not _load_attempted:
        _load_attempted = True
        _lib = _load()
    return _lib


def available() -> bool:
    """reference MKL.isMKLLoaded analogue (tensor/Tensor.scala:689)."""
    return _get_lib() is not None


def num_threads() -> int:
    lib = _get_lib()
    return lib.btpu_num_threads() if lib else 1


# ---------------------------------------------------------------------------
# CRC32C
# ---------------------------------------------------------------------------

def crc32c(data: bytes, crc: int = 0) -> int:
    lib = _get_lib()
    if lib is not None:
        if not isinstance(data, (bytes, bytearray)):
            # ctypes c_char_p takes bytes only; memoryview callers (the
            # zero-copy record walk) pay one slice-local copy here
            data = bytes(data)
        return lib.btpu_crc32c(data, len(data), crc)
    from ..visualization.crc32c import crc32c as py_crc

    return py_crc(data, crc)


# ---------------------------------------------------------------------------
# bf16 wire codec (FP16CompressedTensor parity, reference
# parameters/FP16CompressedTensor.scala — fp32 truncated to its high two
# bytes IS the bf16 bit pattern; native TPU dtype, SURVEY §2.1)
# ---------------------------------------------------------------------------

def f32_to_bf16(src: np.ndarray) -> np.ndarray:
    src = np.ascontiguousarray(src, np.float32)
    out = np.empty(src.size, np.uint16)
    lib = _get_lib()
    if lib is not None:
        lib.btpu_f32_to_bf16(src.ravel(), out, src.size)
    else:
        bits = src.ravel().view(np.uint32).astype(np.uint64)
        rounding = 0x7FFF + ((bits >> 16) & 1)
        trunc = ((bits + rounding) >> 16).astype(np.uint32)
        nan = (bits & 0x7F800000 == 0x7F800000) & (bits & 0x007FFFFF != 0)
        out[:] = np.where(nan, (bits >> 16) | 0x0040,
                          trunc).astype(np.uint16)
    return out.reshape(src.shape)


def bf16_to_f32(src: np.ndarray) -> np.ndarray:
    src = np.ascontiguousarray(src, np.uint16)
    out = np.empty(src.size, np.float32)
    lib = _get_lib()
    if lib is not None:
        lib.btpu_bf16_to_f32(src.ravel(), out, src.size)
    else:
        out[:] = (src.ravel().astype(np.uint32) << 16).view(np.float32)
    return out.reshape(src.shape)


def bf16_add(dst: np.ndarray, src: np.ndarray) -> np.ndarray:
    """dst += src in the compressed domain (parAdd parity).  Mutates and
    returns ``dst``."""
    assert dst.dtype == np.uint16 and src.dtype == np.uint16
    assert dst.size == src.size
    lib = _get_lib()
    if lib is not None and dst.flags.c_contiguous:
        lib.btpu_bf16_add(dst, np.ascontiguousarray(src).ravel(), dst.size)
    else:
        s = bf16_to_f32(dst) + bf16_to_f32(src)
        dst[...] = f32_to_bf16(s)
    return dst


# ---------------------------------------------------------------------------
# record-file framing scan (ingest hot loop)
# ---------------------------------------------------------------------------

def parse_records(buf, verify: bool = True):
    """Scan a TFRecord-framed buffer → list of (offset, length) payload
    spans, CRC-verified natively.  ``buf`` may be bytes OR any readable
    buffer (memoryview over an mmap — the zero-copy ingest path).
    Returns None when the native library is unavailable (caller falls
    back to the python scanner); raises IOError on corruption."""
    lib = _get_lib()
    if lib is None:
        return None
    cap = max(1, len(buf) // 16)
    offsets = np.empty(cap, np.int64)
    lengths = np.empty(cap, np.int64)
    if isinstance(buf, bytes):
        ptr = buf
    else:
        arr = np.frombuffer(buf, np.uint8)
        ptr = ctypes.cast(arr.ctypes.data_as(ctypes.c_void_p),
                          ctypes.c_char_p)
    n = lib.btpu_parse_records(ptr, len(buf), offsets, lengths, cap,
                               1 if verify else 0)
    if n < 0:
        raise IOError(f"corrupt record at byte {-n - 1}")
    return list(zip(offsets[:n].tolist(), lengths[:n].tolist()))


# ---------------------------------------------------------------------------
# multithreaded batch assembly (MTLabeledBGRImgToBatch parity)
# ---------------------------------------------------------------------------

def batch_images(images: np.ndarray, mean, std) -> np.ndarray:
    """(N, H, W, C) uint8/float HWC images -> normalized (N, C, H, W)
    float32 batch, assembled across the native thread pool."""
    n, h, w, c = images.shape
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    assert mean.size == c and std.size == c
    out = np.empty(n * c * h * w, np.float32)
    lib = _get_lib()
    if lib is not None and images.dtype == np.uint8:
        lib.btpu_batch_images_u8(np.ascontiguousarray(images).reshape(-1),
                                 n, h, w, c, mean, std, out)
    elif lib is not None:
        lib.btpu_batch_images_f32(
            np.ascontiguousarray(images, np.float32).reshape(-1),
            n, h, w, c, mean, std, out)
    else:
        normed = (images.astype(np.float32) - mean) / std
        out[:] = np.transpose(normed, (0, 3, 1, 2)).ravel()
    return out.reshape(n, c, h, w)
