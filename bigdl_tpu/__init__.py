"""bigdl_tpu — a TPU-native deep learning framework.

A ground-up JAX/XLA rebuild of the capabilities of the reference
BigDL-era framework (Torch-style layers, Optimizer lifecycle,
DataSet/Transformer pipeline, synchronous distributed SGD) designed
TPU-first: one jitted train step, pjit/shard_map parallelism over a
device mesh, XLA collectives instead of a block-manager all-reduce.
"""

__version__ = "0.2.0"
