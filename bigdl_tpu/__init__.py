"""bigdl_tpu — a TPU-native deep learning framework.

A ground-up JAX/XLA rebuild of the capabilities of the reference
BigDL-era framework (Torch-style layers, Optimizer lifecycle,
DataSet/Transformer pipeline, synchronous distributed SGD) designed
TPU-first: one jitted train step, pjit/shard_map parallelism over a
device mesh, XLA collectives instead of a block-manager all-reduce.
"""

__version__ = "0.2.0"

# Default logging: one stderr handler with the canonical format, unless
# the embedding application already configured handlers (then this is a
# no-op).  Library modules themselves never call logging.basicConfig —
# the observability lint in tests/test_determinism.py enforces it.
from .telemetry.slog import configure_logging as _configure_logging

_configure_logging()
del _configure_logging
