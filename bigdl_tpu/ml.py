"""ML-pipeline estimators (reference org/apache/spark/ml/DLEstimator.scala:53,
DLClassifier.scala:36 over the DLEstimatorBase version shim — SURVEY §1.7).

The reference plugs training into Spark ML's Estimator/Transformer
pipeline contract (fit(DataFrame) → Model, Model.transform(DataFrame)).
TPU-native equivalent: the same fit/transform lifecycle over host arrays
(or any iterable of rows) — scikit-learn-shaped, no Spark session.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .dataset import Sample
from .dataset.dataset import array
from .optim.optimizer import LocalOptimizer
from .optim.trigger import max_epoch


class DLEstimator:
    """Trains ``model`` against ``criterion`` on (features, labels) arrays
    and yields a :class:`DLModel` (reference DLEstimator.scala:53 —
    featureSize/labelSize fix the per-row tensor shapes).
    """

    def __init__(self, model, criterion, feature_size: Sequence[int],
                 label_size: Sequence[int]):
        self.model = model
        self.criterion = criterion
        self.feature_size = tuple(feature_size)
        self.label_size = tuple(label_size)
        self.batch_size = 32
        self.max_epoch = 10
        self.learning_rate = 0.1
        self.optim_method = None

    # fluent setters follow the reference's Params (DLEstimator.scala:60-90)
    def set_batch_size(self, v: int):
        self.batch_size = v
        return self

    def set_max_epoch(self, v: int):
        self.max_epoch = v
        return self

    def set_learning_rate(self, v: float):
        self.learning_rate = v
        return self

    def set_optim_method(self, method):
        self.optim_method = method
        return self

    def _make_samples(self, features, labels):
        return [Sample(np.asarray(f, np.float32).reshape(self.feature_size),
                       np.asarray(l, np.float32).reshape(self.label_size))
                for f, l in zip(features, labels)]

    def fit(self, features, labels) -> "DLModel":
        from .optim.optim_method import SGD

        samples = self._make_samples(features, labels)
        opt = LocalOptimizer(self.model, array(samples), self.criterion,
                             batch_size=self.batch_size)
        opt.set_optim_method(self.optim_method
                             or SGD(learning_rate=self.learning_rate))
        opt.set_end_when(max_epoch(self.max_epoch))
        trained = opt.optimize()
        return DLModel(trained, self.feature_size,
                       batch_size=self.batch_size)


class DLModel:
    """Inference transformer (reference DLEstimator.scala:155 DLModel)."""

    def __init__(self, model, feature_size: Sequence[int],
                 batch_size: int = 32):
        self.model = model
        self.feature_size = tuple(feature_size)
        self.batch_size = batch_size

    def transform(self, features) -> np.ndarray:
        """Row-wise forward; returns stacked predictions."""
        from .optim.predictor import Predictor

        samples = [Sample(np.asarray(f, np.float32).reshape(self.feature_size),
                          np.float32(0)) for f in features]
        outs = Predictor(self.model).predict(array(samples),
                                             batch_size=self.batch_size)
        return np.stack([np.asarray(o) for o in outs])


class DLClassifier(DLEstimator):
    """Classification specialization (reference DLClassifier.scala:36):
    scalar 1-based class labels, argmax predictions."""

    def __init__(self, model, criterion, feature_size: Sequence[int]):
        super().__init__(model, criterion, feature_size, (1,))

    def fit(self, features, labels) -> "DLClassifierModel":
        base = super().fit(features, labels)
        return DLClassifierModel(base.model, self.feature_size,
                                 batch_size=self.batch_size)


class DLClassifierModel(DLModel):
    """reference DLClassifier.scala:63 — transform emits class ids."""

    def transform(self, features) -> np.ndarray:
        probs = super().transform(features)
        return probs.reshape(probs.shape[0], -1).argmax(axis=1) + 1
