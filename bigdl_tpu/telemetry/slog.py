"""Structured logging entry points for the library.

Library modules must never configure the root logger at import time
(module-level ``logging.basicConfig`` hijacks the embedding
application's logging — the print/basicConfig lint in
tests/test_determinism.py enforces this); they call
:func:`get_logger` and leave configuration to the application.
:func:`configure_logging` is the one sanctioned knob: applications
(and the package's own examples/bench entry points) call it once, and
it respects any handlers the host process already installed.
"""
from __future__ import annotations

import logging

__all__ = ["configure_logging", "get_logger"]

_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


def get_logger(name: str = "bigdl_tpu") -> logging.Logger:
    """The library logger (children via ``get_logger("bigdl_tpu.x")``)."""
    return logging.getLogger(name)


def configure_logging(level: int = logging.INFO,
                      force: bool = False) -> bool:
    """Install a basic stderr handler + format on the root logger —
    unless the application already configured one (``force=True``
    overrides).  Returns True when configuration was applied."""
    root = logging.getLogger()
    if root.handlers and not force:
        return False
    logging.basicConfig(level=level, format=_FORMAT, force=force)
    return True
