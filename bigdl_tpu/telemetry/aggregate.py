"""Cross-host telemetry aggregation over the elastic KV transport.

Every host periodically publishes its telemetry payload (metrics
snapshot + goodput ledger + span-category totals) under
``tm/<incarnation>/<host>`` — incarnation-keyed exactly like the SDC
votes, so a post-reconfiguration cluster view never mixes in snapshots
from a membership that no longer exists.  The leader collects the
newest payload per member and merges them into one cluster view:

* counters sum; gauges report per-host values plus min/mean/max;
* histograms with identical bucket geometry merge by adding bucket
  counts (the :class:`~.registry.Histogram` merge contract);
* goodput ledgers sum per-category host-seconds
  (:meth:`~.goodput.GoodputLedger.merge_snapshots`);
* per-host step-time skew is derived from each host's published
  ``bigdl_train_step_seconds`` mean vs the cluster median.

The same payloads also serialize to a **snapshot directory** (one
``<host>.json`` per host) — what ``tools/run_report.py`` renders.
"""
from __future__ import annotations

import json
import os
import statistics
from typing import Dict, List, Optional, Sequence

from .goodput import GoodputLedger

__all__ = [
    "TM_PREFIX", "collect_snapshots", "merge_alerts", "merge_cluster",
    "merge_incidents", "merge_metrics", "merge_perf",
    "merge_timeline", "metrics_to_prometheus", "publish_snapshot",
    "read_snapshot_dir", "write_snapshot",
]

TM_PREFIX = "tm/"


# ---------------------------------------------------------------------------
# transport plumbing
# ---------------------------------------------------------------------------

def publish_snapshot(transport, host: str, payload: dict,
                     incarnation: int = 0):
    """Publish one host's telemetry payload for the current
    incarnation (overwrites the host's previous snapshot — the view is
    "newest per host", not a journal)."""
    transport.put(f"{TM_PREFIX}{int(incarnation)}/{host}",
                  json.dumps(payload))


def collect_snapshots(transport, incarnation: int = 0,
                      members: Optional[Sequence[str]] = None
                      ) -> Dict[str, dict]:
    """The leader's read side: newest payload per host for the given
    incarnation (restricted to ``members`` when given — a departed
    host's stale snapshot must not haunt the cluster view)."""
    prefix = f"{TM_PREFIX}{int(incarnation)}/"
    out: Dict[str, dict] = {}
    for key in transport.keys(prefix):
        host = key[len(prefix):]
        if members is not None and host not in members:
            continue
        raw = transport.get(key)
        if raw is None:
            continue
        try:
            out[host] = json.loads(raw)
        except ValueError:
            continue
    return out


# ---------------------------------------------------------------------------
# snapshot directories (the run-report input)
# ---------------------------------------------------------------------------

def write_snapshot(directory: str, host: str, payload: dict) -> str:
    """Write one host's payload as ``<dir>/<host>.json`` (atomic:
    tmp + rename, same discipline as FileKV)."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{host}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, path)
    return path


def read_snapshot_dir(directory: str) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    if not os.path.isdir(directory):
        return out
    for name in sorted(os.listdir(directory)):
        if not name.endswith(".json") or ".tmp." in name:
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                out[name[:-len(".json")]] = json.load(f)
        except (OSError, ValueError):
            continue
    return out


# ---------------------------------------------------------------------------
# merging
# ---------------------------------------------------------------------------

def merge_metrics(metric_snaps: Sequence[dict]) -> dict:
    """Fold per-host ``MetricsRegistry.snapshot()['metrics']`` dicts
    into one cluster view.  Series are keyed by (name, labels);
    counters sum, histograms bucket-add (mismatched geometry falls back
    to count/sum only), gauges keep min/mean/max across hosts."""
    out: dict = {}
    for snap in metric_snaps:
        for name, fam in (snap or {}).items():
            dst = out.setdefault(name, {"type": fam.get("type"),
                                        "help": fam.get("help"),
                                        "series": {}})
            for series in fam.get("series", ()):
                key = json.dumps(series.get("labels") or {},
                                 sort_keys=True)
                cur = dst["series"].get(key)
                if cur is None:
                    dst["series"][key] = _copy_series(series,
                                                      fam.get("type"))
                else:
                    _fold_series(cur, series, fam.get("type"))
    # dict-of-series back to the list shape snapshots use
    for fam in out.values():
        fam["series"] = [
            dict(s, labels=json.loads(k))
            for k, s in sorted(fam["series"].items())]
    return out


def _copy_series(series: dict, kind: str) -> dict:
    s = {k: v for k, v in series.items() if k != "labels"}
    if kind == "gauge":
        s["per_host_values"] = [series.get("value", 0.0)]
    return s


def _fold_series(cur: dict, series: dict, kind: str):
    if kind == "counter":
        cur["value"] = cur.get("value", 0.0) + series.get("value", 0.0)
    elif kind == "gauge":
        vals = cur.setdefault("per_host_values", [cur.get("value", 0.0)])
        vals.append(series.get("value", 0.0))
        cur["value"] = max(vals)
        cur["min"] = min(vals)
        cur["mean"] = sum(vals) / len(vals)
    elif kind == "histogram":
        cur["count"] = cur.get("count", 0) + series.get("count", 0)
        cur["sum"] = cur.get("sum", 0.0) + series.get("sum", 0.0)
        mins = [m for m in (cur.get("min"), series.get("min"))
                if m is not None]
        maxs = [m for m in (cur.get("max"), series.get("max"))
                if m is not None]
        cur["min"] = min(mins) if mins else None
        cur["max"] = max(maxs) if maxs else None
        if cur.get("bounds") == series.get("bounds") and \
                cur.get("buckets") and series.get("buckets"):
            cur["buckets"] = [a + b for a, b in zip(cur["buckets"],
                                                    series["buckets"])]
            # exemplars DO merge: a trace id is a fleet-wide pointer
            # (the trc/ fragments live on the shared transport, not in
            # a per-host store), so the merged bucket keeps the NEWEST
            # exemplar per bucket across hosts — before this fix the
            # fold silently discarded every exemplar the PR 13 tracing
            # attached, orphaning the OpenMetrics trace links in every
            # fleet-level scrape
            merged_ex = dict(cur.get("exemplars") or {})
            for idx, ex in (series.get("exemplars") or {}).items():
                have = merged_ex.get(idx)
                if have is None or float(ex.get("ts") or 0.0) \
                        >= float(have.get("ts") or 0.0):
                    merged_ex[idx] = dict(ex)
            if merged_ex:
                cur["exemplars"] = merged_ex
            else:
                cur.pop("exemplars", None)
        else:  # geometry drift: keep count/sum, drop buckets AND
            cur.pop("buckets", None)   # their per-bucket exemplars
            cur.pop("exemplars", None)
        # per-series quantiles do not merge; the cluster view keeps
        # count/sum/min/max (+ merged buckets when geometries match)
        cur.pop("p50", None)
        cur.pop("p99", None)


def metrics_to_prometheus(metrics: dict) -> str:
    """Prometheus/OpenMetrics text of a snapshot-shaped metrics dict —
    including a MERGED cluster view (:func:`merge_metrics` output), so
    the fleet-level scrape carries the folded histograms WITH their
    surviving exemplars (the round-trip the exemplar-merge fix is
    tested through).  Mirrors ``MetricsRegistry.to_prometheus``."""
    from .registry import _esc_help, _fmt_float, _label_str

    lines = []
    for name, fam in sorted((metrics or {}).items()):
        kind = fam.get("type")
        if fam.get("help"):
            lines.append(f"# HELP {name} {_esc_help(fam['help'])}")
        lines.append(f"# TYPE {name} {kind}")
        for series in fam.get("series", ()):
            labels = series.get("labels") or {}
            if kind == "histogram":
                bounds = series.get("bounds")
                buckets = series.get("buckets")
                if bounds and buckets:
                    exemplars = series.get("exemplars") or {}
                    cum = 0
                    for i, (bound, c) in enumerate(zip(
                            list(bounds) + [float("inf")], buckets)):
                        cum += c
                        le = dict(labels, le=_fmt_float(bound))
                        line = f"{name}_bucket{_label_str(le)} {cum}"
                        ex = exemplars.get(str(i), exemplars.get(i))
                        if ex is not None:
                            line += (' # {trace_id="%s"} %s'
                                     % (ex["trace_id"],
                                        _fmt_float(ex["value"])))
                        lines.append(line)
                lines.append(f"{name}_sum{_label_str(labels)} "
                             f"{_fmt_float(series.get('sum', 0.0))}")
                lines.append(f"{name}_count{_label_str(labels)} "
                             f"{series.get('count', 0)}")
            else:
                lines.append(f"{name}{_label_str(labels)} "
                             f"{_fmt_float(series.get('value', 0.0))}")
    return "\n".join(lines) + "\n"


def merge_alerts(payloads: Dict[str, dict]) -> Optional[dict]:
    """Union per-host SLO-engine snapshots (``payload["alerts"]``,
    see ``Telemetry.payload``) into one cluster alert view: every
    host's active alerts (host-stamped), recent transitions in time
    order, and per-state totals.  None when no host published an
    engine snapshot."""
    # dedupe active by (rule, host): a rule reported twice for one
    # host (overlapping snapshot collections, a re-published payload)
    # must union to ONE deterministic entry — the worst one wins
    # (severity page > ticket, then the newest fired-at time)
    def _active_rank(a: dict):
        return (1 if a.get("severity") == "page" else 0,
                a.get("since") or 0.0)

    active_by_key: Dict[tuple, dict] = {}
    recent_by_key: Dict[tuple, dict] = {}
    totals: Dict[str, int] = {}
    hosts = []
    for host, p in sorted(payloads.items()):
        snap = (p or {}).get("alerts")
        if not snap:
            continue
        hosts.append(host)
        for a in snap.get("active", ()):
            key = (a.get("rule"), host)
            cur = active_by_key.get(key)
            if cur is None or _active_rank(a) > _active_rank(cur):
                active_by_key[key] = dict(a, host=host)
        for a in snap.get("recent", ()):
            # identical transitions replayed across overlapping
            # collections dedupe exactly; conflicting states at the
            # same instant keep the worst (firing beats resolved)
            key = (a.get("rule"), host, a.get("at"))
            cur = recent_by_key.get(key)
            if cur is not None and not (
                    a.get("state") == "firing"
                    and cur.get("state") != "firing"):
                continue
            recent_by_key[key] = dict(a, host=host)
    if not hosts:
        return None
    active = [active_by_key[k] for k in sorted(
        active_by_key, key=lambda k: (str(k[0]), str(k[1])))]
    recent = sorted(recent_by_key.values(),
                    key=lambda a: (a.get("at") or 0.0,
                                   str(a.get("rule")),
                                   str(a.get("host"))))
    for a in recent:
        state = a.get("state", "?")
        totals[state] = totals.get(state, 0) + 1
    worst = "ok"
    if any(a.get("severity") == "page" for a in active):
        worst = "critical"
    elif active:
        worst = "degraded"
    return {"hosts": hosts, "active": active, "recent": recent[-64:],
            "totals": totals, "verdict": worst}


def merge_incidents(payloads: Dict[str, dict]) -> Optional[dict]:
    """Union per-host incident-engine snapshots
    (``payload["incidents"]``, see ``Telemetry.payload``) into one
    cluster incident view: every host's open and recent (finalized)
    incidents, host-stamped, deduped by (id, host), ordered by opened
    time.  None when no host published an engine snapshot."""
    open_by_key: Dict[tuple, dict] = {}
    recent_by_key: Dict[tuple, dict] = {}
    hosts = []
    opened = 0
    for host, p in sorted(payloads.items()):
        snap = (p or {}).get("incidents")
        if not snap:
            continue
        hosts.append(host)
        opened += int(snap.get("opened") or 0)
        for inc in snap.get("open", ()):
            open_by_key[(inc.get("id"), host)] = dict(inc, host=host)
        for inc in snap.get("recent", ()):
            # a finalized re-publish of a previously-open incident
            # replaces the open entry for the same (id, host)
            key = (inc.get("id"), host)
            open_by_key.pop(key, None)
            recent_by_key[key] = dict(inc, host=host)
    if not hosts:
        return None
    def order(i: dict):
        return (i.get("opened_at") or 0.0, str(i.get("id")),
                str(i.get("host")))

    return {"hosts": hosts,
            "open": sorted(open_by_key.values(), key=order),
            "recent": sorted(recent_by_key.values(), key=order),
            "opened": opened}


def host_skew(payloads: Dict[str, dict]) -> Dict[str, dict]:
    """Per-host mean step time and skew vs the cluster median, from
    each host's published ``bigdl_train_step_seconds`` histogram."""
    means: Dict[str, float] = {}
    for host, payload in payloads.items():
        fam = ((payload.get("metrics") or {})
               .get("bigdl_train_step_seconds"))
        if not fam:
            continue
        for series in fam.get("series", ()):
            count = series.get("count") or 0
            if count > 0:
                means[host] = float(series.get("sum", 0.0)) / count
                break
    if not means:
        return {}
    med = statistics.median(means.values())
    return {h: {"mean_step_s": m,
                "skew": (m / med) if med > 0 else 1.0}
            for h, m in sorted(means.items())}


def merge_perf(payloads: Dict[str, dict]) -> Optional[dict]:
    """Fold per-host ``perf`` payload sections (the PerfAccountant's
    cost-model view) into the cluster perf summary: per-host FLOP
    totals sum, cluster MFU is total flops over Σ(host wall × host
    peak), program cost entries union (identical programs on every
    data-parallel host — first publisher wins, tagged with how many
    hosts reported it), HBM watermarks keep the per-host maxima."""
    per_host = {}
    programs: dict = {}
    program_hosts: Dict[str, int] = {}
    total_flops = 0.0
    denom = 0.0  # sum over hosts of wall_s x peak_flops
    hbm_peak = None
    nominal = False
    device = None
    for host, p in sorted(payloads.items()):
        perf = p.get("perf")
        if not perf:
            continue
        dev = perf.get("device") or {}
        device = device or dev
        nominal = nominal or bool(dev.get("nominal"))
        flops = float(perf.get("flops_total") or 0.0)
        wall = float((p.get("goodput") or {}).get("wall_s") or 0.0)
        peak = dev.get("peak_flops_per_sec") or 0.0
        entry = {"flops_total": flops, "wall_s": wall}
        if wall > 0 and peak:
            entry["mfu"] = flops / wall / peak
            denom += wall * peak
        total_flops += flops
        hbm = perf.get("hbm") or {}
        if hbm.get("peak_bytes_in_use") is not None:
            entry["hbm_peak_bytes"] = hbm["peak_bytes_in_use"]
            hbm_peak = max(hbm_peak or 0.0, hbm["peak_bytes_in_use"])
            if hbm.get("bytes_limit") is not None:
                entry["hbm_limit_bytes"] = hbm["bytes_limit"]
        per_host[host] = entry
        for label, prog in (perf.get("programs") or {}).items():
            programs.setdefault(label, dict(prog))
            program_hosts[label] = program_hosts.get(label, 0) + 1
    if not per_host:
        return None
    for label, n in program_hosts.items():
        programs[label]["reporting_hosts"] = n
    out = {
        "flops_total": total_flops,
        "cluster_mfu": (total_flops / denom) if denom > 0 else None,
        "nominal_device": nominal,
        "device": device,
        "per_host": per_host,
        "programs": programs,
    }
    if hbm_peak is not None:
        out["hbm_peak_bytes"] = hbm_peak
    return out


def merge_timeline(payloads: Dict[str, dict],
                   skew: Optional[Dict[str, dict]] = None
                   ) -> Optional[dict]:
    """Fold per-host published step spans (``payload["spans"]``, see
    ``Telemetry.payload``) into ONE cluster-wide Perfetto/Chrome-trace
    timeline: one pid per host, host monotonic clocks aligned onto the
    first publishing host's via each payload's (mono, wall)
    ``clock_anchor`` pair, and the per-host step-time skew (vs the
    cluster median) stamped on each host's process metadata.  None
    when no host published spans."""
    skew = skew if skew is not None else host_skew(payloads)
    hosts = [h for h in sorted(payloads)
             if (payloads[h] or {}).get("spans")]
    if not hosts:
        return None
    ref_anchor = (payloads[hosts[0]].get("clock_anchor") or {})
    ref_delta = (ref_anchor.get("wall", 0.0)
                 - ref_anchor.get("mono", 0.0))
    events = []
    for pid, host in enumerate(hosts, start=1):
        payload = payloads[host]
        anchor = payload.get("clock_anchor") or {}
        offset = ((anchor.get("wall", 0.0) - anchor.get("mono", 0.0))
                  - ref_delta) if anchor and ref_anchor else 0.0
        meta = {"name": host, "host": host}
        if host in (skew or {}):
            meta["step_time_skew"] = skew[host].get("skew")
            meta["mean_step_s"] = skew[host].get("mean_step_s")
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": meta})
        for sp in payload.get("spans", ()):
            args = dict(sp.get("args") or {})
            args["host"] = host
            events.append({
                "name": sp["name"], "cat": sp["cat"], "ph": "X",
                "ts": (sp["start"] + offset) * 1e6,
                "dur": sp["dur"] * 1e6,
                "pid": pid, "tid": sp.get("tid", 0), "args": args,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "hosts": hosts}


def merge_cluster(payloads: Dict[str, dict]) -> dict:
    """Fold per-host telemetry payloads (host → the dict
    ``Telemetry.payload()`` publishes) into the one cluster view the
    run report renders."""
    hosts = sorted(payloads)
    goodput = GoodputLedger.merge_snapshots(
        [p.get("goodput") or {} for p in payloads.values()])
    spans: Dict[str, float] = {}
    for p in payloads.values():
        for cat, secs in (p.get("span_totals") or {}).items():
            spans[cat] = spans.get(cat, 0.0) + float(secs)
    skew = host_skew(payloads)
    # per-tenant serving fold (multi-tenant fleets: each replica's
    # "serving" section carries a tenants map — counters sum)
    tenants: Dict[str, dict] = {}
    for p in payloads.values():
        for t, rec in ((p.get("serving") or {}).get("tenants")
                       or {}).items():
            agg = tenants.setdefault(
                t, {"requests": {}, "sheds": {}, "total": 0,
                    "served_ok": 0, "shed_total": 0})
            for status, n in (rec.get("requests") or {}).items():
                agg["requests"][status] = \
                    agg["requests"].get(status, 0) + int(n)
            for reason, n in (rec.get("sheds") or {}).items():
                agg["sheds"][reason] = \
                    agg["sheds"].get(reason, 0) + int(n)
            for key in ("total", "served_ok", "shed_total"):
                agg[key] += int(rec.get(key) or 0)
    return {
        "hosts": hosts,
        "incarnation": max(
            (int(p.get("incarnation", 0)) for p in payloads.values()),
            default=0),
        "goodput": goodput,
        "metrics": merge_metrics(
            [p.get("metrics") or {} for p in payloads.values()]),
        "span_totals": dict(sorted(spans.items())),
        # per-tenant serving outcomes (empty on single-model fleets /
        # training-only runs) — tools/run_report.py renders the table
        "tenants": dict(sorted(tenants.items())),
        "per_host_skew": skew,
        "perf": merge_perf(payloads),
        # the cluster-wide Perfetto timeline (None when no host
        # published spans — the payloads' span export is bounded)
        "timeline": merge_timeline(payloads, skew=skew),
        # the cluster alert view (None when no host runs an SLO
        # engine) — tools/run_report.py --alerts renders it
        "alerts": merge_alerts(payloads),
        # the cluster incident view (None when no host runs an
        # incident engine) — tools/incident_report.py renders it
        "incidents": merge_incidents(payloads),
    }
