"""Structured step tracer — nested spans into a bounded ring buffer,
exported as Chrome-trace JSON (the format Perfetto / chrome://tracing
load directly).

Where the registry answers "how many / how long on average", the
tracer answers "what was the wall clock doing at second 83": every
driver iteration records a ``step`` span whose children attribute the
time to an explicit category — ``data_wait`` (input pipeline),
``host_to_device`` (infeed), ``compile`` (XLA build), ``compute`` /
``collective`` (the xplane phase split of a profiled step,
optim/profiling.py), ``checkpoint``, ``recovery``.  The buffer is a
ring: a week-long run keeps the most recent ``capacity`` spans instead
of growing without bound.

Spans nest two ways:

* :meth:`Tracer.span` — a context manager pushing onto a thread-local
  stack; children opened inside it are linked to it and cannot
  outlive it (closing the parent closes abandoned children).
* :meth:`Tracer.record` — retroactive insertion with explicit
  ``start``/``duration`` (and optionally an explicit ``parent``), for
  timings that are only known after the fact — e.g. the profiler's
  compute/collective split of a step that already ended.  Children
  recorded under a parent are clamped into the parent's interval, so
  the no-child-outlives-its-parent invariant holds for exports.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from .trace_context import REQUEST_CATEGORIES

__all__ = ["CATEGORIES", "STEP_CATEGORIES", "Span", "Tracer"]

#: the training-side vocabulary — everything the goodput ledger can
#: attribute a second of wall clock to, plus the profiled split of
#: on-device time
STEP_CATEGORIES = (
    "step", "data_wait", "host_to_device", "compile", "compute",
    "collective", "checkpoint", "recovery", "idle", "other",
)

#: the closed vocabulary of span categories: the training table above
#: plus the request-path table (ONE shared constant source —
#: ``telemetry.trace_context.REQUEST_CATEGORIES`` — so router, server
#: and tracer can never drift; a vocabulary lint enforces it)
CATEGORIES = STEP_CATEGORIES + REQUEST_CATEGORIES


class Span:
    __slots__ = ("id", "name", "category", "start", "end", "tid",
                 "parent_id", "args")

    def __init__(self, id: int, name: str, category: str, start: float,
                 tid: int, parent_id: Optional[int],
                 args: Optional[dict]):
        self.id = id
        self.name = name
        self.category = category
        self.start = start
        self.end: Optional[float] = None
        self.tid = tid
        self.parent_id = parent_id
        self.args = args

    @property
    def duration(self) -> float:
        return (self.end - self.start) if self.end is not None else 0.0

    def __repr__(self):
        return (f"Span({self.name!r}, cat={self.category!r}, "
                f"dur={self.duration:.6f}s)")

    def to_dict(self) -> dict:
        """JSON-serializable form — what trace fragments and telemetry
        payloads publish over the KV transport."""
        out = {"id": self.id, "name": self.name, "cat": self.category,
               "start": self.start, "dur": self.duration,
               "tid": self.tid}
        if self.parent_id is not None:
            out["parent_id"] = self.parent_id
        if self.args:
            out["args"] = dict(self.args)
        return out


class _SpanCtx:
    """Context manager for one open span (returned by Tracer.span)."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> Span:
        return self.span

    def __exit__(self, exc_type, exc, tb):
        self._tracer._close(self.span)
        return False


class Tracer:
    def __init__(self, capacity: int = 8192,
                 clock: Callable[[], float] = time.perf_counter,
                 enabled: bool = True):
        self.capacity = int(capacity)
        self._clock = clock
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._done: deque = deque(maxlen=self.capacity)
        self._local = threading.local()
        self._next_id = 0
        self.dropped = 0  # spans evicted from the ring

    # -- internals ------------------------------------------------------
    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _alloc_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def _finish(self, span: Span):
        with self._lock:
            if len(self._done) == self._done.maxlen:
                self.dropped += 1
            self._done.append(span)

    # -- recording ------------------------------------------------------
    def span(self, name: str, category: str = "other",
             **args) -> _SpanCtx:
        """Open a nested span: ``with tracer.span("step", "step") as s``.
        Children opened on the same thread while it is open are linked
        to it."""
        _check_category(category)
        stack = self._stack()
        parent = stack[-1] if stack else None
        s = Span(self._alloc_id(), str(name), category, self._clock(),
                 threading.get_ident(),
                 parent.id if parent else None, args or None)
        if self.enabled:
            stack.append(s)
        else:
            s.end = s.start  # disabled: a zero-width tombstone, not kept
        return _SpanCtx(self, s)

    def _close(self, span: Span):
        if not self.enabled and span.end is not None:
            return
        now = self._clock()
        stack = self._stack()
        # close abandoned children first (an exception can unwind past
        # a child's __exit__ only through re-entrancy bugs; be safe)
        while stack and stack[-1] is not span:
            child = stack.pop()
            child.end = now
            self._finish(child)
        if stack and stack[-1] is span:
            stack.pop()
        span.end = now
        self._finish(span)

    def record(self, name: str, category: str, start: float,
               duration: float, parent: Optional[Span] = None,
               **args) -> Optional[Span]:
        """Retroactively insert a completed span.  With ``parent``, the
        interval is clamped into the parent's so no child outlives it
        (profiler-derived children are estimates, not clock truths)."""
        if not self.enabled:
            return None
        _check_category(category)
        start = float(start)
        end = start + max(0.0, float(duration))
        if parent is not None and parent.end is not None:
            start = min(max(start, parent.start), parent.end)
            end = min(max(end, start), parent.end)
        tid = threading.get_ident()
        # one lock round trip (id alloc + ring append) — retroactive
        # records run on serving hot paths
        with self._lock:
            self._next_id += 1
            s = Span(self._next_id, str(name), category, start, tid,
                     parent.id if parent else None, args or None)
            s.end = end
            if len(self._done) == self._done.maxlen:
                self.dropped += 1
            self._done.append(s)
        return s

    @property
    def clock(self) -> Callable[[], float]:
        return self._clock

    # -- export ---------------------------------------------------------
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._done)

    def clear(self):
        with self._lock:
            self._done.clear()

    def export_spans(self, limit: Optional[int] = None) -> List[dict]:
        """The newest ``limit`` completed spans as JSON-serializable
        dicts (all of them when ``limit`` is None) — what
        ``Telemetry.payload`` publishes for the cluster timeline."""
        spans = self.spans()
        if limit is not None and len(spans) > int(limit):
            spans = spans[-int(limit):]
        return [s.to_dict() for s in spans]

    def category_totals(self) -> Dict[str, float]:
        """Seconds per category, summed over completed spans.  ``step``
        spans count their SELF time (step minus attributed children),
        so a step with profiled compute/collective children does not
        double-report."""
        spans = self.spans()
        child_sum: Dict[int, float] = {}
        for s in spans:
            if s.parent_id is not None:
                child_sum[s.parent_id] = (child_sum.get(s.parent_id, 0.0)
                                          + s.duration)
        out: Dict[str, float] = {}
        for s in spans:
            dur = s.duration
            if s.category == "step":
                dur = max(0.0, dur - child_sum.get(s.id, 0.0))
            out[s.category] = out.get(s.category, 0.0) + dur
        return out

    def to_chrome_trace(self) -> dict:
        """Chrome-trace ("Trace Event Format") JSON dict — load it in
        Perfetto (ui.perfetto.dev) or chrome://tracing.  Complete
        ("ph":"X") events, microsecond timestamps."""
        pid = os.getpid()
        events = []
        for s in self.spans():
            ev = {
                "name": s.name, "cat": s.category, "ph": "X",
                "ts": s.start * 1e6, "dur": s.duration * 1e6,
                "pid": pid, "tid": s.tid,
            }
            args = dict(s.args or {})
            args["span_id"] = s.id
            if s.parent_id is not None:
                args["parent_id"] = s.parent_id
            ev["args"] = args
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_json(self, path: str):
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)


_CATEGORY_SET = frozenset(CATEGORIES)


def _check_category(category: str):
    if category not in _CATEGORY_SET:
        raise ValueError(f"unknown span category {category!r}; one of "
                         f"{CATEGORIES}")
