"""Unified telemetry spine: metrics registry, structured step tracing,
Perfetto export, goodput accounting, cross-host aggregation.

The reproduction had grown four siloed observability fragments
(TensorBoard scalars, the xplane phase split, FlightRecorder journals,
ad-hoc serving/elastic counter bags); this package is the one spine
they hang off:

* :mod:`.registry`  — thread-safe Counter/Gauge/Histogram with label
  sets, JSON snapshots + Prometheus text export, injectable clock.
* :mod:`.tracer`    — nested spans with explicit categories into a
  bounded ring buffer, exported as Chrome-trace/Perfetto JSON.
* :mod:`.goodput`   — :class:`GoodputLedger` classifying every second
  of run wall clock (productive / compile / data-stall / checkpoint /
  recovery / idle).
* :mod:`.aggregate` — hosts publish snapshots over the elastic KV
  transport (incarnation-keyed); the leader merges a cluster view;
  snapshot directories feed ``tools/run_report.py``.
* :mod:`.slog`      — structured logging entry points (the library
  never calls ``logging.basicConfig`` at import time).

:class:`Telemetry` is the driver-facing bundle: ``Optimizer
.set_telemetry(Telemetry(...))`` wires all four optimizer mesh paths,
the serving path and the resilience hooks into the same registry,
tracer and ledger.
"""
from __future__ import annotations

import time
from typing import Optional

from .aggregate import (
    collect_snapshots, merge_alerts, merge_cluster, merge_incidents,
    merge_metrics, merge_timeline, publish_snapshot,
    read_snapshot_dir, write_snapshot,
)
from .device_info import DeviceSpec, device_spec, peak_flops_per_sec
from .events import (CHANGE_EVENT_KINDS, ChangeEvent, ChangeJournal,
                     default_journal, record_change,
                     reset_default_journal)
from .incidents import Incident, IncidentEngine, IncidentPolicy
from .goodput import GOODPUT_CATEGORIES, GoodputLedger
from .metric_names import METRIC_FAMILY_NAMES
from .perf import PerfAccountant, StepCost, classify_roofline
from .publish import BackgroundPublisher
from .registry import (
    Counter, Gauge, Histogram, MetricsRegistry, default_buckets,
    default_registry, reset_default_registry,
)
from .slo import (Alert, HealthVerdict, SloEngine, SloRule,
                  TrainingHealthMonitor, default_loop_rules,
                  default_serving_rules, default_training_rules,
                  ingest_deadman_rule)
from .slog import configure_logging, get_logger
from .timeseries import MetricRecorder
from .trace_context import (REQUEST_CATEGORIES, TRACE_KV_PREFIX,
                            TailSampler, TraceContext)
from .tracer import CATEGORIES, STEP_CATEGORIES, Span, Tracer

__all__ = [
    "Alert", "BackgroundPublisher", "CATEGORIES",
    "CHANGE_EVENT_KINDS", "GOODPUT_CATEGORIES",
    "ChangeEvent", "ChangeJournal", "Counter", "DeviceSpec",
    "Gauge", "HealthVerdict", "Histogram", "Incident",
    "IncidentEngine", "IncidentPolicy", "METRIC_FAMILY_NAMES",
    "MetricRecorder", "MetricsRegistry", "GoodputLedger",
    "PerfAccountant", "REQUEST_CATEGORIES", "STEP_CATEGORIES",
    "SloEngine", "SloRule",
    "Span", "StepCost", "TRACE_KV_PREFIX", "TailSampler",
    "Telemetry", "TraceContext", "Tracer", "TrainingHealthMonitor",
    "classify_roofline", "collect_snapshots", "configure_logging",
    "default_buckets", "default_journal", "default_loop_rules",
    "default_registry",
    "default_serving_rules", "default_training_rules", "device_spec",
    "get_logger", "ingest_deadman_rule",
    "merge_alerts", "merge_cluster", "merge_incidents",
    "merge_metrics",
    "merge_timeline", "peak_flops_per_sec",
    "publish_snapshot", "read_snapshot_dir", "record_change",
    "reset_default_journal", "reset_default_registry",
    "write_snapshot",
]

#: log-spaced bounds sized for step/phase durations (100µs … ~100s)
STEP_BUCKETS = default_buckets(start=1e-4, factor=2.0, count=21)


class Telemetry:
    """The bundle the training/serving drivers speak to.

    Without arguments it adopts the process-wide default registry (so
    the resilience layer's counters land in the same snapshot), a
    fresh tracer and a fresh goodput ledger.  ``trace_every`` sets the
    tracing cadence: spans are recorded for every Nth step (1 = every
    step, the default; 0 disables span recording while keeping
    metrics + goodput).  ``snapshot_dir`` makes :meth:`write_snapshot`
    drop ``<host>.json`` payloads for ``tools/run_report.py``.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None,
                 ledger: Optional[GoodputLedger] = None,
                 host: str = "local",
                 snapshot_dir: Optional[str] = None,
                 trace_every: int = 1,
                 perf: Optional[PerfAccountant] = None):
        self.registry = registry if registry is not None \
            else default_registry()
        self.tracer = tracer or Tracer()
        self.ledger = ledger or GoodputLedger()
        # XLA cost-model work accounting (telemetry/perf.py): built on
        # the same registry so the mfu family lands in one snapshot
        self.perf = perf if perf is not None \
            else PerfAccountant(registry=self.registry)
        self.host = str(host)
        self.snapshot_dir = snapshot_dir
        self.trace_every = max(0, int(trace_every))
        self.incarnation = 0
        self._steps_seen = 0
        #: optional online SLO engine (telemetry/slo.py) — a
        #: TrainingHealthMonitor built over this bundle registers
        #: itself here so payload() publishes the active-alert view
        self.slo = None
        #: optional incident engine (telemetry/incidents.py) —
        #: registered the same way so payload() publishes open/recent
        #: incident bundles alongside the alerts they explain
        self.incidents = None
        r = self.registry
        # bind the CONCRETE unlabeled series (family.labels()), not the
        # family wrapper: the per-step hooks below run inside the
        # driver loop, and the family->labels->child indirection was a
        # measurable slice of per-iteration idle at millisecond step
        # times (the child exposes the same observe/inc/value/sum API)
        self.steps = r.counter(
            "bigdl_train_steps_total", "compiled train steps run"
        ).labels()
        self.records = r.counter(
            "bigdl_train_records_total", "records trained").labels()
        self.step_seconds = r.histogram(
            "bigdl_train_step_seconds",
            "compiled step wall time (post-compile)",
            bounds=STEP_BUCKETS, window=1024).labels()
        self.compile_seconds = r.histogram(
            "bigdl_train_compile_seconds",
            "first-step wall time of each fresh program (XLA build)",
            bounds=STEP_BUCKETS).labels()
        self.data_wait_seconds = r.histogram(
            "bigdl_train_data_wait_seconds",
            "host wait on the input pipeline per iteration",
            bounds=STEP_BUCKETS, window=1024).labels()
        self.h2d_seconds = r.histogram(
            "bigdl_train_host_to_device_seconds",
            "host-to-device placement (infeed sharding) per iteration",
            bounds=STEP_BUCKETS).labels()
        self.checkpoint_seconds = r.histogram(
            "bigdl_checkpoint_write_seconds",
            "checkpoint write/dispatch wall time",
            bounds=STEP_BUCKETS).labels()
        self.checkpoint_blocked_seconds = r.histogram(
            "bigdl_checkpoint_blocked_seconds",
            "critical-path seconds blocked on checkpoint back-pressure "
            "(async writer queue full)",
            bounds=STEP_BUCKETS).labels()
        self.recoveries = r.counter(
            "bigdl_recovery_windows_total",
            "fault-to-first-productive-step recovery windows").labels()
        self.skipped_steps = r.counter(
            "bigdl_guard_skipped_steps_total",
            "steps skipped by the NaN/Inf gradient guard").labels()

    # -- driver hooks ----------------------------------------------------
    def _trace_due(self) -> bool:
        return (self.trace_every > 0
                and self._steps_seen % self.trace_every == 0)

    def on_attempt_begin(self):
        """Start of an optimize attempt: the run clock starts (first
        attempt only — the ledger is idempotent)."""
        self.ledger.start()

    def on_data_wait(self, seconds: float, step: Optional[int] = None):
        """Host time spent waiting on the input pipeline."""
        seconds = max(0.0, float(seconds))
        self.data_wait_seconds.observe(seconds)
        self.ledger.add("data_stall", seconds)
        if self._trace_due():
            end = self.tracer.clock()
            self.tracer.record("data_wait", "data_wait", end - seconds,
                               seconds, step=step)

    def on_host_to_device(self, seconds: float,
                          step: Optional[int] = None):
        """Host→device placement (infeed sharding) — ledgered as part
        of the data stall, traced under its own category."""
        seconds = max(0.0, float(seconds))
        self.h2d_seconds.observe(seconds)
        self.ledger.add("data_stall", seconds)
        if self._trace_due():
            end = self.tracer.clock()
            self.tracer.record("host_to_device", "host_to_device",
                               end - seconds, seconds, step=step)

    def on_step(self, seconds: float, records: int = 0,
                step: Optional[int] = None, compiled: bool = False,
                phase_split=None, skipped: bool = False):
        """One compiled-step dispatch completed.  ``compiled=True``
        classifies it as compile time (the first step of every fresh
        program); ``phase_split`` is the optional
        :class:`~bigdl_tpu.optim.profiling.PhaseSplit` attributing the
        step's device time to compute vs collective children."""
        seconds = max(0.0, float(seconds))
        if self.ledger.in_recovery:
            # the window closes where this step BEGAN — the step's own
            # seconds are attributed below, not as recovery
            rec = self.ledger.recovery_end(exclude=seconds)
            if rec and self.trace_every > 0:
                end = self.tracer.clock() - seconds
                self.tracer.record("recovery", "recovery", end - rec,
                                   rec)
        self.ledger.add("compile" if compiled else "productive", seconds)
        self.steps.inc()
        if records:
            self.records.inc(records)
        if skipped:
            self.skipped_steps.inc()
        (self.compile_seconds if compiled
         else self.step_seconds).observe(seconds)
        self.perf.on_step(seconds, compiled=compiled)
        if self._trace_due():
            end = self.tracer.clock()
            # static FLOPs/bytes/intensity from the cost model ride on
            # EVERY step span — Perfetto traces carry the work
            # attribution even when the xplane profiler never ran
            parent = self.tracer.record(
                "compile" if compiled else "step",
                "compile" if compiled else "step",
                end - seconds, seconds, step=step,
                **self.perf.span_args())
            if phase_split is not None and parent is not None:
                compute_s, collective_s = phase_split
                self.tracer.record("compute", "compute", parent.start,
                                   compute_s, parent=parent, step=step)
                self.tracer.record("collective", "collective",
                                   parent.start + compute_s,
                                   collective_s, parent=parent,
                                   step=step)
        self._steps_seen += 1

    def on_checkpoint(self, seconds: float, step: Optional[int] = None):
        seconds = max(0.0, float(seconds))
        self.checkpoint_seconds.observe(seconds)
        self.ledger.add("checkpoint", seconds)
        if self.trace_every > 0:
            end = self.tracer.clock()
            self.tracer.record("checkpoint", "checkpoint",
                               end - seconds, seconds, step=step)

    def on_checkpoint_blocked(self, seconds: float,
                              step: Optional[int] = None):
        """Critical-path back-pressure from the background checkpoint
        writer: the step boundary waited ``seconds`` for a previous
        async write to commit.  With async checkpointing this (plus
        the snapshot cost fed to :meth:`on_checkpoint`) is ALL the
        checkpoint time the ledger should ever see."""
        seconds = max(0.0, float(seconds))
        if seconds <= 0.0:
            return
        self.checkpoint_blocked_seconds.observe(seconds)
        self.ledger.add("checkpoint", seconds)
        if self.trace_every > 0:
            end = self.tracer.clock()
            self.tracer.record("checkpoint_blocked", "checkpoint",
                               end - seconds, seconds, step=step)

    def on_recovery_begin(self):
        """A fault was detected (retry rollback, membership change):
        wall clock is recovery until the next completed step."""
        if not self.ledger.in_recovery:
            self.recoveries.inc()
        self.ledger.recovery_begin()

    # -- export ----------------------------------------------------------
    #: newest spans carried per published payload — enough for the
    #: cluster timeline's recent window without bloating KV puts
    SPAN_EXPORT_LIMIT = 512

    def payload(self, step: Optional[int] = None) -> dict:
        """The publishable telemetry payload (what lands on the KV
        transport and in snapshot directories).  ``spans`` (the newest
        :data:`SPAN_EXPORT_LIMIT`, with a mono/wall clock anchor) is
        what ``merge_timeline`` folds into the cluster-wide Perfetto
        view."""
        return {
            "host": self.host,
            "step": step,
            "incarnation": int(self.incarnation),
            "ts": time.time(),
            "goodput": self.ledger.snapshot(),
            "metrics": self.registry.snapshot()["metrics"],
            "span_totals": self.tracer.category_totals(),
            "spans": self.tracer.export_spans(self.SPAN_EXPORT_LIMIT),
            "clock_anchor": {"mono": self.tracer.clock(),
                             "wall": time.time()},
            "perf": self.perf.payload(),
            # active/recent SLO alerts (None without an engine) — the
            # cluster fold unions these into the run-report alert table
            "alerts": (self.slo.snapshot() if self.slo is not None
                       else None),
            # open/recent incident bundles (None without an engine) —
            # merge_incidents folds them cluster-wide like alerts
            "incidents": (self.incidents.snapshot()
                          if self.incidents is not None else None),
        }

    def write_snapshot(self, directory: Optional[str] = None,
                       step: Optional[int] = None) -> Optional[str]:
        """Drop ``<host>.json`` into ``directory`` (default: the
        configured ``snapshot_dir``); no-op without one."""
        directory = directory or self.snapshot_dir
        if directory is None:
            return None
        return write_snapshot(directory, self.host, self.payload(step))

    def to_summary(self, summary, step: int):
        """Write the goodput ledger + headline counters as scalar
        events (tags ``telemetry/<field>``) through a
        ``visualization.summary.Summary`` (e.g.
        :class:`~bigdl_tpu.visualization.TelemetrySummary`)."""
        snap = self.ledger.snapshot()
        summary.add_scalar("telemetry/goodput_fraction",
                           snap["productive_fraction"], step)
        summary.add_scalar("telemetry/accounted_fraction",
                           snap["accounted_fraction"], step)
        for cat, secs in snap["seconds"].items():
            summary.add_scalar(f"telemetry/{cat}_s", secs, step)
        summary.add_scalar("telemetry/steps_total", self.steps.value,
                           step)
        summary.add_scalar("telemetry/recovery_windows",
                           self.recoveries.value, step)
        return summary
