"""Metrics registry — thread-safe counters, gauges, mergeable histograms.

The reference attributed cluster time through named Spark accumulators
("computing time average", Metrics.scala:31); our reproduction grew
four siloed counter bags instead (optim.Metrics, ServingMetrics,
ElasticContext counters, FlightRecorder tallies).  This registry is the
one spine they all land on:

* :class:`Counter` / :class:`Gauge` / :class:`Histogram` with label
  sets, addressed through a :class:`MetricsRegistry` by name — the
  prometheus data model, because it is the one every scraper already
  understands.
* Histograms use **fixed log-spaced buckets** so two histograms with
  the same bucket geometry merge by adding counts — the property the
  cross-host aggregation (:mod:`.aggregate`) depends on.  An optional
  bounded sample window gives *exact* quantiles for local consumers
  (the serving p50/p99 contract); merged histograms fall back to
  bucket interpolation.
* Snapshots export as plain JSON (:meth:`MetricsRegistry.snapshot`)
  and as Prometheus text exposition (:meth:`MetricsRegistry
  .to_prometheus`).
* The clock is injectable so snapshot timestamps are deterministic in
  tests.

Library subsystems (retry, breaker, watchdog, elastic) record into the
process-wide :func:`default_registry`; a :class:`~bigdl_tpu.telemetry
.Telemetry` facade built without an explicit registry shares it, so
resilience counters land in the same snapshot as the training metrics.
"""
from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "default_buckets", "default_registry", "reset_default_registry",
]


def default_buckets(start: float = 1e-6, factor: float = 4.0,
                    count: int = 20) -> Tuple[float, ...]:
    """Fixed log-spaced upper bounds: ``start * factor**i``.  The
    default ladder spans 1µs … ~1100s in 20 buckets — wide enough for
    both a histogram of step times and one of whole-run recoveries."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    return tuple(start * factor ** i for i in range(count))


class Counter:
    """Monotonically increasing count (one labeled series)."""

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0

    def inc(self, n: float = 1.0):
        if n < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += float(n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _data(self) -> dict:
        return {"value": self.value}


class Gauge:
    """Set-to-current-value metric (one labeled series)."""

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0

    def set(self, v: float):
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0):
        with self._lock:
            self._value += float(n)

    def dec(self, n: float = 1.0):
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _data(self) -> dict:
        return {"value": self.value}


class Histogram:
    """Fixed log-bucket histogram (one labeled series), mergeable.

    ``bounds`` are cumulative upper bounds (le semantics, +inf bucket
    implicit).  Two histograms with identical bounds merge by adding
    bucket counts / count / sum — associatively, which is what lets the
    cross-host leader fold snapshots in any order.

    ``window`` > 0 additionally keeps the most recent raw observations
    for **exact** quantiles (numpy-percentile semantics over the
    window) — the serving p50/p99 contract.  The window never merges
    (exactness does not compose); a merged histogram answers quantiles
    from its buckets by linear interpolation.
    """

    def __init__(self, bounds: Optional[Sequence[float]] = None,
                 window: int = 0,
                 lock: Optional[threading.RLock] = None):
        self.bounds: Tuple[float, ...] = tuple(
            float(b) for b in (bounds if bounds is not None
                               else default_buckets()))
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError("bucket bounds must be strictly increasing")
        self._lock = lock or threading.RLock()
        self.buckets = [0] * (len(self.bounds) + 1)  # + the +inf bucket
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._window = int(window)
        self._samples: List[float] = []
        # bucket index -> {"value", "trace_id"}: the newest exemplar
        # per bucket (the Prometheus/OpenMetrics exemplar model) —
        # what links a latency bucket to a kept distributed trace
        self._exemplars: Dict[int, dict] = {}

    def observe(self, v: float, exemplar: Optional[str] = None):
        """Record one observation; ``exemplar`` optionally attaches a
        trace id to the covering bucket (newest wins per bucket; the
        wall-clock ``ts`` stamp is what lets the cross-host merge keep
        the newest exemplar per bucket ACROSS hosts)."""
        v = float(v)
        with self._lock:
            idx = bisect.bisect_left(self.bounds, v)
            self.buckets[idx] += 1
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if exemplar is not None:
                self._exemplars[idx] = {"value": v,
                                        "trace_id": str(exemplar),
                                        "ts": time.time()}
            if self._window > 0:
                self._samples.append(v)
                if len(self._samples) > self._window:
                    del self._samples[:len(self._samples) - self._window]

    def exemplars(self) -> Dict[int, dict]:
        with self._lock:
            return {i: dict(e) for i, e in self._exemplars.items()}

    # -- quantiles ------------------------------------------------------
    def quantile(self, q: float) -> Optional[float]:
        """Quantile estimate, ``q`` in [0, 1].  Exact (numpy ``linear``
        interpolation over the bounded sample window) when a window is
        kept; bucket-interpolated otherwise.  None when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} not in [0, 1]")
        with self._lock:
            if self._samples:
                return _exact_quantile(self._samples, q)
            if self.count == 0:
                return None
            return self._bucket_quantile(q)

    def _bucket_quantile(self, q: float) -> float:
        """Prometheus-style interpolation inside the covering bucket,
        clamped to the observed min/max so tails stay honest."""
        rank = q * self.count
        cum = 0
        for i, c in enumerate(self.buckets):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else min(
                    0.0, self.min if self.min is not None else 0.0)
                hi = (self.bounds[i] if i < len(self.bounds)
                      else (self.max if self.max is not None else lo))
                frac = (rank - cum) / c
                v = lo + (hi - lo) * max(0.0, min(1.0, frac))
                return float(min(max(v, self.min), self.max))
            cum += c
        return float(self.max)

    @property
    def mean(self) -> Optional[float]:
        with self._lock:
            return self.sum / self.count if self.count else None

    # -- merging --------------------------------------------------------
    def merge(self, other: "Histogram") -> "Histogram":
        """A NEW histogram holding both inputs' bucket state.  Requires
        identical bucket geometry; windows do not carry over (exact
        quantiles do not compose — the merged histogram interpolates)."""
        if self.bounds != other.bounds:
            raise ValueError(
                f"cannot merge histograms with different bucket bounds "
                f"({len(self.bounds)} vs {len(other.bounds)} buckets)")
        out = Histogram(self.bounds)
        with self._lock, other._lock:
            out.buckets = [a + b for a, b in zip(self.buckets,
                                                 other.buckets)]
            out.count = self.count + other.count
            out.sum = self.sum + other.sum
            mins = [m for m in (self.min, other.min) if m is not None]
            maxs = [m for m in (self.max, other.max) if m is not None]
            out.min = min(mins) if mins else None
            out.max = max(maxs) if maxs else None
        return out

    def _data(self) -> dict:
        with self._lock:
            out = {
                "count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "bounds": list(self.bounds),
                "buckets": list(self.buckets),
                "p50": self.quantile(0.5) if self.count else None,
                "p99": self.quantile(0.99) if self.count else None,
            }
            if self._exemplars:
                # JSON object keys are strings; keep the snapshot
                # round-trippable
                out["exemplars"] = {str(i): dict(e)
                                    for i, e in self._exemplars.items()}
            return out


def _exact_quantile(samples: Sequence[float], q: float) -> float:
    """numpy.percentile(..., interpolation='linear') without numpy —
    the registry must stay importable before jax/numpy init."""
    s = sorted(samples)
    if len(s) == 1:
        return float(s[0])
    pos = q * (len(s) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(s) - 1)
    return float(s[lo] + (s[hi] - s[lo]) * (pos - lo))


class _Family:
    """One named metric family: label-tuple → child instance."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self, name: str, kind: str, help: str,
                 label_names: Sequence[str], lock: threading.RLock,
                 **child_kw):
        self.name = name
        self.kind = kind
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = lock
        self._child_kw = child_kw
        self._children: Dict[Tuple[str, ...], object] = {}

    def labels(self, **kv):
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(kv)}")
        key = tuple(str(kv[n]) for n in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if self.kind == "histogram":
                    child = Histogram(lock=self._lock, **self._child_kw)
                else:
                    child = self._KINDS[self.kind](self._lock)
                self._children[key] = child
            return child

    # unlabeled families act as their single child
    def _default(self):
        if self.label_names:
            raise ValueError(f"{self.name} requires labels "
                             f"{self.label_names}")
        return self.labels()

    def inc(self, n: float = 1.0):
        self._default().inc(n)

    def set(self, v: float):
        self._default().set(v)

    def dec(self, n: float = 1.0):
        self._default().dec(n)

    def observe(self, v: float, exemplar: Optional[str] = None):
        self._default().observe(v, exemplar=exemplar)

    def quantile(self, q: float):
        return self._default().quantile(q)

    @property
    def value(self):
        return self._default().value

    @property
    def count(self):
        return self._default().count

    @property
    def sum(self):
        return self._default().sum

    @property
    def mean(self):
        return self._default().mean

    @property
    def min(self):
        return self._default().min

    @property
    def max(self):
        return self._default().max

    def series(self) -> List[Tuple[Dict[str, str], object]]:
        with self._lock:
            return [(dict(zip(self.label_names, key)), child)
                    for key, child in sorted(self._children.items())]


class MetricsRegistry:
    """Name → metric family, with get-or-create semantics (a second
    registration with the same name returns the existing family, and a
    conflicting kind/labels raises — two subsystems cannot silently
    split one name)."""

    def __init__(self, clock: Callable[[], float] = time.time):
        self._lock = threading.RLock()
        self._clock = clock
        self._families: Dict[str, _Family] = {}

    # -- registration ---------------------------------------------------
    def _register(self, name: str, kind: str, help: str,
                  labels: Sequence[str], **child_kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.label_names}, cannot re-register "
                        f"as {kind}{tuple(labels)}")
                return fam
            fam = _Family(name, kind, help, labels, self._lock,
                          **child_kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> _Family:
        return self._register(name, "counter", help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> _Family:
        return self._register(name, "gauge", help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  bounds: Optional[Sequence[float]] = None,
                  window: int = 0) -> _Family:
        return self._register(name, "histogram", help, labels,
                              bounds=bounds, window=window)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    # -- export ---------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-serializable view of every family and series."""
        with self._lock:
            fams = list(self._families.values())
        out = {"ts": self._clock(), "metrics": {}}
        for fam in fams:
            out["metrics"][fam.name] = {
                "type": fam.kind, "help": fam.help,
                "series": [{"labels": labels, **child._data()}
                           for labels, child in fam.series()],
            }
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4): HELP/TYPE plus
        one line per series; histograms expand to cumulative
        ``_bucket{le=...}`` lines and ``_sum``/``_count``."""
        lines: List[str] = []
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            if fam.help:
                lines.append(f"# HELP {fam.name} "
                             f"{_esc_help(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for labels, child in fam.series():
                if fam.kind == "histogram":
                    cum = 0
                    exemplars = child.exemplars()
                    for i, (bound, c) in enumerate(zip(
                            list(child.bounds) + [float("inf")],
                            child.buckets)):
                        cum += c
                        le = dict(labels, le=_fmt_float(bound))
                        line = (f"{fam.name}_bucket"
                                f"{_label_str(le)} {cum}")
                        ex = exemplars.get(i)
                        if ex is not None:
                            # OpenMetrics exemplar syntax: the bucket
                            # links to a kept distributed trace
                            line += (' # {trace_id="%s"} %s'
                                     % (ex["trace_id"],
                                        _fmt_float(ex["value"])))
                        lines.append(line)
                    lines.append(f"{fam.name}_sum{_label_str(labels)} "
                                 f"{_fmt_float(child.sum)}")
                    lines.append(f"{fam.name}_count"
                                 f"{_label_str(labels)} {child.count}")
                else:
                    lines.append(f"{fam.name}{_label_str(labels)} "
                                 f"{_fmt_float(child.value)}")
        return "\n".join(lines) + "\n"


def _fmt_float(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(float(v))


def _esc_help(s: str) -> str:
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    body = ",".join(
        '{}="{}"'.format(k, str(v).replace("\\", r"\\")
                         .replace('"', r"\"").replace("\n", r"\n"))
        for k, v in sorted(labels.items()))
    return "{" + body + "}"


# ---------------------------------------------------------------------------
# the process-wide registry library subsystems record into
# ---------------------------------------------------------------------------

_default_lock = threading.Lock()
_default: Optional[MetricsRegistry] = None


def default_registry() -> MetricsRegistry:
    """The process-wide registry.  Resilience/serving internals count
    into it unconditionally (counters are cheap); a Telemetry facade
    built without an explicit registry adopts it, so one snapshot
    carries the whole process."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default


def reset_default_registry() -> MetricsRegistry:
    """Swap in a fresh default registry (tests isolate with this)."""
    global _default
    with _default_lock:
        _default = MetricsRegistry()
        return _default
