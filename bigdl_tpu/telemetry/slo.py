"""Online SLO engine: declarative rules over recorder windows,
multi-window burn-rate alerting, anomaly rules, structured alerts.

The observability spine records everything but — before this module —
evaluated nothing online: regressions were caught offline at bench
time (``tools/perf_sentinel.py``) and the control loops acted on
hand-coded raw thresholds.  The :class:`SloEngine` is the online twin
of the offline sentinel: it evaluates a declarative rule set over the
windows a :class:`~.timeseries.MetricRecorder` holds and emits
structured firing/resolved :class:`Alert` events the control planes
act on — the autoscaler consumes verdicts as its breach signal, the
fleet router marks replicas degraded, and the training driver exposes
a :class:`HealthVerdict` the continuous-learning watchdog consults.

Rule kinds
----------
* ``threshold`` — a windowed reducer (:data:`~.timeseries.REDUCERS`)
  compared against a bound.  ``reduce="slope"`` writes loss-descent
  stall rules, ``frac_of_max`` MFU-collapse rules — the reducer
  vocabulary IS the rule vocabulary.
* ``burn_rate`` — the SRE multi-window error-budget form: the bad/
  total event ratio, normalized by the budget, must exceed
  ``burn_factor`` in BOTH a fast and a slow window to fire.  The fast
  window gives detection latency, the slow window immunity to blips;
  recovery clears the fast window first, so resolution is prompt too.
* ``anomaly`` — the recorder's robust ``mad_score`` (newest value vs
  the window median, in MAD units) against a score bound, directional.
  Step-time drift is this rule.
* ``absent`` — the dead-man switch: fires when a series that HAS
  reported stops reporting for a window (a killed replica's health
  feed).  The inverse of the staleness gate.

Every rule carries a **staleness gate**: when its series has not been
fed within ``staleness_s``, the engine renders *no verdict* — state
freezes, nothing fires, nothing resolves (the autoscaler's "no fresh
traffic" gate, generalized).  Firing and resolution both require
``for_intervals`` / ``resolve_intervals`` consecutive evaluations —
one noisy sample alerts nothing.

Alert transitions export as
``bigdl_alerts_total{rule,severity,state}`` plus the
``bigdl_alerts_active`` gauge; :meth:`SloEngine.active_alerts` is the
live snapshot and :meth:`SloEngine.verdict` the one-word summary.
"""
from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass, field as dc_field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import metric_names as M
from .timeseries import MetricRecorder

log = logging.getLogger("bigdl_tpu")

__all__ = [
    "Alert", "HealthVerdict", "SloEngine", "SloRule",
    "TrainingHealthMonitor", "default_loop_rules",
    "default_serving_rules", "default_training_rules",
    "ingest_deadman_rule",
]

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


@dataclass
class SloRule:
    """One declarative health rule — see the module docstring for the
    kinds.  ``family``/``labels``/``signal`` address the recorder
    series (``signal`` is the sampled field: ``value`` for counters/
    gauges, ``count``/``sum``/``p99``… for histograms); reference
    families through :mod:`~bigdl_tpu.telemetry.metric_names` so a
    rename can never orphan the rule."""
    name: str
    family: str = ""
    labels: Dict[str, str] = dc_field(default_factory=dict)
    signal: str = "value"
    kind: str = "threshold"        # threshold | burn_rate | anomaly | absent
    severity: str = "page"         # page | ticket
    description: str = ""
    # -- shared evaluation knobs
    window_s: float = 60.0
    staleness_s: Optional[float] = None   # default: window_s
    for_intervals: int = 1
    resolve_intervals: int = 1
    min_samples: int = 1
    # -- threshold
    reduce: str = "last"
    op: str = ">="
    threshold: float = 0.0
    # -- burn_rate (bad series = family/labels/signal above)
    total_family: str = ""
    total_labels: Dict[str, str] = dc_field(default_factory=dict)
    total_signal: str = "value"
    budget: float = 0.01           # allowed bad fraction of total
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    burn_factor: float = 2.0
    # -- anomaly
    score: float = 4.0
    direction: str = "up"          # up | down | both

    def __post_init__(self):
        if self.kind not in ("threshold", "burn_rate", "anomaly",
                             "absent"):
            raise ValueError(f"rule {self.name!r}: unknown kind "
                             f"{self.kind!r}")
        if self.kind == "threshold" and self.op not in _OPS:
            raise ValueError(f"rule {self.name!r}: unknown op "
                             f"{self.op!r}")
        if self.kind == "burn_rate" and not self.total_family:
            raise ValueError(f"rule {self.name!r}: burn_rate needs "
                             f"total_family")
        if self.severity not in ("page", "ticket"):
            raise ValueError(f"rule {self.name!r}: severity must be "
                             f"page|ticket")

    @property
    def stale_after(self) -> float:
        if self.staleness_s is not None:
            return float(self.staleness_s)
        if self.kind == "burn_rate":
            return float(self.fast_window_s)
        return float(self.window_s)


@dataclass
class Alert:
    """One structured firing/resolved transition."""
    rule: str
    severity: str
    state: str                     # firing | resolved
    at: float
    value: Optional[float] = None
    reason: str = ""
    labels: Dict[str, str] = dc_field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "state": self.state, "at": self.at,
                "value": self.value, "reason": self.reason,
                "labels": dict(self.labels)}


@dataclass(frozen=True)
class HealthVerdict:
    """The one-word health summary a watchdog consults: ``ok`` (no
    firing alerts), ``degraded`` (ticket-severity firing), or
    ``critical`` (page-severity firing)."""
    status: str
    firing: Tuple[str, ...]
    at: float

    @property
    def healthy(self) -> bool:
        return self.status == "ok"


class _RuleState:
    __slots__ = ("breach_streak", "clear_streak", "firing", "fired_at",
                 "last_value", "last_verdict_at")

    def __init__(self):
        self.breach_streak = 0
        self.clear_streak = 0
        self.firing = False
        self.fired_at: Optional[float] = None
        self.last_value: Optional[float] = None
        self.last_verdict_at: Optional[float] = None


class SloEngine:
    """Evaluates a rule set over one recorder — see the module
    docstring.  Thread-safe; ``evaluate()`` is the cadence tick."""

    def __init__(self, recorder: MetricRecorder,
                 rules: Sequence[SloRule] = (),
                 registry=None,
                 clock: Optional[Callable[[], float]] = None,
                 max_events: int = 1024):
        self.recorder = recorder
        self.clock = clock or recorder.clock
        self._lock = threading.RLock()
        self._rules: Dict[str, SloRule] = {}
        self._state: Dict[str, _RuleState] = {}
        self.events: List[Alert] = []
        self._max_events = int(max_events)
        self.evaluations = 0
        if registry is None:
            from .registry import default_registry

            registry = default_registry()
        self.registry = registry
        self._alerts_total = registry.counter(
            M.ALERTS_TOTAL,
            "SLO alert transitions per rule, severity and state",
            labels=("rule", "severity", "state"))
        self._alerts_active = registry.gauge(
            M.ALERTS_ACTIVE, "alerts currently firing in this engine")
        for rule in rules:
            self.add_rule(rule)

    # ------------------------------------------------------------ rules
    def add_rule(self, rule: SloRule) -> SloRule:
        with self._lock:
            if rule.name in self._rules:
                raise ValueError(f"rule {rule.name!r} already "
                                 f"registered")
            self._rules[rule.name] = rule
            self._state[rule.name] = _RuleState()
        return rule

    def remove_rule(self, name: str):
        with self._lock:
            self._rules.pop(name, None)
            st = self._state.pop(name, None)
        if st is not None and st.firing:
            self._alerts_active.dec()

    @property
    def rules(self) -> Tuple[SloRule, ...]:
        with self._lock:
            return tuple(self._rules.values())

    # ------------------------------------------------------------ predicates
    def _eval_threshold(self, rule: SloRule, now: float):
        v = self.recorder.reduce(
            rule.family, rule.reduce, labels=rule.labels,
            field=rule.signal, window_s=rule.window_s, now=now,
            min_samples=rule.min_samples)
        if v is None:
            return None, None
        return _OPS[rule.op](v, rule.threshold), v

    def _eval_burn_rate(self, rule: SloRule, now: float):
        burns = []
        for win in (rule.fast_window_s, rule.slow_window_s):
            bad = self.recorder.reduce(
                rule.family, "rate", labels=rule.labels,
                field=rule.signal, window_s=win, now=now,
                min_samples=2)
            total = self.recorder.reduce(
                rule.total_family, "rate", labels=rule.total_labels,
                field=rule.total_signal, window_s=win, now=now,
                min_samples=2)
            if bad is None or total is None:
                return None, None
            ratio = (bad / total) if total > 0 else 0.0
            burns.append(ratio / max(rule.budget, 1e-12))
        # firing needs BOTH windows burning; the recorded value is the
        # fast burn (the number that moves first, both ways)
        return (burns[0] >= rule.burn_factor
                and burns[1] >= rule.burn_factor), burns[0]

    def _eval_anomaly(self, rule: SloRule, now: float):
        v = self.recorder.reduce(
            rule.family, "mad_score", labels=rule.labels,
            field=rule.signal, window_s=rule.window_s, now=now,
            min_samples=max(3, rule.min_samples))
        if v is None:
            return None, None
        if rule.direction == "up":
            breach = v >= rule.score
        elif rule.direction == "down":
            breach = v <= -rule.score
        else:
            breach = abs(v) >= rule.score
        return breach, (None if math.isinf(v)
                        else v)

    def _eval_absent(self, rule: SloRule, now: float):
        age = self.recorder.age(rule.family, labels=rule.labels,
                                field=rule.signal, now=now)
        if age is None:
            # never reported: nothing to go dead — no verdict (a
            # fleet booting up must not page for replicas that have
            # not published yet)
            return None, None
        return age > rule.window_s, age

    # ------------------------------------------------------------ evaluate
    def evaluate(self, now: Optional[float] = None) -> List[Alert]:
        """One evaluation round over every rule.  Returns the alert
        transitions emitted THIS round (most rounds: none)."""
        now = self.clock() if now is None else now
        emitted: List[Alert] = []
        with self._lock:
            rules = list(self._rules.values())
        for rule in rules:
            # staleness gate: an unfed series renders NO verdict —
            # the absent kind is the one rule ABOUT staleness
            if rule.kind != "absent":
                age = self.recorder.age(rule.family,
                                        labels=rule.labels,
                                        field=rule.signal, now=now)
                if age is None or age > rule.stale_after:
                    continue
            if rule.kind == "threshold":
                breach, value = self._eval_threshold(rule, now)
            elif rule.kind == "burn_rate":
                breach, value = self._eval_burn_rate(rule, now)
            elif rule.kind == "anomaly":
                breach, value = self._eval_anomaly(rule, now)
            else:
                breach, value = self._eval_absent(rule, now)
            if breach is None:
                continue
            st = self._state[rule.name]
            st.last_value = value
            st.last_verdict_at = now
            if breach:
                st.breach_streak += 1
                st.clear_streak = 0
                if not st.firing \
                        and st.breach_streak >= rule.for_intervals:
                    st.firing = True
                    st.fired_at = now
                    emitted.append(self._emit(rule, "firing", now,
                                              value))
            else:
                st.clear_streak += 1
                st.breach_streak = 0
                if st.firing \
                        and st.clear_streak >= rule.resolve_intervals:
                    st.firing = False
                    st.fired_at = None
                    emitted.append(self._emit(rule, "resolved", now,
                                              value))
        with self._lock:
            self.evaluations += 1
            self._alerts_active.set(float(sum(
                1 for s in self._state.values() if s.firing)))
        return emitted

    def _emit(self, rule: SloRule, state: str, now: float,
              value) -> Alert:
        reason = (f"{rule.description or rule.kind}"
                  f" (value={value!r})" if state == "firing"
                  else f"recovered (value={value!r})")
        alert = Alert(rule=rule.name, severity=rule.severity,
                      state=state, at=now, value=value, reason=reason,
                      labels=dict(rule.labels))
        with self._lock:
            self.events.append(alert)
            if len(self.events) > self._max_events:
                del self.events[:len(self.events) - self._max_events]
        self._alerts_total.labels(rule=rule.name,
                                  severity=rule.severity,
                                  state=state).inc()
        (log.warning if state == "firing" else log.info)(
            "slo: %s %s [%s] %s", state.upper(), rule.name,
            rule.severity, reason)
        return alert

    # ------------------------------------------------------------ reading
    def firing(self, names: Optional[Sequence[str]] = None
               ) -> List[dict]:
        """Currently firing alerts (optionally restricted to a rule
        subset), as dicts carrying the rule, severity, value, and
        fired-at time."""
        out = []
        with self._lock:
            for name, st in self._state.items():
                if not st.firing:
                    continue
                if names is not None and name not in names:
                    continue
                rule = self._rules[name]
                out.append({"rule": name, "severity": rule.severity,
                            "labels": dict(rule.labels),
                            "value": st.last_value,
                            "since": st.fired_at,
                            "last_verdict_at": st.last_verdict_at,
                            "description": rule.description})
        return sorted(out, key=lambda a: a["rule"])

    def active_alerts(self) -> List[dict]:
        return self.firing()

    def verdict(self, now: Optional[float] = None) -> HealthVerdict:
        now = self.clock() if now is None else now
        firing = self.firing()
        if not firing:
            return HealthVerdict("ok", (), now)
        status = ("critical" if any(a["severity"] == "page"
                                    for a in firing) else "degraded")
        return HealthVerdict(status,
                             tuple(a["rule"] for a in firing), now)

    def snapshot(self) -> dict:
        """The publishable view: active alerts, recent transitions,
        per-rule state — what ``Telemetry.payload`` ships and
        ``tools/run_report.py --alerts`` renders."""
        with self._lock:
            events = [a.to_dict() for a in self.events[-64:]]
            rules = {
                name: {"firing": st.firing, "since": st.fired_at,
                       "value": st.last_value,
                       "breach_streak": st.breach_streak,
                       "severity": self._rules[name].severity}
                for name, st in sorted(self._state.items())}
            evaluations = self.evaluations
        return {"active": self.active_alerts(), "recent": events,
                "rules": rules, "evaluations": evaluations,
                "verdict": self.verdict().status}


# ---------------------------------------------------------------------------
# default rule packs
# ---------------------------------------------------------------------------

def default_serving_rules(pool: str = "both", *,
                          tenant: Optional[str] = None,
                          p99_high_s: float = 0.5,
                          shed_high: float = 0.02,
                          kv_occupancy_high: float = 0.90,
                          error_budget: float = 0.02,
                          window_s: float = 30.0,
                          fast_window_s: float = 30.0,
                          slow_window_s: float = 300.0,
                          burn_factor: float = 2.0,
                          for_intervals: int = 2,
                          resolve_intervals: int = 2
                          ) -> List[SloRule]:
    """The serving rule pack for ONE role pool, over the per-pool
    signals the autoscaler feeds its recorder: p99, shed rate, KV
    occupancy thresholds plus the multi-window shed error-budget
    burn.

    ``tenant`` instantiates the pack per tenant on a multi-tenant
    fleet: the rules watch that tenant's ``model:role`` pool series
    (the spec :func:`~bigdl_tpu.serving.pools.split_pool` parses, the
    series a tenant-scoped autoscaler pool feeds) under distinct rule
    names — each tenant's pack fires and resolves independently, so
    one tenant burning its budget never marks another tenant's
    traffic degraded."""
    pool = pool if tenant is None else f"{tenant}:{pool}"
    L = {"pool": pool}
    return [
        SloRule(name=f"serving/{pool}/p99",
                family=M.AUTOSCALE_POOL_P99_SECONDS, labels=L,
                kind="threshold", reduce="last", op=">=",
                threshold=p99_high_s, window_s=window_s,
                for_intervals=for_intervals,
                resolve_intervals=resolve_intervals,
                description=f"{pool} pool p99 >= {p99_high_s}s"),
        SloRule(name=f"serving/{pool}/shed_rate",
                family=M.AUTOSCALE_POOL_SHED_RATE, labels=L,
                kind="threshold", reduce="last", op=">=",
                threshold=shed_high, window_s=window_s,
                for_intervals=for_intervals,
                resolve_intervals=resolve_intervals,
                description=f"{pool} pool shedding >= "
                            f"{100 * shed_high:g}% of fresh traffic"),
        SloRule(name=f"serving/{pool}/kv_occupancy",
                family=M.AUTOSCALE_POOL_KV_OCCUPANCY, labels=L,
                kind="threshold", reduce="last", op=">=",
                threshold=kv_occupancy_high, window_s=window_s,
                for_intervals=for_intervals,
                resolve_intervals=resolve_intervals, severity="ticket",
                description=f"{pool} pool KV occupancy >= "
                            f"{kv_occupancy_high:g}"),
        SloRule(name=f"serving/{pool}/error_budget",
                family=M.AUTOSCALE_POOL_SHED_TOTAL, labels=L,
                total_family=M.AUTOSCALE_POOL_REQUESTS_TOTAL,
                total_labels=L, kind="burn_rate", budget=error_budget,
                fast_window_s=fast_window_s,
                slow_window_s=slow_window_s, burn_factor=burn_factor,
                for_intervals=for_intervals,
                resolve_intervals=resolve_intervals,
                description=f"{pool} pool burning its "
                            f"{100 * error_budget:g}% error budget at "
                            f">= {burn_factor:g}x in both windows"),
    ]


def default_training_rules(*, goodput_floor: float = 0.5,
                           step_drift_score: float = 6.0,
                           loss_window_s: float = 120.0,
                           loss_min_slope: float = 0.0,
                           divergence_ratio: float = 1.5,
                           mfu_drop_frac: float = 0.5,
                           window_s: float = 60.0,
                           for_intervals: int = 2,
                           resolve_intervals: int = 2
                           ) -> List[SloRule]:
    """The training rule pack: goodput productive-fraction floor,
    step-time drift (MAD anomaly), loss-descent stall + divergence,
    and MFU collapse — the online verdicts the continuous-learning
    watchdog consults."""
    return [
        SloRule(name="training/goodput",
                family=M.GOODPUT_PRODUCTIVE_FRACTION,
                kind="threshold", reduce="last", op="<",
                threshold=goodput_floor, window_s=window_s,
                for_intervals=for_intervals,
                resolve_intervals=resolve_intervals, severity="ticket",
                description=f"goodput productive fraction < "
                            f"{goodput_floor:g}"),
        SloRule(name="training/step_time_drift",
                family=M.TRAIN_STEP_TIME_SECONDS, kind="anomaly",
                score=step_drift_score, direction="up",
                window_s=window_s, for_intervals=for_intervals,
                resolve_intervals=resolve_intervals, severity="ticket",
                min_samples=8,
                description=f"step time drifted >= "
                            f"{step_drift_score:g} MADs above the "
                            f"window median"),
        SloRule(name="training/loss_stall",
                family=M.TRAIN_LOSS, kind="threshold", reduce="slope",
                op=">=", threshold=-abs(loss_min_slope),
                window_s=loss_window_s, for_intervals=for_intervals,
                resolve_intervals=resolve_intervals, severity="ticket",
                min_samples=8,
                description="loss stopped descending (robust slope "
                            "over the window)"),
        SloRule(name="training/loss_divergence",
                family=M.TRAIN_LOSS, kind="threshold",
                reduce="frac_of_min", op=">=",
                threshold=divergence_ratio, window_s=loss_window_s,
                for_intervals=for_intervals,
                resolve_intervals=resolve_intervals,
                min_samples=4,
                description=f"loss >= {divergence_ratio:g}x its "
                            f"window minimum (divergence)"),
        SloRule(name="training/mfu_collapse",
                family=M.PERF_MFU, kind="threshold",
                reduce="frac_of_max", op="<", threshold=mfu_drop_frac,
                window_s=window_s, for_intervals=for_intervals,
                resolve_intervals=resolve_intervals,
                min_samples=4,
                description=f"MFU fell below {mfu_drop_frac:g}x its "
                            f"window maximum"),
    ]


def ingest_deadman_rule(*, window_s: float = 5.0,
                        name: str = "loop/ingest_deadman",
                        severity: str = "page") -> SloRule:
    """The streaming-ingest dead-man switch: the continuous-learning
    loop feeds its cumulative fresh-batch counter every interval that
    delivers data; a stream that HAS delivered and then goes silent
    for more than ``window_s`` fires this structured alert instead of
    silently idling the trainer.  (A loop that has never ingested
    renders no verdict — booting up is not a stall.)"""
    return SloRule(
        name=name, family=M.LOOP_INGEST_BATCHES_TOTAL, kind="absent",
        window_s=window_s, severity=severity,
        description=f"ingest stream silent > {window_s:g}s (dead-man)")


def default_loop_rules(*, interval_s: float = 1.0,
                       deadman_intervals: int = 5,
                       serve_budget: float = 0.05,
                       burn_factor: float = 2.0,
                       fast_intervals: int = 4,
                       slow_intervals: int = 16,
                       for_intervals: int = 2,
                       resolve_intervals: int = 2) -> List[SloRule]:
    """The continuous-learning loop's rule pack: the ingest dead-man
    switch plus the **post-swap burn-rate watch** — the SRE
    multi-window error-budget burn over the fleet-wide served bad/
    total counters the loop feeds each interval.  While a fresh deploy
    is inside its watch window, a firing ``loop/serving_burn`` is the
    signal that triggers automatic fleet-wide rollback
    (``ServingFleet.rollback_last_deploy``); outside a watch it is an
    ordinary page.  Windows are sized in loop intervals
    (``interval_s`` scales them to the loop's cadence)."""
    return [
        ingest_deadman_rule(
            window_s=deadman_intervals * interval_s),
        SloRule(name="loop/serving_burn",
                family=M.LOOP_SERVED_BAD_TOTAL,
                total_family=M.LOOP_SERVED_REQUESTS_TOTAL,
                kind="burn_rate", budget=serve_budget,
                fast_window_s=fast_intervals * interval_s,
                slow_window_s=slow_intervals * interval_s,
                burn_factor=burn_factor,
                for_intervals=for_intervals,
                resolve_intervals=resolve_intervals,
                description=f"fleet serving errors burning the "
                            f"{100 * serve_budget:g}% budget at >= "
                            f"{burn_factor:g}x in both windows "
                            f"(post-swap watch)"),
    ]


# ---------------------------------------------------------------------------
# the training-side monitor (the driver hook)
# ---------------------------------------------------------------------------

class TrainingHealthMonitor:
    """The training driver's online watchdog: feeds per-step loss and
    step time (plus goodput/MFU at evaluation cadence) into a
    recorder, evaluates the training rule pack every
    ``every_n_steps``, and answers :meth:`verdict` — the
    :class:`HealthVerdict` hook the continuous-learning scenario
    consults while the run is LIVE.

    Attach with ``optimizer.set_health_monitor(monitor)``; the driver
    calls :meth:`on_step` each iteration.  Built from a
    :class:`~bigdl_tpu.telemetry.Telemetry` bundle it shares the
    bundle's registry (alert counters land in the same snapshot) and
    registers itself as the bundle's ``slo`` engine so
    ``Telemetry.payload()`` publishes the active-alert view.
    """

    def __init__(self, telemetry=None,
                 rules: Optional[Sequence[SloRule]] = None,
                 every_n_steps: int = 8,
                 recorder: Optional[MetricRecorder] = None,
                 registry=None,
                 clock: Callable[[], float] = time.monotonic):
        self.telemetry = telemetry
        self.every_n_steps = max(1, int(every_n_steps))
        self.recorder = recorder or MetricRecorder(clock=clock)
        if registry is None and telemetry is not None:
            registry = telemetry.registry
        self.engine = SloEngine(
            self.recorder,
            rules=(rules if rules is not None
                   else default_training_rules()),
            registry=registry, clock=self.recorder.clock)
        if telemetry is not None and \
                getattr(telemetry, "slo", None) is None:
            telemetry.slo = self.engine
        self._steps = 0

    def on_step(self, step: int, loss: float, seconds: float):
        """One driver iteration: feed the loss/step-time series; at
        cadence, refresh the slow signals and evaluate the rules."""
        r = self.recorder
        if loss == loss and not math.isinf(loss):  # NaN/Inf never
            r.observe(M.TRAIN_LOSS, float(loss))   # poison a window
        r.observe(M.TRAIN_STEP_TIME_SECONDS, float(seconds))
        self._steps += 1
        if self._steps % self.every_n_steps == 0:
            self._refresh_slow_signals()
            self.engine.evaluate()

    def _refresh_slow_signals(self):
        tm = self.telemetry
        if tm is None:
            return
        try:
            snap = tm.ledger.snapshot()
            self.recorder.observe(M.GOODPUT_PRODUCTIVE_FRACTION,
                                  float(snap["productive_fraction"]))
            fam = tm.registry.get(M.PERF_MFU)
            if fam is not None:
                for _labels, child in fam.series():
                    if child.value > 0:
                        self.recorder.observe(M.PERF_MFU,
                                              float(child.value))
                    break
        except Exception:  # health accounting must never stop training
            log.debug("health monitor slow-signal refresh failed",
                      exc_info=True)

    def evaluate(self, now: Optional[float] = None):
        return self.engine.evaluate(now=now)

    def verdict(self, now: Optional[float] = None) -> HealthVerdict:
        return self.engine.verdict(now=now)

    def snapshot(self) -> dict:
        return self.engine.snapshot()
