"""Distributed request-trace context — the one shared constant table.

A request that crosses the fleet (router → prefill replica → decode
replica, with retries and hedge duplicates along the way) used to
leave one unstitchable span fragment per process.  This module is the
*vocabulary* that lets those fragments stitch back into one trace:

* :data:`REQUEST_CATEGORIES` — the closed span-category vocabulary of
  the request path, appended to the tracer's training vocabulary
  (``telemetry.tracer.CATEGORIES``).  Router, server, and tracer all
  import THIS table; a vocabulary lint (tests/test_determinism.py)
  fails on any stringly-typed category that isn't in it.
* :data:`TRACE_KV_PREFIX` / :func:`trace_key` — the elastic-KV key
  schema trace fragments publish under:
  ``trc/<incarnation>/<trace_id>/<host>`` (incarnation-keyed exactly
  like telemetry snapshots and SDC votes, so a reconfigured fleet
  never stitches a dead membership's fragments).
* :class:`TraceContext` — the per-request context minted at
  ``FleetRouter.submit`` / ``submit_generate`` and propagated through
  every dispatch, retry, hedge duplicate, and the crc-sealed
  prefill→decode handoff blob: trace id, parent span id, the
  REMAINING deadline budget at fork time, and the sampling decision.
* :class:`TailSampler` — tail-based retention: the keep/drop decision
  runs at request COMPLETION, when the outcome is known — errors,
  sheds, retries, hedges and p99-exceeding requests are always kept;
  OK traffic is kept probabilistically under a rate budget.
"""
from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

__all__ = [
    "REQUEST_CATEGORIES", "TRACE_KV_PREFIX", "TRACE_WIRE_KEY",
    "TraceContext", "TailSampler", "trace_key", "trace_prefix",
]

#: the closed vocabulary of request-phase span categories.  Everything
#: a traced request's wall clock can be attributed to, across router
#: and replica:
#:
#: * ``request``     — the router-side root span (one per request)
#: * ``attempt``     — one dispatch attempt (primary, retry, or hedge
#:                     duplicate; hedges carry ``hedge=True`` and a
#:                     terminal ``hedge_outcome``)
#: * ``queue``       — replica admission-queue wait
#: * ``batch``       — bucket coalesce / batch-formation window
#: * ``execute``     — compiled-step execution of the request's batch
#: * ``prefill``     — prompt pass + first token (paged/disagg path)
#: * ``decode``      — the token-streaming loop
#: * ``kv_gather``   — KV page gather/scatter (handoff export/import)
#: * ``handoff``     — the sealed prefill→decode handoff hop
#: * ``swap_window`` — a hot-swap/canary window overlapping the request
#: * ``error``       — a typed failure (status + error ride the args)
REQUEST_CATEGORIES = (
    "request", "attempt", "queue", "batch", "execute",
    "prefill", "decode", "kv_gather", "handoff", "swap_window",
    "error",
)

#: KV key prefix for published trace fragments (next to ``tm/`` and
#: ``sdc/`` in the elastic keyspace)
TRACE_KV_PREFIX = "trc/"

#: the key a TraceContext rides under in wire dicts (handoff-blob
#: extras, submit kwargs) — one name, no stringly drift
TRACE_WIRE_KEY = "trace"


def trace_prefix(incarnation: int, trace_id: str) -> str:
    """Key prefix of every host's fragment for one trace."""
    return f"{TRACE_KV_PREFIX}{int(incarnation)}/{trace_id}/"


def trace_key(incarnation: int, trace_id: str, host: str) -> str:
    """``trc/<incarnation>/<trace_id>/<host>`` — one fragment per
    (trace, host), newest-wins like telemetry snapshots."""
    return trace_prefix(incarnation, trace_id) + str(host)


@dataclass
class TraceContext:
    """The context one request carries across process boundaries.

    ``deadline_s`` is the REMAINING budget at the point this context
    was minted or forked — each retry forks a child with the budget
    that actually remains, so a stitched trace shows the budget
    draining across attempts.  ``sampled`` is the head decision
    (record spans at all); retention is decided tail-side by
    :class:`TailSampler` when the outcome is known.
    """
    trace_id: str
    span_id: int = 1            # parent span id for remote children
    deadline_s: Optional[float] = None
    sampled: bool = True
    attempt: int = 0
    phase: Optional[str] = None  # prefill | decode | None
    #: multi-tenant attribution — which tenant/model/version this
    #: request belongs to, so one kept trace is enough to diagnose a
    #: noisy-neighbor incident.  None on single-model fleets.
    tenant: Optional[str] = None
    model: Optional[str] = None
    model_version: Optional[str] = None

    @classmethod
    def mint(cls, deadline_s: Optional[float] = None,
             sampled: bool = True) -> "TraceContext":
        """A fresh root context (trace id from the OS entropy pool —
        never the seeded training streams, which checkpoint/replay)."""
        return cls(trace_id=os.urandom(8).hex(), span_id=1,
                   deadline_s=deadline_s, sampled=sampled)

    def child(self, span_id: int, remaining_s: Optional[float] = None,
              attempt: Optional[int] = None,
              phase: Optional[str] = None) -> "TraceContext":
        """Fork for one dispatch attempt: same trace, new parent span,
        the budget that remains NOW."""
        return TraceContext(
            trace_id=self.trace_id, span_id=int(span_id),
            deadline_s=(self.deadline_s if remaining_s is None
                        else remaining_s),
            sampled=self.sampled,
            attempt=self.attempt if attempt is None else int(attempt),
            phase=self.phase if phase is None else phase,
            tenant=self.tenant, model=self.model,
            model_version=self.model_version)

    def to_wire(self) -> dict:
        """JSON-serializable wire form (submit kwargs, handoff-blob
        extras)."""
        return {
            "trace_id": self.trace_id, "span_id": int(self.span_id),
            "deadline_s": self.deadline_s, "sampled": bool(self.sampled),
            "attempt": int(self.attempt), "phase": self.phase,
            "tenant": self.tenant, "model": self.model,
            "model_version": self.model_version,
        }

    @classmethod
    def from_wire(cls, wire) -> Optional["TraceContext"]:
        """Parse a wire dict (or pass through a TraceContext); None on
        anything unusable — a malformed context must degrade to
        untraced, never fail the request."""
        if wire is None:
            return None
        if isinstance(wire, TraceContext):
            return wire
        try:
            return cls(
                trace_id=str(wire["trace_id"]),
                span_id=int(wire.get("span_id", 1)),
                deadline_s=wire.get("deadline_s"),
                sampled=bool(wire.get("sampled", True)),
                attempt=int(wire.get("attempt", 0)),
                phase=wire.get("phase"),
                tenant=wire.get("tenant"),
                model=wire.get("model"),
                model_version=wire.get("model_version"))
        except (TypeError, KeyError, ValueError):
            return None


class TailSampler:
    """Tail-based retention policy, decided at request completion.

    Always keeps: non-OK outcomes (errors, sheds, deadline expiries),
    retried requests, hedged requests, and requests whose latency
    reached the current p99.  OK traffic under the tail is kept
    probabilistically under ``keep_per_s`` (a token bucket — the
    budget bounds stitch/storage cost, not observability of trouble).
    """

    def __init__(self, keep_per_s: float = 10.0, burst: float = 20.0,
                 ok_prob: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 seed: int = 0):
        self.keep_per_s = float(keep_per_s)
        self.burst = max(1.0, float(burst))
        self.ok_prob = float(ok_prob)
        self._clock = clock
        self._tokens = self.burst
        self._t_last = clock()
        # explicitly seeded local generator (never the global stream —
        # the determinism lint, and sampling must not perturb training)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.kept: Dict[str, int] = {}
        self.dropped = 0

    def _take_token(self) -> bool:
        now = self._clock()
        self._tokens = min(
            self.burst,
            self._tokens + (now - self._t_last) * self.keep_per_s)
        self._t_last = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False

    def keep(self, *, ok: bool, retried: bool = False,
             hedged: bool = False, latency_s: float = 0.0,
             p99_s: Optional[float] = None) -> Optional[str]:
        """The keep reason, or None to drop.  Reasons: ``error`` /
        ``retry`` / ``hedge`` / ``p99`` / ``budget``."""
        with self._lock:
            reason = None
            if not ok:
                reason = "error"
            elif retried:
                reason = "retry"
            elif hedged:
                reason = "hedge"
            elif p99_s is not None and p99_s > 0 \
                    and latency_s >= p99_s:
                reason = "p99"
            elif self._rng.random() < self.ok_prob \
                    and self._take_token():
                reason = "budget"
            if reason is None:
                self.dropped += 1
            else:
                self.kept[reason] = self.kept.get(reason, 0) + 1
            return reason

    def snapshot(self) -> dict:
        with self._lock:
            return {"kept": dict(sorted(self.kept.items())),
                    "kept_total": sum(self.kept.values()),
                    "dropped": self.dropped,
                    "keep_per_s": self.keep_per_s}
