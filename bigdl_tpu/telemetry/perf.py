"""Performance accounting from XLA's own cost model.

The telemetry spine (registry/tracer/goodput) accounts *time*; this
module accounts *work*: per-compiled-step FLOPs and bytes accessed
derived from XLA (``Lowered.cost_analysis()`` — the pre-optimization
HLO cost model, which counts the math as written, without remat or
fusion artifacts — or ``Compiled.cost_analysis()`` +
``memory_analysis()`` when the caller holds an AOT executable), plus
live HBM watermarks from ``device.memory_stats()`` polled at step
boundaries.  From those it publishes the MFU family as first-class
registry metrics and classifies every analyzed program against the
device roofline (compute-bound vs HBM-bound vs collective-bound,
peaks from :mod:`.device_info`).

Nothing here hand-codes a model's FLOPs: the numbers come from the
exact program the driver dispatches.  Every entry point degrades to a
no-op on failure — perf accounting must never take down a training
step (``memory_stats()`` returning None on CPU jaxlib is the normal
case, not an error).

jax is imported lazily inside functions: the registry/tracer side of
the spine stays importable before backend init.
"""
from __future__ import annotations

import logging
from typing import Dict, NamedTuple, Optional

from .device_info import DeviceSpec, current_device_spec
from .registry import MetricsRegistry, default_registry

log = logging.getLogger("bigdl_tpu")

__all__ = ["PerfAccountant", "StepCost", "classify_roofline",
           "cost_from_analysis"]

#: roofline verdicts (``unknown`` = not enough device/byte data)
ROOFLINE_BOUNDS = ("compute", "hbm", "collective", "unknown")


class StepCost(NamedTuple):
    """Static cost of one compiled program, from XLA's cost model."""

    flops: float
    bytes_accessed: float
    #: caller-supplied estimate (XLA's per-op byte counts do not
    #: attribute collective wire bytes); 0.0 = single-chip program
    collective_bytes: float = 0.0
    #: from Compiled.memory_analysis() when available, else None
    peak_bytes: Optional[float] = None
    argument_bytes: Optional[float] = None
    output_bytes: Optional[float] = None
    temp_bytes: Optional[float] = None
    #: "lowered" (pre-optimization HLO) or "compiled" (executable)
    source: str = "lowered"

    @property
    def arithmetic_intensity(self) -> Optional[float]:
        if not self.bytes_accessed:
            return None
        return self.flops / self.bytes_accessed


def cost_from_analysis(analysis, collective_bytes: float = 0.0,
                       memory=None, source: str = "lowered") -> StepCost:
    """Normalize a jax ``cost_analysis()`` result (dict, or a 1-list
    of dicts on older executables) + optional ``memory_analysis()``
    into a :class:`StepCost`."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    get = analysis.get if hasattr(analysis, "get") else lambda *_: 0.0
    kw = {}
    if memory is not None:
        arg = float(getattr(memory, "argument_size_in_bytes", 0))
        out = float(getattr(memory, "output_size_in_bytes", 0))
        tmp = float(getattr(memory, "temp_size_in_bytes", 0))
        kw = dict(argument_bytes=arg, output_bytes=out, temp_bytes=tmp,
                  peak_bytes=arg + out + tmp)
    return StepCost(
        flops=float(get("flops", 0.0) or 0.0),
        bytes_accessed=float(get("bytes accessed", 0.0) or 0.0),
        collective_bytes=max(0.0, float(collective_bytes or 0.0)),
        source=source, **kw)


def classify_roofline(cost: StepCost, spec: DeviceSpec) -> dict:
    """Which wall does this program lean on?

    Attainable-time comparison: ``flops/peak`` vs ``bytes/hbm_bw`` vs
    ``collective_bytes/ici_bw`` — the largest lower bound names the
    binding resource.  The compute-vs-HBM half is equivalent to
    comparing arithmetic intensity against the device ridge point
    (``peak_flops / hbm_bw``); stating it as times lets the collective
    leg join the same comparison.  Returns the classification plus the
    inputs it was made from, so reports can show their work.
    """
    ai = cost.arithmetic_intensity
    ridge = spec.ridge_flops_per_byte
    times = {}
    if spec.peak_flops_per_sec:
        times["compute"] = cost.flops / spec.peak_flops_per_sec
    if spec.hbm_bytes_per_sec and cost.bytes_accessed:
        times["hbm"] = cost.bytes_accessed / spec.hbm_bytes_per_sec
    if spec.ici_bytes_per_sec and cost.collective_bytes:
        times["collective"] = (cost.collective_bytes
                               / spec.ici_bytes_per_sec)
    bound = max(times, key=times.get) if times else "unknown"
    if "hbm" not in times and bound == "compute" and not cost.flops:
        bound = "unknown"
    return {
        "bound": bound,
        "arithmetic_intensity": ai,
        "ridge_flops_per_byte": ridge,
        "attainable_seconds": times,
        "nominal_device": spec.nominal,
    }


class PerfAccountant:
    """Derives work metrics for the programs a driver dispatches.

    One accountant per process side (training driver, bench worker,
    serving server).  ``analyze_jitted`` is called once per fresh
    program (the driver's ``first_step``); ``on_step`` once per
    dispatch.  Publishes into the registry:

    * ``bigdl_perf_flops_per_step`` / ``bigdl_perf_bytes_per_step`` /
      ``bigdl_perf_collective_bytes`` gauges, labeled by ``program``;
    * ``bigdl_perf_arithmetic_intensity`` gauge per program;
    * ``bigdl_perf_mfu`` gauge per program (rolling mean over the
      last observed step times) + ``bigdl_perf_model_flops_per_sec``;
    * ``bigdl_perf_flops_total`` counter — the cross-host foldable
      total (counters sum in the cluster merge);
    * ``bigdl_perf_hbm_{bytes_in_use,peak_bytes,limit_bytes}`` gauges
      from ``device.memory_stats()``, polled every
      ``memory_poll_every`` steps (backends without memory stats —
      CPU jaxlib — leave them untouched).
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 spec: Optional[DeviceSpec] = None,
                 memory_poll_every: int = 16):
        self.registry = registry if registry is not None \
            else default_registry()
        self._spec = spec
        self.memory_poll_every = max(1, int(memory_poll_every))
        self._programs: Dict[str, StepCost] = {}
        self._current: Optional[str] = None
        self._steps_seen = 0
        self._ema_flops_per_sec: Dict[str, float] = {}
        self.last_memory_stats: Optional[dict] = None
        r = self.registry
        self.flops_per_step = r.gauge(
            "bigdl_perf_flops_per_step",
            "XLA cost-model FLOPs of one compiled step",
            labels=("program",))
        self.bytes_per_step = r.gauge(
            "bigdl_perf_bytes_per_step",
            "XLA cost-model bytes accessed by one compiled step",
            labels=("program",))
        self.collective_bytes = r.gauge(
            "bigdl_perf_collective_bytes",
            "estimated collective wire bytes per step (sparse-transport "
            "leaves accounted as actual index+value bytes)",
            labels=("program",))
        self.sparse_bytes_saved = r.gauge(
            "bigdl_perf_sparse_bytes_saved",
            "collective wire bytes per step NOT moved because sparse "
            "gradient transport replaced the dense all-reduce",
            labels=("program",))
        self.sync_bytes_saved = r.gauge(
            "bigdl_perf_sync_bytes_saved",
            "collective wire bytes per step NOT moved because relaxed "
            "synchrony (periodic(k) local SGD) replaced the lockstep "
            "per-step reduction with amortized k-step averaging",
            labels=("program",))
        self.sparse_flops_skipped_gauge = r.gauge(
            "bigdl_perf_sparse_flops_skipped",
            "dense-equivalent MXU FLOPs per step NOT executed because "
            "block-sparse kernels skipped masked blocks (kernel-"
            "reported: XLA's cost model cannot see inside Pallas "
            "custom calls)",
            labels=("program",))
        #: kernel-reported sparse corrections per program — the
        #: uncorrected cost is retained so repeated reports replace,
        #: never compound
        self._sparse_flops: Dict[str, dict] = {}
        self._uncorrected: Dict[str, StepCost] = {}
        self.intensity = r.gauge(
            "bigdl_perf_arithmetic_intensity",
            "flops / bytes accessed of one compiled step",
            labels=("program",))
        self.mfu = r.gauge(
            "bigdl_perf_mfu",
            "model flops utilization vs the device peak "
            "(per analyzed program; rolling over recent steps)",
            labels=("program",))
        self.model_flops_per_sec = r.gauge(
            "bigdl_perf_model_flops_per_sec",
            "achieved model FLOP/s (per analyzed program)",
            labels=("program",))
        self.flops_total = r.counter(
            "bigdl_perf_flops_total",
            "cost-model FLOPs executed (sums across hosts)")
        self.hbm_in_use = r.gauge(
            "bigdl_perf_hbm_bytes_in_use",
            "device memory in use at the last poll")
        self.hbm_peak = r.gauge(
            "bigdl_perf_hbm_peak_bytes",
            "device memory high-watermark at the last poll")
        self.hbm_limit = r.gauge(
            "bigdl_perf_hbm_limit_bytes",
            "device memory capacity reported by the backend")

    # -- device ----------------------------------------------------------
    @property
    def spec(self) -> DeviceSpec:
        if self._spec is None:
            try:
                self._spec = current_device_spec()
            except Exception:  # backend not up: nominal denominator
                from .device_info import CPU_SPEC

                self._spec = CPU_SPEC
        return self._spec

    # -- program analysis ------------------------------------------------
    def analyze_jitted(self, fn, *args, label: str = "train_step",
                       collective_bytes: float = 0.0,
                       sparse_bytes_saved: float = 0.0,
                       sync_bytes_saved: float = 0.0,
                       **kwargs) -> Optional[StepCost]:
        """Lower a jitted callable with the driver's concrete args and
        read XLA's cost model — no compile, no execution, no donation
        (lowering only traces avals), a few seconds of host work per
        fresh program.  Returns None (and logs at debug) on any
        failure: accounting never takes down the step loop."""
        try:
            lowered = fn.lower(*args, **kwargs)
            cost = cost_from_analysis(lowered.cost_analysis(),
                                      collective_bytes=collective_bytes,
                                      source="lowered")
        except Exception as e:
            log.debug("perf: cost analysis failed for %r: %s: %s",
                      label, type(e).__name__, e)
            return None
        return self.on_program(label, cost,
                               sparse_bytes_saved=sparse_bytes_saved,
                               sync_bytes_saved=sync_bytes_saved)

    def analyze_compiled(self, compiled, label: str = "train_step",
                         collective_bytes: float = 0.0
                         ) -> Optional[StepCost]:
        """Read an AOT executable's cost + memory analyses (the bench
        path, which already compiles ahead of time)."""
        try:
            memory = None
            try:
                memory = compiled.memory_analysis()
            except Exception:
                pass
            cost = cost_from_analysis(compiled.cost_analysis(),
                                      collective_bytes=collective_bytes,
                                      memory=memory, source="compiled")
        except Exception as e:
            log.debug("perf: compiled analysis failed for %r: %s: %s",
                      label, type(e).__name__, e)
            return None
        return self.on_program(label, cost)

    def on_program(self, label: str, cost: StepCost,
                   sparse_bytes_saved: float = 0.0,
                   sync_bytes_saved: float = 0.0) -> StepCost:
        """Install an analyzed program: publish its static gauges and
        make it the one ``on_step`` attributes work to."""
        label = str(label)
        self._programs[label] = cost
        # a fresh analysis supersedes any kernel-reported sparse
        # correction (the caller re-reports after re-analyzing)
        self._uncorrected.pop(label, None)
        self._sparse_flops.pop(label, None)
        self._current = label
        self.flops_per_step.labels(program=label).set(cost.flops)
        self.bytes_per_step.labels(program=label).set(
            cost.bytes_accessed)
        self.collective_bytes.labels(program=label).set(
            cost.collective_bytes)
        if sparse_bytes_saved:
            self.sparse_bytes_saved.labels(program=label).set(
                float(sparse_bytes_saved))
        if sync_bytes_saved:
            self.sync_bytes_saved.labels(program=label).set(
                float(sync_bytes_saved))
        if cost.arithmetic_intensity is not None:
            self.intensity.labels(program=label).set(
                cost.arithmetic_intensity)
        self.poll_memory_stats()
        return cost

    def report_sparse_flops(self, label: str, executed_flops: float,
                            dense_equiv_flops: float) -> Optional[StepCost]:
        """Kernel-reported effective-FLOPs correction for a program
        whose Pallas kernels SKIP work the cost model cannot see.

        XLA counts a Pallas call as a zero-FLOP custom call, so a
        block-sparse kernel's skipped blocks are invisible: without
        this correction a 2x wall-clock win at 50% density reads as an
        MFU regression.  The caller (driver/bench — it knows the mask)
        reports the kernel's ``executed`` FLOPs and the ``dense
        equivalent``; the program's accounted FLOPs become
        ``cost-model + executed`` (MFU/model_flops_per_sec rate on
        EXECUTED work), the dense equivalent is recorded alongside in
        the payload, and the difference lands in the
        ``bigdl_perf_sparse_flops_skipped`` gauge.  Repeated reports
        for one program replace (never compound) the correction."""
        label = str(label)
        executed = max(0.0, float(executed_flops))
        dense_eq = max(executed, float(dense_equiv_flops))
        base = self._uncorrected.get(label)
        if base is None:
            base = self._programs.get(label, StepCost(0.0, 0.0))
            self._uncorrected[label] = base
        skipped = dense_eq - executed
        corrected = base._replace(flops=base.flops + executed)
        self._programs[label] = corrected
        self._sparse_flops[label] = {
            "executed_flops": base.flops + executed,
            "dense_equivalent_flops": base.flops + dense_eq,
            "sparse_flops_skipped": skipped,
        }
        self.sparse_flops_skipped_gauge.labels(program=label).set(
            skipped)
        self.flops_per_step.labels(program=label).set(corrected.flops)
        if corrected.arithmetic_intensity is not None:
            self.intensity.labels(program=label).set(
                corrected.arithmetic_intensity)
        return corrected

    @property
    def current_cost(self) -> Optional[StepCost]:
        return self._programs.get(self._current) \
            if self._current else None

    @property
    def current_label(self) -> Optional[str]:
        return self._current

    # -- per-step accounting ---------------------------------------------
    def on_step(self, seconds: float, compiled: bool = False,
                label: Optional[str] = None):
        """One dispatch of the current (or named) analyzed program
        completed in ``seconds``.  Compile steps still count their
        FLOPs (the work ran) but are excluded from the MFU rate — a
        first-step wall is XLA build time, not math time."""
        label = label or self._current
        cost = self._programs.get(label) if label else None
        if cost is None:
            return
        self.flops_total.inc(cost.flops)
        seconds = float(seconds)
        if seconds > 0 and not compiled:
            rate = cost.flops / seconds
            # EMA over recent steps: one outlier step must not own the
            # published MFU, one gauge read must not require history
            prev = self._ema_flops_per_sec.get(label)
            rate = rate if prev is None else (0.8 * prev + 0.2 * rate)
            self._ema_flops_per_sec[label] = rate
            self.model_flops_per_sec.labels(program=label).set(rate)
            peak = self.spec.peak_flops_per_sec
            if peak:
                self.mfu.labels(program=label).set(rate / peak)
        self._steps_seen += 1
        if self._steps_seen % self.memory_poll_every == 0:
            self.poll_memory_stats()

    # -- HBM watermarks --------------------------------------------------
    def poll_memory_stats(self, device=None) -> Optional[dict]:
        """Read ``device.memory_stats()`` into the HBM gauges.  CPU
        jaxlib returns None (and some backends lack the method) — both
        degrade to a no-op returning None, never an exception."""
        try:
            if device is None:
                import jax

                device = jax.devices()[0]
            stats = getattr(device, "memory_stats", lambda: None)()
        except Exception as e:
            log.debug("perf: memory_stats unavailable: %s", e)
            return None
        if not stats:
            return None
        self.last_memory_stats = dict(stats)
        if "bytes_in_use" in stats:
            self.hbm_in_use.set(float(stats["bytes_in_use"]))
        if "peak_bytes_in_use" in stats:
            self.hbm_peak.set(float(stats["peak_bytes_in_use"]))
        if "bytes_limit" in stats:
            self.hbm_limit.set(float(stats["bytes_limit"]))
        return self.last_memory_stats

    # -- roofline + export -----------------------------------------------
    def roofline(self, label: Optional[str] = None) -> Optional[dict]:
        cost = self._programs.get(label or self._current or "")
        if cost is None:
            return None
        return classify_roofline(cost, self.spec)

    def span_args(self) -> dict:
        """Static work attributes for the current program — attached
        to every step span so Perfetto traces carry intensity
        annotations even in unprofiled runs."""
        cost = self.current_cost
        if cost is None:
            return {}
        out = {"flops": cost.flops, "bytes": cost.bytes_accessed}
        if cost.collective_bytes:
            out["collective_bytes"] = cost.collective_bytes
        if cost.arithmetic_intensity is not None:
            out["intensity"] = round(cost.arithmetic_intensity, 3)
        rf = self.roofline()
        if rf is not None:
            out["bound"] = rf["bound"]
        return out

    def payload(self) -> dict:
        """The ``perf`` section of the telemetry payload (what the
        cross-host merge folds and run_report renders)."""
        programs = {}
        for label, cost in self._programs.items():
            entry = dict(cost._asdict())
            entry["arithmetic_intensity"] = cost.arithmetic_intensity
            rf = classify_roofline(cost, self.spec)
            entry["bound"] = rf["bound"]
            # kernel-reported sparse correction: executed-basis flops
            # with the dense equivalent recorded alongside
            if label in self._sparse_flops:
                entry.update(self._sparse_flops[label])
            rate = self._ema_flops_per_sec.get(label)
            if rate is not None:
                entry["model_flops_per_sec"] = rate
                if self.spec.peak_flops_per_sec:
                    entry["mfu"] = rate / self.spec.peak_flops_per_sec
            programs[label] = entry
        out = {
            "device": self.spec.to_dict(),
            "flops_total": self.flops_total.value,
            "programs": programs,
        }
        if self.last_memory_stats is not None:
            out["hbm"] = {
                k: self.last_memory_stats[k]
                for k in ("bytes_in_use", "peak_bytes_in_use",
                          "bytes_limit")
                if k in self.last_memory_stats}
        return out
