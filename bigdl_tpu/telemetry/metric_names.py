"""The one shared table of ``bigdl_*`` metric family names.

Every metric family any subsystem registers is declared HERE, as a
constant, and a vocabulary lint (tests/test_telemetry.py) fails on any
``"bigdl_*"`` family-name string literal in ``bigdl_tpu/`` that is not
a member of :data:`METRIC_FAMILY_NAMES` — the span-category lint
pattern (telemetry/trace_context.py), applied to metric names.

Why it exists: the SLO engine (:mod:`.slo`) addresses metric families
*by name* in declarative alert rules.  Before this table, renaming a
family was a silent break — the rule kept evaluating a series that no
longer existed and the alert simply never fired again.  With the
table, rules reference families through these constants, the lint
pins every registration site to the same spelling, and a renamed
metric can never silently orphan an SLO rule.

The table carries NAMES only (the registry still owns kind/labels/
help); modules may keep using string literals at registration sites —
the lint only requires each literal to be a member.
"""
from __future__ import annotations

__all__ = ["METRIC_FAMILY_NAMES"]

# --- training spine (telemetry/__init__.py) ------------------------------
TRAIN_STEPS_TOTAL = "bigdl_train_steps_total"
TRAIN_RECORDS_TOTAL = "bigdl_train_records_total"
TRAIN_STEP_SECONDS = "bigdl_train_step_seconds"
TRAIN_COMPILE_SECONDS = "bigdl_train_compile_seconds"
TRAIN_DATA_WAIT_SECONDS = "bigdl_train_data_wait_seconds"
TRAIN_H2D_SECONDS = "bigdl_train_host_to_device_seconds"
CHECKPOINT_WRITE_SECONDS = "bigdl_checkpoint_write_seconds"
CHECKPOINT_BLOCKED_SECONDS = "bigdl_checkpoint_blocked_seconds"
RECOVERY_WINDOWS_TOTAL = "bigdl_recovery_windows_total"
GUARD_SKIPPED_STEPS_TOTAL = "bigdl_guard_skipped_steps_total"

# --- resilience / elastic / infeed ---------------------------------------
RETRY_ATTEMPTS_TOTAL = "bigdl_retry_attempts_total"
WATCHDOG_TRIPS_TOTAL = "bigdl_watchdog_trips_total"
BREAKER_TRANSITIONS_TOTAL = "bigdl_breaker_transitions_total"
ELASTIC_EVICTIONS_TOTAL = "bigdl_elastic_evictions_total"
ELASTIC_INCARNATION_CHANGES_TOTAL = \
    "bigdl_elastic_incarnation_changes_total"
MESH_REBUILDS_TOTAL = "bigdl_mesh_rebuilds_total"
INTEGRITY_VOTES_TOTAL = "bigdl_integrity_votes_total"
INTEGRITY_DISAGREEMENTS_TOTAL = "bigdl_integrity_disagreements_total"
CHECKPOINT_ASYNC_WRITES_TOTAL = "bigdl_checkpoint_async_writes_total"
CHECKPOINT_ASYNC_WRITE_SECONDS_TOTAL = \
    "bigdl_checkpoint_async_write_seconds_total"
INFEED_BUFFER_HITS_TOTAL = "bigdl_infeed_buffer_hits_total"
INFEED_BUFFER_MISSES_TOTAL = "bigdl_infeed_buffer_misses_total"

# --- performance accounting (telemetry/perf.py, parallel/plan.py) --------
PERF_FLOPS_PER_STEP = "bigdl_perf_flops_per_step"
PERF_BYTES_PER_STEP = "bigdl_perf_bytes_per_step"
PERF_COLLECTIVE_BYTES = "bigdl_perf_collective_bytes"
PERF_SPARSE_BYTES_SAVED = "bigdl_perf_sparse_bytes_saved"
PERF_SYNC_BYTES_SAVED = "bigdl_perf_sync_bytes_saved"
PERF_SPARSE_FLOPS_SKIPPED = "bigdl_perf_sparse_flops_skipped"
PERF_ARITHMETIC_INTENSITY = "bigdl_perf_arithmetic_intensity"
PERF_MFU = "bigdl_perf_mfu"
PERF_MODEL_FLOPS_PER_SEC = "bigdl_perf_model_flops_per_sec"
PERF_FLOPS_TOTAL = "bigdl_perf_flops_total"
PERF_HBM_BYTES_IN_USE = "bigdl_perf_hbm_bytes_in_use"
PERF_HBM_PEAK_BYTES = "bigdl_perf_hbm_peak_bytes"
PERF_HBM_LIMIT_BYTES = "bigdl_perf_hbm_limit_bytes"
PLAN_PARAM_BYTES_PER_DEVICE = "bigdl_plan_param_bytes_per_device"
PLAN_PARAM_BYTES_TOTAL = "bigdl_plan_param_bytes_total"

# --- serving (serving/metrics.py, router.py, autoscale.py) ---------------
SERVING_REQUESTS_TOTAL = "bigdl_serving_requests_total"
SERVING_LATENCY_SECONDS = "bigdl_serving_latency_seconds"
SERVING_QUEUED_SECONDS = "bigdl_serving_queued_seconds"
SERVING_QUEUE_DEPTH = "bigdl_serving_queue_depth"
SERVING_BATCHES_TOTAL = "bigdl_serving_batches_total"
SERVING_PADDED_ROWS_TOTAL = "bigdl_serving_padded_rows_total"
SERVING_FLOPS_TOTAL = "bigdl_serving_flops_total"
SERVING_SWAPS_TOTAL = "bigdl_serving_swaps_total"
SERVING_HEDGES_TOTAL = "bigdl_serving_hedges_total"
SERVING_RETRIES_TOTAL = "bigdl_serving_retries_total"
SERVING_PHASE_SECONDS = "bigdl_serving_phase_seconds"
SERVING_TTFT_SECONDS = "bigdl_serving_ttft_seconds"
SERVING_TPOT_SECONDS = "bigdl_serving_tpot_seconds"
SERVING_KV_PAGES_TOTAL = "bigdl_serving_kv_pages_total"
SERVING_KV_PAGES_FREE = "bigdl_serving_kv_pages_free"
SERVING_KV_OCCUPANCY = "bigdl_serving_kv_occupancy"
FLEET_DISPATCH_TOTAL = "bigdl_fleet_dispatch_total"
AUTOSCALE_DECISIONS_TOTAL = "bigdl_autoscale_decisions_total"

# --- multi-tenant fleet (serving/registry.py, router.py, metrics.py) ------
#: per-tenant twins of the serving families.  The metrics registry pins
#: each family to ONE label tuple, so tenant observability lives in
#: parallel ``bigdl_tenant_*`` families rather than widening the
#: existing ones (which would break every registered series).
TENANT_REQUESTS_TOTAL = "bigdl_tenant_requests_total"
TENANT_SHEDS_TOTAL = "bigdl_tenant_sheds_total"
TENANT_PHASE_SECONDS = "bigdl_tenant_phase_seconds"
TENANT_TTFT_SECONDS = "bigdl_tenant_ttft_seconds"
TENANT_TPOT_SECONDS = "bigdl_tenant_tpot_seconds"
TENANT_DISPATCH_TOTAL = "bigdl_tenant_dispatch_total"
#: router admission decisions, labeled {tenant, decision}:
#: admitted | tenant_quota | global | not_found | flood
TENANT_ADMISSION_TOTAL = "bigdl_tenant_admission_total"
TENANT_INFLIGHT = "bigdl_tenant_inflight"
#: KV pages currently held per pool owner (labels: tenant)
TENANT_KV_PAGES_HELD = "bigdl_tenant_kv_pages_held"

# --- the online health engine (timeseries.py + slo.py) -------------------
#: structured alert transitions, labeled {rule, severity, state}
ALERTS_TOTAL = "bigdl_alerts_total"
#: number of alerts currently firing in one engine
ALERTS_ACTIVE = "bigdl_alerts_active"
#: per-role-pool control signals the autoscaler feeds its recorder
#: (labels: pool) — what the default serving rule pack evaluates
AUTOSCALE_POOL_P99_SECONDS = "bigdl_autoscale_pool_p99_seconds"
AUTOSCALE_POOL_QUEUE_DEPTH = "bigdl_autoscale_pool_queue_depth"
AUTOSCALE_POOL_KV_OCCUPANCY = "bigdl_autoscale_pool_kv_occupancy"
AUTOSCALE_POOL_SHED_RATE = "bigdl_autoscale_pool_shed_rate"
AUTOSCALE_POOL_SHED_TOTAL = "bigdl_autoscale_pool_shed_total"
AUTOSCALE_POOL_REQUESTS_TOTAL = "bigdl_autoscale_pool_requests_total"
#: per-replica health signals the fleet health monitor feeds (labels:
#: replica) — what the per-replica degradation rules evaluate
REPLICA_P99_SECONDS = "bigdl_replica_p99_seconds"
REPLICA_QUEUE_DEPTH = "bigdl_replica_queue_depth"
REPLICA_ERRORS_TOTAL = "bigdl_replica_errors_total"
REPLICA_REQUESTS_TOTAL = "bigdl_replica_requests_total"
#: training health signals the TrainingHealthMonitor feeds
TRAIN_LOSS = "bigdl_train_loss"
TRAIN_STEP_TIME_SECONDS = "bigdl_train_step_time_seconds"
GOODPUT_PRODUCTIVE_FRACTION = "bigdl_goodput_productive_fraction"

# --- continuous-learning loop (loop/continuous.py) ------------------------
#: deploy state-machine terminal outcomes, labeled {outcome}:
#: confirmed | gated | rejected | rolled_back | refused
LOOP_DEPLOYS_TOTAL = "bigdl_loop_deploys_total"
#: cumulative fresh ingest batches the loop has absorbed — the series
#: the ingest dead-man rule watches (a stalled stream goes silent here)
LOOP_INGEST_BATCHES_TOTAL = "bigdl_loop_ingest_batches_total"
#: fleet-wide served request totals the loop feeds its recorder each
#: interval — the denominator/numerator of the post-swap burn-rate
#: watch (bad = internal_error + unavailable + deadline_exceeded)
LOOP_SERVED_REQUESTS_TOTAL = "bigdl_loop_served_requests_total"
LOOP_SERVED_BAD_TOTAL = "bigdl_loop_served_bad_total"

# --- parameter-server embedding store (nn/embedding_store.py +
# --- serving/sparse_fetch.py) ---------------------------------------------
#: the live table version per table (labels: table) — bumped by every
#: repartition; the serving fetch publishes it in health snapshots and
#: the hot-row cache retires every entry from prior versions
EMBED_TABLE_VERSION = "bigdl_embed_table_version"
#: hot-row cache traffic on the remote-sparse-fetch path (labels: table)
EMBED_CACHE_HITS_TOTAL = "bigdl_embed_cache_hits_total"
EMBED_CACHE_MISSES_TOTAL = "bigdl_embed_cache_misses_total"
#: rows moved by live re-partitioning (labels: table) — ~1/N of the
#: table per 1-host delta under consistent assignment
EMBED_ROWS_MIGRATED_TOTAL = "bigdl_embed_rows_migrated_total"
#: lookups shed typed (deadline/migration/breaker) instead of served
#: unverified (labels: table)
EMBED_ROWS_SHED_TOTAL = "bigdl_embed_rows_shed_total"
#: rows served that failed verification — the must-stay-zero audit
#: every embedding chaos test pins (labels: table)
EMBED_BAD_ROWS_TOTAL = "bigdl_embed_bad_rows_total"

# --- incident engine (telemetry/events.py + incidents.py) -----------------
#: state-change events recorded into the fleet-wide change journal,
#: labeled {kind} (deploy_started, membership_evict, chaos_inject, ...)
CHANGE_EVENTS_TOTAL = "bigdl_change_events_total"
#: incidents opened by the IncidentEngine, labeled {severity}
INCIDENTS_TOTAL = "bigdl_incidents_total"
#: incidents currently holding an open capture window
INCIDENTS_ACTIVE = "bigdl_incidents_active"

#: every bigdl_* metric family name any bigdl_tpu module may register
#: or reference — the vocabulary the lint enforces
METRIC_FAMILY_NAMES = frozenset(
    v for k, v in list(globals().items())
    if isinstance(v, str) and v.startswith("bigdl_")
    and k.isupper())
