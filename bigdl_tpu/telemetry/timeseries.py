"""Bounded in-memory metric time-series — what the SLO engine reads.

The registry (:mod:`.registry`) answers "what is the value NOW"; an
online health verdict needs "how has it been MOVING": a shed counter
is only alarming as a *rate*, a loss gauge as a *slope*, a p99 as a
*windowed* read over fresh traffic.  The :class:`MetricRecorder`
closes that gap without a database: it samples metric families at a
cadence into bounded per-series ring buffers and answers windowed
reductions over them.

* **Sources** — three, composing: :meth:`MetricRecorder.sample` walks
  a live :class:`~.registry.MetricsRegistry`; :meth:`sample_metrics`
  walks any snapshot-shaped dict — including the CLUSTER view the
  existing cross-host fold produces
  (:func:`~.aggregate.merge_metrics`), so a leader records cluster
  series with zero new transport; :meth:`observe` is the direct feed
  control loops use (the autoscaler feeds per-pool signals, the fleet
  health monitor per-replica signals).
* **Counter→rate conversion** — reset-tolerant, prometheus-style: a
  sample smaller than its predecessor reads as a counter reset and
  contributes its own value, never a negative increment.
* **Staleness** — every series remembers when it was last fed;
  :meth:`age`/:meth:`fresh` generalize the autoscaler's "no fresh
  traffic" gate: a signal nobody refreshed is stale history, not an
  actionable value, and the SLO engine renders NO verdict over it.
* **Windowed reducers** — ``last``/``min``/``max``/``mean``/``delta``/
  ``rate``/``ewma``/``p<q>`` window-percentile/robust ``slope``
  (Theil–Sen)/``mad_score`` (median-absolute-deviation anomaly
  score)/``frac_of_max``/``frac_of_min`` — the vocabulary SLO rules
  are written in.

The clock is injectable; tests (and the bench's chaos scenarios)
drive it by hand for deterministic detection latencies.
"""
from __future__ import annotations

import json
import math
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["MetricRecorder", "Series", "REDUCERS"]


class Series:
    """One bounded (t, value) ring buffer.  ``kind`` decides delta
    semantics: ``counter`` series reduce reset-tolerantly, ``gauge``
    series literally."""

    __slots__ = ("kind", "_samples", "_lock")

    def __init__(self, kind: str = "gauge", capacity: int = 512):
        if kind not in ("gauge", "counter"):
            raise ValueError(f"series kind {kind!r} not gauge|counter")
        self.kind = kind
        self._samples: deque = deque(maxlen=max(2, int(capacity)))
        self._lock = threading.Lock()

    def add(self, t: float, v: float):
        with self._lock:
            self._samples.append((float(t), float(v)))

    def last(self) -> Optional[Tuple[float, float]]:
        with self._lock:
            return self._samples[-1] if self._samples else None

    def window(self, since: float) -> List[Tuple[float, float]]:
        """Samples with t >= since, oldest first — plus the one sample
        immediately BEFORE the window when the series is a counter
        (the increase across the window boundary is real traffic)."""
        with self._lock:
            samples = list(self._samples)
        out = [s for s in samples if s[0] >= since]
        if self.kind == "counter":
            before = [s for s in samples if s[0] < since]
            if before:
                out.insert(0, before[-1])
        return out

    def __len__(self):
        with self._lock:
            return len(self._samples)


# ---------------------------------------------------------------------------
# reducers
# ---------------------------------------------------------------------------

def _increase(samples: Sequence[Tuple[float, float]]) -> float:
    """Reset-tolerant counter increase over ordered samples: a drop
    reads as a reset (the new value IS the increment since it)."""
    inc = 0.0
    for (_, prev), (_, cur) in zip(samples, samples[1:]):
        inc += cur - prev if cur >= prev else cur
    return inc


def _percentile(values: Sequence[float], q: float) -> float:
    s = sorted(values)
    if len(s) == 1:
        return float(s[0])
    pos = q * (len(s) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(s) - 1)
    return float(s[lo] + (s[hi] - s[lo]) * (pos - lo))


def _median(values: Sequence[float]) -> float:
    return _percentile(values, 0.5)


def _slope(samples: Sequence[Tuple[float, float]]) -> Optional[float]:
    """Robust slope (value per second): Theil–Sen — the median of
    pairwise slopes, so one outlier sample cannot fake a trend.  The
    pair count is capped by even subsampling (the reducer runs inside
    control loops)."""
    if len(samples) < 2:
        return None
    pts = list(samples)
    if len(pts) > 32:
        stride = len(pts) / 32.0
        pts = [pts[int(i * stride)] for i in range(32)]
        if pts[-1] != samples[-1]:
            pts.append(samples[-1])
    slopes = []
    for i in range(len(pts)):
        for j in range(i + 1, len(pts)):
            dt = pts[j][0] - pts[i][0]
            if dt > 0:
                slopes.append((pts[j][1] - pts[i][1]) / dt)
    return _median(slopes) if slopes else None


def _mad_score(values: Sequence[float]) -> Optional[float]:
    """Signed robust anomaly score of the NEWEST value against the
    window: (last - median) / (1.4826 * MAD).  A zero MAD (constant
    window) scores 0 when the last value matches and ±inf when it
    broke away — exactly the "flat line just jumped" case."""
    if len(values) < 3:
        return None
    med = _median(values)
    mad = _median([abs(v - med) for v in values])
    dev = values[-1] - med
    if mad <= 0.0:
        return 0.0 if dev == 0.0 else math.copysign(math.inf, dev)
    return dev / (1.4826 * mad)


def _ewma(samples: Sequence[Tuple[float, float]],
          half_life_s: float) -> Optional[float]:
    if not samples:
        return None
    t_end = samples[-1][0]
    num = den = 0.0
    for t, v in samples:
        w = 0.5 ** ((t_end - t) / max(half_life_s, 1e-9))
        num += w * v
        den += w
    return num / den if den > 0 else None


#: reducer name -> callable(series, samples, **kw).  ``p<q>`` (e.g.
#: ``p99``) is parsed dynamically.
REDUCERS = (
    "last", "min", "max", "mean", "delta", "rate", "ewma", "slope",
    "mad_score", "frac_of_max", "frac_of_min",
)


class MetricRecorder:
    """Cadence-samples metric families into bounded per-series rings
    and answers windowed reductions — see the module docstring.

    Parameters
    ----------
    registry : optional :class:`~.registry.MetricsRegistry` that
        :meth:`sample` walks (families registered later are picked up
        automatically — the walk is by name).
    capacity : ring size per series (512 samples at a 5 s cadence is
        ~42 minutes of history).
    histogram_fields : which derived fields a sampled histogram series
        records (each becomes its own ring: ``count``/``sum`` are
        counter-kind, quantiles/mean gauge-kind).
    """

    def __init__(self, registry=None, capacity: int = 512,
                 clock: Callable[[], float] = time.monotonic,
                 histogram_fields: Sequence[str] = ("count", "sum",
                                                    "p50", "p99")):
        self.registry = registry
        self.capacity = int(capacity)
        self.clock = clock
        self.histogram_fields = tuple(histogram_fields)
        self._series: Dict[Tuple[str, str, str], Series] = {}
        self._lock = threading.Lock()
        self.samples_taken = 0

    # ------------------------------------------------------------ feeding
    @staticmethod
    def _labels_key(labels: Optional[dict]) -> str:
        return json.dumps({k: str(v) for k, v in (labels or {}).items()},
                          sort_keys=True)

    def _get_series(self, family: str, labels: Optional[dict],
                    field: str, kind: str) -> Series:
        key = (str(family), self._labels_key(labels), str(field))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = Series(kind=kind,
                                               capacity=self.capacity)
            return s

    def observe(self, family: str, value: float,
                labels: Optional[dict] = None, kind: str = "gauge",
                field: str = "value", now: Optional[float] = None):
        """Direct feed — the control-loop source (no registry walk).
        ``kind`` only matters on first touch of a series."""
        now = self.clock() if now is None else now
        self._get_series(family, labels, field, kind).add(now,
                                                          float(value))

    def sample(self, now: Optional[float] = None):
        """One cadence tick over the live registry: every family's
        every series lands one sample per field."""
        if self.registry is None:
            raise ValueError("recorder built without a registry — use "
                             "observe()/sample_metrics()")
        self.sample_metrics(self.registry.snapshot()["metrics"],
                            now=now)

    def sample_metrics(self, metrics: dict,
                       now: Optional[float] = None):
        """One cadence tick over any snapshot-shaped metrics dict —
        including the merged cluster view
        (:func:`~.aggregate.merge_metrics` output): the cross-host
        series merge rides the existing aggregate fold, not a second
        transport."""
        now = self.clock() if now is None else now
        for name, fam in (metrics or {}).items():
            kind = fam.get("type")
            for series in fam.get("series", ()):
                labels = series.get("labels") or {}
                if kind in ("counter", "gauge"):
                    v = series.get("value")
                    if v is not None:
                        self._get_series(name, labels, "value",
                                         kind).add(now, float(v))
                elif kind == "histogram":
                    for field in self.histogram_fields:
                        v = series.get(field)
                        if v is None:
                            continue
                        fkind = ("counter" if field in ("count", "sum")
                                 else "gauge")
                        self._get_series(name, labels, field,
                                         fkind).add(now, float(v))
        self.samples_taken += 1

    # ------------------------------------------------------------ reading
    def series(self, family: str, labels: Optional[dict] = None,
               field: str = "value") -> Optional[Series]:
        key = (str(family), self._labels_key(labels), str(field))
        with self._lock:
            return self._series.get(key)

    def series_labels(self, family: str,
                      field: str = "value") -> List[dict]:
        """Every label set a family has been fed under (the engine's
        per-replica rule discovery)."""
        with self._lock:
            return [json.loads(lk) for (fam, lk, f) in self._series
                    if fam == family and f == field]

    def age(self, family: str, labels: Optional[dict] = None,
            field: str = "value",
            now: Optional[float] = None) -> Optional[float]:
        """Seconds since the series was last fed; None when it has
        never been fed at all."""
        s = self.series(family, labels, field)
        last = s.last() if s is not None else None
        if last is None:
            return None
        now = self.clock() if now is None else now
        return max(0.0, now - last[0])

    def fresh(self, family: str, labels: Optional[dict] = None,
              field: str = "value", max_age_s: float = 60.0,
              now: Optional[float] = None) -> bool:
        age = self.age(family, labels, field, now=now)
        return age is not None and age <= max_age_s

    def reduce(self, family: str, reducer: str,
               labels: Optional[dict] = None, field: str = "value",
               window_s: float = 60.0, now: Optional[float] = None,
               half_life_s: Optional[float] = None,
               min_samples: int = 1) -> Optional[float]:
        """One windowed reduction; None when the series is missing or
        the window holds fewer than ``min_samples`` samples (no data
        is NO verdict, never a zero)."""
        s = self.series(family, labels, field)
        if s is None:
            return None
        now = self.clock() if now is None else now
        samples = s.window(now - float(window_s))
        if len(samples) < max(1, int(min_samples)):
            return None
        values = [v for _, v in samples]
        if reducer == "last":
            return values[-1]
        if reducer == "min":
            return min(values)
        if reducer == "max":
            return max(values)
        if reducer == "mean":
            return sum(values) / len(values)
        if reducer == "delta":
            if len(samples) < 2:
                return None
            return (_increase(samples) if s.kind == "counter"
                    else values[-1] - values[0])
        if reducer == "rate":
            if len(samples) < 2:
                return None
            dt = samples[-1][0] - samples[0][0]
            if dt <= 0:
                return None
            inc = (_increase(samples) if s.kind == "counter"
                   else values[-1] - values[0])
            return inc / dt
        if reducer == "ewma":
            return _ewma(samples, half_life_s
                         if half_life_s is not None
                         else float(window_s) / 4.0)
        if reducer == "slope":
            return _slope(samples)
        if reducer == "mad_score":
            return _mad_score(values)
        if reducer == "frac_of_max":
            top = max(values)
            return values[-1] / top if top > 0 else None
        if reducer == "frac_of_min":
            bot = min(values)
            return values[-1] / bot if bot > 0 else None
        if reducer.startswith("p") and reducer[1:].isdigit():
            q = int(reducer[1:]) / 100.0
            if not 0.0 <= q <= 1.0:
                raise ValueError(f"percentile {reducer!r} out of range")
            return _percentile(values, q)
        raise ValueError(f"unknown reducer {reducer!r}; one of "
                         f"{REDUCERS} or p<0-100>")

    def snapshot(self) -> dict:
        """Bounded JSON view: per-series sample counts + newest value
        + age (debug/report surface, not a data export)."""
        now = self.clock()
        out = {}
        with self._lock:
            items = list(self._series.items())
        for (fam, lk, field), s in sorted(items):
            last = s.last()
            out.setdefault(fam, []).append({
                "labels": json.loads(lk), "field": field,
                "kind": s.kind, "samples": len(s),
                "last": last[1] if last else None,
                "age_s": (now - last[0]) if last else None,
            })
        return {"series": out, "samples_taken": self.samples_taken}
