"""Goodput ledger — classify every second of run wall clock.

"Where did the last hour of cluster time go?" is the question the
BigDL paper's iteration analysis answers with per-phase accumulators;
at production scale the honest unit is not the step but the **run**:
a trainer that steps fast but spends half its life recompiling after
evictions has 50% goodput, and nothing in a step-time histogram says
so.  The ledger classifies run wall clock into exactly one of:

* ``productive``  — compiled steps doing real optimization work
* ``compile``     — XLA builds (the first step of every fresh program)
* ``data_stall``  — the device waited on the input pipeline
* ``checkpoint``  — writing / restoring state
* ``recovery``    — fault detected → first post-restore productive
  step (retry backoff, rendezvous, re-shard all land here)
* ``idle``        — the remainder (validation, logging, host python)

``accounted_fraction`` is attributed ÷ wall **including idle**: idle
is a named bucket, not an excuse, so the ledger always explains where
the time went — the acceptance bar for a merged cluster snapshot is
>= 99% accounted.  The clock is injectable; tests drive it by hand.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

__all__ = ["GOODPUT_CATEGORIES", "GoodputLedger"]

GOODPUT_CATEGORIES = (
    "productive", "compile", "data_stall", "checkpoint", "recovery",
    "idle",
)


class GoodputLedger:
    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._start: Optional[float] = None
        self._seconds: Dict[str, float] = {
            c: 0.0 for c in GOODPUT_CATEGORIES if c != "idle"}
        self._recovery_since: Optional[float] = None
        self.recovery_windows = 0

    # -- lifecycle ------------------------------------------------------
    def start(self):
        """Start (or continue) the run clock — idempotent, so every
        retry attempt may call it and only the first one counts."""
        with self._lock:
            if self._start is None:
                self._start = self._clock()
        return self

    @property
    def started(self) -> bool:
        with self._lock:
            return self._start is not None

    # -- attribution ----------------------------------------------------
    def add(self, category: str, seconds: float):
        if category == "idle":
            raise ValueError("idle is derived (wall - attributed), "
                             "never added")
        if category not in self._seconds:
            raise ValueError(f"unknown goodput category {category!r}; "
                             f"one of {GOODPUT_CATEGORIES}")
        with self._lock:
            if self._start is None:
                self._start = self._clock()
            self._seconds[category] += max(0.0, float(seconds))

    def recovery_begin(self):
        """A fault was detected: wall clock from now until
        :meth:`recovery_end` is recovery, whatever python it runs."""
        with self._lock:
            if self._start is None:
                self._start = self._clock()
            if self._recovery_since is None:
                self._recovery_since = self._clock()
                self.recovery_windows += 1

    def recovery_end(self, exclude: float = 0.0) -> float:
        """First productive work after a fault: close the window.
        ``exclude`` trims seconds off the tail — the caller learns of
        the recovery's end only AFTER the first post-restore step
        completed, and that step's own duration is attributed as
        compile/productive, not recovery (no double counting).
        Returns the window's attributed seconds (0.0 when none was
        open)."""
        with self._lock:
            if self._recovery_since is None:
                return 0.0
            dt = max(0.0, self._clock() - self._recovery_since
                     - max(0.0, float(exclude)))
            self._seconds["recovery"] += dt
            self._recovery_since = None
            return dt

    @property
    def in_recovery(self) -> bool:
        with self._lock:
            return self._recovery_since is not None

    # -- reporting ------------------------------------------------------
    def snapshot(self) -> dict:
        """Wall clock, per-category seconds (idle = the unattributed
        remainder, an open recovery window counted live), productive
        and accounted fractions."""
        with self._lock:
            now = self._clock()
            wall = (now - self._start) if self._start is not None else 0.0
            secs = dict(self._seconds)
            if self._recovery_since is not None:
                secs["recovery"] += now - self._recovery_since
            attributed = sum(secs.values())
            secs["idle"] = max(0.0, wall - attributed)
            total = attributed + secs["idle"]
            # < 1.0 only when attribution OVERLAPPED (sum > wall); the
            # drivers attribute disjoint segments, so ~1.0
            accounted = min(1.0, wall / total) if total > 0 else 1.0
            return {
                "wall_s": wall,
                "seconds": secs,
                "productive_fraction": (secs["productive"] / wall
                                        if wall > 0 else 0.0),
                "accounted_fraction": accounted,
            }

    @staticmethod
    def merge_snapshots(snaps) -> dict:
        """Cluster view: per-category seconds and wall clock summed
        over host snapshots (host-seconds, the unit cluster goodput is
        honestly measured in)."""
        snaps = list(snaps)
        secs = {c: 0.0 for c in GOODPUT_CATEGORIES}
        wall = 0.0
        for s in snaps:
            wall += float(s.get("wall_s", 0.0))
            for c, v in (s.get("seconds") or {}).items():
                secs[c] = secs.get(c, 0.0) + float(v)
        attributed = sum(secs.values())
        return {
            "hosts": len(snaps),
            "wall_s": wall,
            "seconds": secs,
            "productive_fraction": (secs["productive"] / wall
                                    if wall > 0 else 0.0),
            "accounted_fraction": (min(1.0, wall / attributed)
                                   if attributed > 0 else 1.0),
        }
