"""Fleet-wide change journal: the typed, ordered record of every
state-changing act any subsystem performs.

The observability stack can *detect* degradation (the SLO engine's
burn-rate/anomaly alerts) and *measure* where latency lives (critical-
path traces); what it could not answer before this module is "what
CHANGED?" — deploys, rollbacks, membership evictions, autoscale moves,
breaker trips, registry flips, tenant-quota sheds and chaos injections
were scattered across per-subsystem logs.  The
:class:`ChangeJournal` is the one bounded, ordered ring they all emit
into, and the :class:`~.incidents.IncidentEngine` reads it back to
align "metric went bad at T" with "something changed at T-ε".

Every event is a :class:`ChangeEvent`:

* ``kind``     — a short verb from the event vocabulary
  (``deploy_started``, ``membership_evict``, ``autoscale_up``,
  ``breaker_open``, ``tenant_shed``, ``chaos_inject``, ...);
* ``at``       — journal-clock time (``time.monotonic`` by default, so
  event times are directly comparable with
  :class:`~.timeseries.MetricRecorder` sample times);
* ``scope``    — the blast radius as labels: any of
  ``host`` / ``replica`` / ``pool`` / ``model`` / ``tenant`` /
  ``table``.  An empty scope means fleet-wide.  Scope is what lets
  attribution rank an event touching the breached series' replica
  above one touching the whole fleet;
* ``ground_truth`` — ``True`` only when a chaos injector
  (:mod:`bigdl_tpu.resilience.faults`) recorded the event at arm time.
  Benches score blame rankings against these; production code never
  sets it.

Journal writes are lock-cheap (one deque append + one counter inc) —
safe on pump/dispatch paths.  High-rate sites (per-request tenant
sheds) use :meth:`ChangeJournal.record_throttled` so a flood cannot
evict the deploy event that explains it out of the bounded ring.

A process-wide default journal mirrors the ``default_registry``
pattern: subsystems call :func:`record_change` unconditionally, tests
isolate with :func:`reset_default_journal`.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from . import metric_names as M
from .registry import default_registry

__all__ = [
    "CHANGE_EVENT_KINDS", "ChangeEvent", "ChangeJournal",
    "default_journal", "record_change", "reset_default_journal",
]

#: scope keys an event may carry (anything else is dropped at record
#: time — the vocabulary stays closed so attribution can match scopes
#: against SLO rule labels without guessing)
SCOPE_KEYS = ("host", "replica", "pool", "model", "tenant", "table")

#: the event vocabulary — every ``kind`` any subsystem records.  Like
#: the metric-name table this is NAMES only; emitting an unlisted kind
#: raises, so the vocabulary cannot drift silently.
CHANGE_EVENT_KINDS = frozenset({
    # deploys (serving/fleet.py, serving/swap.py, loop/continuous.py)
    "deploy_started", "deploy_confirmed", "deploy_rejected",
    "deploy_rolled_back",
    # fleet elasticity (serving/fleet.py)
    "replica_added", "replica_removed", "replica_restarted",
    # cluster membership (resilience/elastic.py)
    "membership_change", "membership_evict", "membership_readmit",
    # autoscaler verdicts (serving/autoscale.py)
    "autoscale_up", "autoscale_down",
    # circuit breaker transitions (serving/breaker.py)
    "breaker_open", "breaker_half_open", "breaker_closed",
    # model registry flips (serving/registry.py)
    "model_registered", "model_unregistered",
    # admission control (serving/router.py)
    "tenant_shed",
    # chaos injections (resilience/faults.py, ground_truth=True)
    "chaos_inject", "chaos_clear",
})


@dataclass(frozen=True)
class ChangeEvent:
    """One recorded state change — see the module docstring."""
    seq: int
    kind: str
    at: float
    scope: Dict[str, str] = field(default_factory=dict)
    detail: str = ""
    ground_truth: bool = False
    source: str = ""

    def to_dict(self) -> dict:
        return {"seq": self.seq, "kind": self.kind,
                "at": round(self.at, 6), "scope": dict(self.scope),
                "detail": self.detail,
                "ground_truth": self.ground_truth,
                "source": self.source}


class ChangeJournal:
    """Bounded, ordered, thread-safe ring of :class:`ChangeEvent`.

    ``clock`` defaults to ``time.monotonic`` so event times share the
    :class:`~.timeseries.MetricRecorder` timebase; benches inject a
    fake clock into both for deterministic alignment.
    """

    def __init__(self, capacity: int = 2048,
                 clock: Optional[Callable[[], float]] = None,
                 registry=None):
        self.capacity = max(1, int(capacity))
        self._clock = clock or time.monotonic
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._next_seq = 0
        #: (kind, throttle-key) -> last record time
        self._throttle: Dict[tuple, float] = {}
        self._counter = (registry if registry is not None
                         else default_registry()).counter(
            M.CHANGE_EVENTS_TOTAL,
            "state-change events recorded into the change journal",
            labels=("kind",))
        self.dropped = 0   # throttled (never recorded) events

    # ------------------------------------------------------------ write
    def record(self, kind: str, detail: str = "", *,
               ground_truth: bool = False, source: str = "",
               now: Optional[float] = None,
               **scope) -> ChangeEvent:
        """Append one event.  ``scope`` keyword args are restricted to
        :data:`SCOPE_KEYS`; ``None`` values are dropped so call sites
        can pass optional model/tenant straight through."""
        if kind not in CHANGE_EVENT_KINDS:
            raise ValueError(
                f"unknown change-event kind {kind!r} — add it to "
                f"telemetry.events.CHANGE_EVENT_KINDS first")
        clean = {k: str(v) for k, v in scope.items()
                 if k in SCOPE_KEYS and v is not None}
        at = self._clock() if now is None else float(now)
        with self._lock:
            ev = ChangeEvent(seq=self._next_seq, kind=kind, at=at,
                             scope=clean, detail=str(detail),
                             ground_truth=bool(ground_truth),
                             source=str(source))
            self._next_seq += 1
            self._events.append(ev)
        self._counter.labels(kind=kind).inc()
        return ev

    def record_throttled(self, kind: str, detail: str = "", *,
                         key: str = "", min_interval_s: float = 1.0,
                         ground_truth: bool = False, source: str = "",
                         now: Optional[float] = None,
                         **scope) -> Optional[ChangeEvent]:
        """Like :meth:`record` but drops repeats of (kind, key) inside
        ``min_interval_s`` — for high-rate sites (per-request tenant
        sheds) where a flood must not evict the deploy event that
        explains it out of the ring.  Returns None on a drop."""
        at = self._clock() if now is None else float(now)
        tk = (kind, key)
        with self._lock:
            last = self._throttle.get(tk)
            if last is not None and (at - last) < min_interval_s:
                self.dropped += 1
                return None
            self._throttle[tk] = at
        return self.record(kind, detail, ground_truth=ground_truth,
                           source=source, now=at, **scope)

    # ------------------------------------------------------------ read
    def events(self, since: Optional[float] = None,
               until: Optional[float] = None) -> List[ChangeEvent]:
        """Events with ``since <= at <= until`` (inclusive, either
        side optional), oldest first."""
        with self._lock:
            evs = list(self._events)
        if since is not None:
            evs = [e for e in evs if e.at >= since]
        if until is not None:
            evs = [e for e in evs if e.at <= until]
        return evs

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def snapshot(self, limit: int = 128) -> dict:
        """The newest ``limit`` events plus counts, as plain dicts."""
        with self._lock:
            evs = list(self._events)[-max(0, int(limit)):]
            recorded = self._next_seq
        return {"events": [e.to_dict() for e in evs],
                "recorded": recorded,
                "dropped_throttled": self.dropped,
                "capacity": self.capacity}


# ---------------------------------------------------------------------------
# the process-wide journal subsystems record into
# ---------------------------------------------------------------------------

_default_lock = threading.Lock()
_default: Optional[ChangeJournal] = None


def default_journal() -> ChangeJournal:
    """The process-wide change journal.  Serving/resilience internals
    record into it unconditionally (writes are cheap); an
    :class:`~.incidents.IncidentEngine` built without an explicit
    journal adopts it, so one capture sees the whole process."""
    global _default
    with _default_lock:
        if _default is None:
            _default = ChangeJournal()
        return _default


def reset_default_journal(
        clock: Optional[Callable[[], float]] = None) -> ChangeJournal:
    """Swap in a fresh default journal (tests/benches isolate with
    this; ``clock`` lets a bench pin the journal to its fake clock)."""
    global _default
    with _default_lock:
        _default = ChangeJournal(clock=clock)
        return _default


def record_change(kind: str, detail: str = "", *,
                  ground_truth: bool = False, source: str = "",
                  now: Optional[float] = None,
                  throttle_key: Optional[str] = None,
                  min_interval_s: float = 1.0,
                  **scope) -> Optional[ChangeEvent]:
    """Record into the process-wide journal (the one-line call every
    instrumented subsystem makes).  ``throttle_key`` switches to the
    throttled path."""
    j = default_journal()
    if throttle_key is not None:
        return j.record_throttled(kind, detail, key=throttle_key,
                                  min_interval_s=min_interval_s,
                                  ground_truth=ground_truth,
                                  source=source, now=now, **scope)
    return j.record(kind, detail, ground_truth=ground_truth,
                    source=source, now=now, **scope)
