"""Background publisher — cross-host KV publishing off the step path.

Elastic telemetry snapshots and SDC-vote checksums ride the KV
transport (``FileKV`` writes real files; a production etcd/redis put is
a network round trip).  Doing those puts inline means transport
latency lands directly in step wall clock.  This publisher moves them
to a single daemon thread with three properties the elastic layer
needs:

* **never blocks the caller** — the work deque is bounded; when full,
  the oldest coalescible task is dropped (telemetry snapshots are
  "newest wins" by contract, so dropping a stale one loses nothing);
* **incarnation-keyed staleness discard** — each task may carry the
  incarnation it was created under; at execution time a task from a
  membership that no longer exists is discarded instead of published
  (the same rule the ``tm/<incarnation>/<host>`` keyspace encodes);
* **coalescing** — tasks sharing a ``key`` replace their queued
  predecessor (one pending telemetry snapshot, not a backlog), while
  ``urgent`` tasks (vote checksums — a synchronous round is waiting on
  them) jump the queue.

:meth:`BackgroundPublisher.drain` is the barrier for readers that need
their own freshest payload visible before collecting (the leader's
``cluster_snapshot``).
"""
from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Callable, Optional

log = logging.getLogger("bigdl_tpu")

__all__ = ["BackgroundPublisher"]


class _Task:
    __slots__ = ("fn", "incarnation", "key")

    def __init__(self, fn, incarnation, key):
        self.fn = fn
        self.incarnation = incarnation
        self.key = key


class BackgroundPublisher:
    def __init__(self, incarnation_of: Optional[Callable[[], int]] = None,
                 capacity: int = 16, name: str = "bigdl-publisher"):
        self._incarnation_of = incarnation_of
        self.capacity = max(1, int(capacity))
        self._name = name
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._dq: deque = deque()
        self._in_flight = 0
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        # -- counters ---------------------------------------------------
        self.published = 0
        self.discarded_stale = 0
        self.coalesced = 0
        self.dropped = 0
        self.errors = 0

    # -- internals -------------------------------------------------------
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name=self._name)
            self._thread.start()

    def _run(self):
        while True:
            with self._cv:
                while not self._dq and not self._closed:
                    self._cv.wait()
                if not self._dq and self._closed:
                    return
                task = self._dq.popleft()
                self._in_flight += 1
            try:
                stale = (task.incarnation is not None
                         and self._incarnation_of is not None
                         and self._incarnation_of() != task.incarnation)
                if stale:
                    with self._cv:
                        self.discarded_stale += 1
                else:
                    task.fn()
                    with self._cv:
                        self.published += 1
            except Exception:
                with self._cv:
                    self.errors += 1
                log.warning("background publish failed", exc_info=True)
            finally:
                with self._cv:
                    self._in_flight -= 1
                    self._cv.notify_all()

    # -- API -------------------------------------------------------------
    def submit(self, fn: Callable[[], None], *,
               incarnation: Optional[int] = None,
               key: Optional[str] = None, urgent: bool = False) -> bool:
        """Queue ``fn`` for background execution; returns False when
        the publisher is closed (the caller should fall back to a
        synchronous publish).  Never blocks."""
        task = _Task(fn, incarnation, key)
        with self._cv:
            if self._closed:
                return False
            if key is not None:
                for old in list(self._dq):
                    if old.key == key:
                        self._dq.remove(old)
                        self.coalesced += 1
                        break
            if len(self._dq) >= self.capacity:
                # bounded: shed the oldest non-urgent backlog entry
                self._dq.popleft()
                self.dropped += 1
            if urgent:
                self._dq.appendleft(task)
            else:
                self._dq.append(task)
            self._cv.notify_all()
        self._ensure_thread()
        return True

    def drain(self, timeout: Optional[float] = 5.0) -> bool:
        """Block until the queue is empty and nothing is in flight —
        the freshest submitted payload is then visible to collectors.
        Returns False on timeout."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._dq or self._in_flight:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining if remaining is not None else 0.5)
        return True

    def close(self, timeout: float = 5.0):
        self.drain(timeout=timeout)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    @property
    def backlog(self) -> int:
        with self._cv:
            return len(self._dq) + self._in_flight
