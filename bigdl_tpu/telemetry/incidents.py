"""Incident engine: black-box capture + causal attribution over the
change journal.

The SLO engine (:mod:`.slo`) answers *that* something broke; the
change journal (:mod:`.events`) records *what changed*; this module
joins them.  An :class:`IncidentEngine` subscribes to SLO alert
transitions (chain :meth:`observe` after ``SloEngine.evaluate`` —
the :class:`~bigdl_tpu.serving.health.FleetHealthMonitor` does this
when built with one).  On a rule's ``ok → firing`` edge it opens an
:class:`Incident` and freezes the **black box**:

* the breached metric's own time-series slice over the pre-window,
  plus correlated series — every recorder series whose label set
  shares a (key, value) with the breached rule's labels, capped;
* the journal slice covering ``[breach - pre_window, finalize]``;
* optionally, kept traces in-window from a pluggable
  ``trace_provider(since, until) -> list`` (the tail sampler's store
  lives fleet-side, so the provider is injected, not imported).

The incident stays open for ``post_intervals`` further observe rounds
(the post-window — events landing *after* the breach still make the
timeline), then finalizes:

1. **Deflection onset** — the breached series' pre-window samples are
   scanned for the first point deviating > 3 robust sigmas (MAD) from
   the pre-window baseline; the alert's ``for_intervals`` hysteresis
   means the true onset PRECEDES the firing edge, and alignment
   against onset, not breach, is what separates the deploy that
   caused the regression from the autoscale move that reacted to it.
2. **Suspect ranking** — every journal event in the capture window is
   scored: *scope match* (a (key, value) shared with the breached
   labels outranks fleet-wide; a conflicting value ranks below it) +
   *time proximity* to onset (earlier-and-near beats later;
   effect-before-cause is damped, not excluded — clock granularity) +
   a small *disruptiveness prior* on kinds that historically cause
   incidents (deploys, evictions, chaos).  Ties break on journal
   order.  The ranked list is the incident's answer to "what
   changed?"; ``ground_truth`` events let benches score it.

Snapshots publish through :meth:`Telemetry.payload` (``incidents``
key) and fold cluster-wide via
:func:`~.aggregate.merge_incidents`, exactly like alerts.
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from . import metric_names as M
from .events import ChangeEvent, ChangeJournal, default_journal
from .registry import default_registry
from .timeseries import MetricRecorder

__all__ = ["Incident", "IncidentEngine", "IncidentPolicy"]

#: kinds that historically *cause* incidents (vs react to them) —
#: a small additive prior, never enough to outrank a scope match
_DISRUPTIVE_KINDS = frozenset({
    "deploy_started", "deploy_rolled_back", "membership_evict",
    "replica_removed", "chaos_inject",
})


@dataclass
class IncidentPolicy:
    """Capture-window + ranking knobs."""
    #: seconds of pre-breach history frozen into the black box
    pre_window_s: float = 60.0
    #: observe rounds the incident stays open post-breach
    post_intervals: int = 3
    #: correlated series captured besides the breached one (cap)
    max_correlated: int = 8
    #: ranked suspects kept on the finalized incident
    max_suspects: int = 5
    #: samples kept per captured series (newest first wins)
    max_samples: int = 256
    #: a rule that re-fires within this many seconds of its last
    #: incident's open does NOT open a second one (flap guard)
    cooldown_s: float = 30.0
    #: proximity decay constant (seconds) for the time-alignment term
    proximity_tau_s: float = 15.0


@dataclass
class Incident:
    """One opened (and eventually finalized) incident bundle."""
    id: str
    rule: str
    severity: str
    opened_at: float               # metric-clock time of the breach
    value: Optional[float]
    labels: Dict[str, str] = field(default_factory=dict)
    status: str = "open"           # open | finalized
    onset_at: Optional[float] = None
    #: {"<family>|<field>|<labels-json>": [[t, v], ...]}
    series: Dict[str, List] = field(default_factory=dict)
    events: List[dict] = field(default_factory=list)
    traces: List = field(default_factory=list)
    suspects: List[dict] = field(default_factory=list)
    finalized_at: Optional[float] = None
    capture_latency_s: float = 0.0
    rounds_left: int = 0

    def to_dict(self) -> dict:
        return {
            "id": self.id, "rule": self.rule,
            "severity": self.severity,
            "opened_at": round(self.opened_at, 6),
            "value": self.value, "labels": dict(self.labels),
            "status": self.status,
            "onset_at": (round(self.onset_at, 6)
                         if self.onset_at is not None else None),
            "series": {k: [[round(t, 6), v] for t, v in s]
                       for k, s in self.series.items()},
            "events": list(self.events),
            "traces": list(self.traces),
            "suspects": list(self.suspects),
            "finalized_at": self.finalized_at,
            "capture_latency_s": round(self.capture_latency_s, 6),
        }


class IncidentEngine:
    """Opens, captures and attributes incidents — see the module
    docstring.

    Parameters
    ----------
    recorder : the :class:`~.timeseries.MetricRecorder` the SLO rules
        evaluate over (the black box slices ITS series).
    journal : the :class:`~.events.ChangeJournal` to align against
        (default: the process-wide journal).
    engine : optional :class:`~.slo.SloEngine` — lets the capture
        resolve a firing rule's family/labels (without it, only the
        alert's label set scopes the capture).
    trace_provider : optional ``(since, until) -> list`` returning
        kept-trace summaries in-window.
    """

    def __init__(self, recorder: MetricRecorder,
                 journal: Optional[ChangeJournal] = None,
                 engine=None,
                 policy: Optional[IncidentPolicy] = None,
                 registry=None,
                 trace_provider: Optional[
                     Callable[[float, float], list]] = None,
                 clock: Optional[Callable[[], float]] = None,
                 max_incidents: int = 32):
        self.recorder = recorder
        self.journal = journal if journal is not None \
            else default_journal()
        self.engine = engine
        self.policy = policy or IncidentPolicy()
        self.trace_provider = trace_provider
        self.clock = clock or getattr(recorder, "clock", time.monotonic)
        self._open: Dict[str, Incident] = {}     # rule -> incident
        self._recent: deque = deque(maxlen=max(1, int(max_incidents)))
        self._last_opened: Dict[str, float] = {} # rule -> opened_at
        self._lock = threading.Lock()
        self._n = 0
        reg = registry if registry is not None else default_registry()
        self._ctr = reg.counter(
            M.INCIDENTS_TOTAL, "incidents opened",
            labels=("severity",))
        self._gauge = reg.gauge(
            M.INCIDENTS_ACTIVE,
            "incidents holding an open capture window")

    # ------------------------------------------------------------ rules
    def _rule_obj(self, name: str):
        if self.engine is None:
            return None
        for r in self.engine.rules:
            if r.name == name:
                return r
        return None

    # ------------------------------------------------------------ observe
    def observe(self, transitions=None,
                now: Optional[float] = None) -> List[Incident]:
        """One round: open incidents for fresh ``firing`` transitions,
        advance the post-window of everything already open, finalize
        what expired.  ``transitions`` accepts
        :class:`~.slo.Alert` objects or their dicts (what
        ``SloEngine.evaluate`` / ``FleetHealthMonitor.observe``
        return).  Returns incidents finalized THIS round."""
        now = self.clock() if now is None else float(now)
        opened_now = set()
        for tr in (transitions or ()):
            a = tr if isinstance(tr, dict) else tr.to_dict()
            if a.get("state") != "firing":
                continue
            if self._maybe_open(a, now):
                opened_now.add(str(a.get("rule")))
        return self._advance(now, skip=opened_now)

    def _maybe_open(self, alert: dict, now: float) -> bool:
        rule = str(alert.get("rule"))
        with self._lock:
            if rule in self._open:
                return False
            last = self._last_opened.get(rule)
            if last is not None \
                    and (now - last) < self.policy.cooldown_s:
                return False
            self._n += 1
            inc = Incident(
                id=f"inc-{self._n:04d}", rule=rule,
                severity=str(alert.get("severity") or "page"),
                opened_at=float(alert.get("at") or now),
                value=alert.get("value"),
                labels=dict(alert.get("labels") or {}),
                rounds_left=max(0, int(self.policy.post_intervals)))
            self._open[rule] = inc
            self._last_opened[rule] = now
        t0 = time.perf_counter()
        self._capture(inc)
        inc.capture_latency_s = time.perf_counter() - t0
        self._ctr.labels(severity=inc.severity).inc()
        self._gauge.set(len(self._open))
        return True

    # ------------------------------------------------------------ capture
    def _series_key(self, family: str, fld: str, labels: dict) -> str:
        lk = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
        return f"{family}|{fld}|{{{lk}}}"

    def _slice(self, family: str, labels: dict, fld: str,
               since: float) -> List:
        s = self.recorder.series(family, labels or None, fld)
        if s is None:
            return []
        samples = s.window(since)
        return samples[-self.policy.max_samples:]

    def _capture(self, inc: Incident):
        """Freeze the pre-window black box: the breached series plus
        scope-correlated neighbors."""
        since = inc.opened_at - self.policy.pre_window_s
        rule = self._rule_obj(inc.rule)
        breached = []
        if rule is not None and rule.family:
            key = self._series_key(rule.family, rule.signal,
                                   rule.labels)
            samples = self._slice(rule.family, rule.labels,
                                  rule.signal, since)
            if samples:
                inc.series[key] = samples
                breached = samples
            if getattr(rule, "total_family", ""):
                tkey = self._series_key(rule.total_family,
                                        rule.total_signal,
                                        rule.total_labels)
                ts = self._slice(rule.total_family, rule.total_labels,
                                 rule.total_signal, since)
                if ts:
                    inc.series[tkey] = ts
        # correlated families: any recorder series sharing a
        # (key, value) with the breached labels (capped)
        want = set((inc.labels or {}).items())
        if want:
            snap = self.recorder.snapshot()["series"]
            taken = 0
            for fam in sorted(snap):
                for entry in snap[fam]:
                    if taken >= self.policy.max_correlated:
                        break
                    labels = entry.get("labels") or {}
                    fld = entry.get("field") or "value"
                    key = self._series_key(fam, fld, labels)
                    if key in inc.series:
                        continue
                    if not (want & set(labels.items())):
                        continue
                    samples = self._slice(fam, labels, fld, since)
                    if samples:
                        inc.series[key] = samples
                        taken += 1
        inc.onset_at = self._onset(breached, inc.opened_at)

    @staticmethod
    def _onset(samples: List, breach_at: float) -> float:
        """First sample deviating > 3 robust sigmas from the
        pre-window baseline — the deflection onset the suspects align
        against.  Falls back to the breach time."""
        pre = [(t, v) for t, v in samples if t <= breach_at]
        if len(pre) < 4:
            return breach_at
        vals = sorted(v for _, v in pre)
        mid = len(vals) // 2
        med = (vals[mid] if len(vals) % 2
               else 0.5 * (vals[mid - 1] + vals[mid]))
        devs = sorted(abs(v - med) for _, v in pre)
        mad = (devs[mid] if len(devs) % 2
               else 0.5 * (devs[mid - 1] + devs[mid]))
        sigma = 1.4826 * mad
        if sigma <= 0.0:
            # constant baseline: onset is the first value that moved
            for t, v in pre:
                if v != med:
                    return t
            return breach_at
        for t, v in pre:
            if abs(v - med) > 3.0 * sigma:
                return t
        return breach_at

    # ------------------------------------------------------------ finalize
    def _advance(self, now: float, skip=()) -> List[Incident]:
        done: List[Incident] = []
        with self._lock:
            open_incs = list(self._open.items())
        for rule, inc in open_incs:
            if rule in skip:
                continue      # opened THIS round: the post-window
            inc.rounds_left -= 1     # starts next observe round
            if inc.rounds_left > 0:
                continue
            t0 = time.perf_counter()
            self._finalize(inc, now)
            inc.capture_latency_s += time.perf_counter() - t0
            with self._lock:
                self._open.pop(rule, None)
                self._recent.append(inc)
            done.append(inc)
        if done:
            self._gauge.set(len(self._open))
        return done

    def _finalize(self, inc: Incident, now: float):
        since = inc.opened_at - self.policy.pre_window_s
        events = self.journal.events(since=since, until=now)
        inc.events = [e.to_dict() for e in events]
        if self.trace_provider is not None:
            try:
                inc.traces = list(
                    self.trace_provider(since, now) or ())
            except Exception:
                inc.traces = []
        onset = inc.onset_at if inc.onset_at is not None \
            else inc.opened_at
        scored = []
        for i, ev in enumerate(events):
            scored.append((self._score(ev, inc.labels, onset), -i, ev))
        scored.sort(key=lambda s: (-s[0], s[1]))
        inc.suspects = [
            dict(ev.to_dict(), score=round(score, 4), rank=r + 1)
            for r, (score, _, ev) in
            enumerate(scored[:self.policy.max_suspects])]
        inc.status = "finalized"
        inc.finalized_at = now

    def _score(self, ev: ChangeEvent, breached: Dict[str, str],
               onset: float) -> float:
        """Scope match + time proximity + disruptiveness prior — the
        blame-ranking rules (documented in docs/observability.md)."""
        score = 0.0
        for k, v in (ev.scope or {}).items():
            want = (breached or {}).get(k)
            if want is None:
                continue
            score += 2.0 if str(want) == str(v) else -2.0
        # an event with NO scope is fleet-wide: plausible for any
        # breach, but a scoped match must outrank it
        if not ev.scope:
            score += 0.5
        dt = onset - ev.at
        tau = max(1e-6, self.policy.proximity_tau_s)
        if dt >= 0.0:
            # cause precedes effect: nearer-to-onset is stronger
            score += 1.5 * math.exp(-dt / tau)
        else:
            # event after onset: damped, not excluded (clock
            # granularity can invert cause/effect by one tick)
            score += 0.75 * math.exp(dt / tau)
        if ev.kind in _DISRUPTIVE_KINDS:
            score += 0.25
        return score

    # ------------------------------------------------------------ reading
    @property
    def opened_total(self) -> int:
        with self._lock:
            return self._n

    def open_incidents(self) -> List[Incident]:
        with self._lock:
            return list(self._open.values())

    def incidents(self) -> List[Incident]:
        """Finalized incidents, oldest first (bounded)."""
        with self._lock:
            return list(self._recent)

    def snapshot(self) -> dict:
        """The publishable view — what ``Telemetry.payload`` ships
        under ``incidents`` and ``merge_incidents`` folds."""
        with self._lock:
            open_ = [i.to_dict() for i in self._open.values()]
            recent = [i.to_dict() for i in self._recent]
            n = self._n
        return {"open": open_, "recent": recent, "opened": n}
