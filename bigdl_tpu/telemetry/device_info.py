"""Device capability table — the ONE copy of per-chip peaks.

``bench.py`` carried the bf16 peak-FLOP/s table and
``models/resnet_mfu_lab.py`` reached into it through a lazy
file-path import; every future consumer (the PerfAccountant's MFU
and roofline math, serving goodput-per-chip) would have grown the
same cross-import.  The table lives here now; ``bench.py`` keeps a
compat shim.

Numbers are public spec-sheet figures per **chip**:

* ``peak_flops_per_sec`` — dense bf16 peak, multiply-add counted as
  2 FLOPs (the MFU denominator convention).
* ``hbm_bytes`` / ``hbm_bytes_per_sec`` — HBM capacity and bandwidth
  (the roofline's memory axis; the ridge point is
  ``peak_flops / hbm_bw``).
* ``ici_bytes_per_sec`` — aggregate inter-chip interconnect
  bandwidth.  Interconnect counting conventions vary between spec
  sheets (per-link vs aggregate, per-direction vs bidirectional);
  these are order-of-magnitude figures for roofline *classification*
  (is this program collective-bound?), not for bandwidth accounting.

The CPU row is **nominal** (``nominal=True``): a placeholder peak so
MFU-family metrics stay computable (and testable) on the CPU backend;
absolute CPU MFU values are not meaningful.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

__all__ = [
    "DEVICE_SPECS", "DeviceSpec", "PEAK_FLOPS_TABLE",
    "current_device_spec", "device_spec", "peak_flops_per_sec",
]

GiB = 1024 ** 3


class DeviceSpec(NamedTuple):
    """Per-chip capability row (see module docstring for units)."""

    kind: str
    peak_flops_per_sec: float
    hbm_bytes: Optional[float]
    hbm_bytes_per_sec: Optional[float]
    ici_bytes_per_sec: Optional[float]
    nominal: bool = False

    @property
    def ridge_flops_per_byte(self) -> Optional[float]:
        """The roofline ridge point: arithmetic intensity above which
        the chip is compute-bound rather than HBM-bound."""
        if not self.hbm_bytes_per_sec:
            return None
        return self.peak_flops_per_sec / self.hbm_bytes_per_sec

    def to_dict(self) -> dict:
        return dict(self._asdict())


# substring-matched against jax's device_kind (lowercased), first hit
# wins — mirrors the original bench.py table order
DEVICE_SPECS = (
    DeviceSpec("v6e", 918e12, 32 * GiB, 1640e9, 900e9),
    DeviceSpec("trillium", 918e12, 32 * GiB, 1640e9, 900e9),
    DeviceSpec("v5p", 459e12, 95 * GiB, 2765e9, 1200e9),
    DeviceSpec("v5e", 197e12, 16 * GiB, 819e9, 400e9),
    DeviceSpec("v5litepod", 197e12, 16 * GiB, 819e9, 400e9),
    DeviceSpec("v5 lite", 197e12, 16 * GiB, 819e9, 400e9),
    DeviceSpec("v4", 275e12, 32 * GiB, 1228e9, 1200e9),
    DeviceSpec("v3", 123e12, 32 * GiB, 900e9, 656e9),
    DeviceSpec("v2", 45e12, 16 * GiB, 700e9, 496e9),
)

#: nominal CPU row: ~a few f32 GEMM cores' worth of peak and one
#: DDR channel group of bandwidth — keeps MFU/roofline math exercised
#: on the CPU backend without pretending to measure the host
CPU_SPEC = DeviceSpec("cpu", 100e9, None, 20e9, None, nominal=True)

#: (kind substring, bf16 peak FLOP/s) — the shape bench.py always had
PEAK_FLOPS_TABLE = tuple(
    (s.kind, s.peak_flops_per_sec) for s in DEVICE_SPECS)


def peak_flops_per_sec(device_kind: str) -> Optional[float]:
    """bf16 peak FLOP/s per chip for a jax ``device_kind`` string, or
    None when unknown (the bench.py contract: a CPU/unknown device has
    no honest peak and reports no MFU)."""
    spec = device_spec(device_kind)
    return None if spec is None or spec.nominal \
        else spec.peak_flops_per_sec


def device_spec(device_kind: str) -> Optional[DeviceSpec]:
    """Capability row for a ``device_kind`` string: substring match
    against the table, the nominal CPU row for cpu/host kinds, None
    for anything else."""
    k = (device_kind or "").lower()
    for spec in DEVICE_SPECS:
        if spec.kind in k:
            return spec
    if "cpu" in k or "host" in k or "interpreter" in k:
        return CPU_SPEC
    return None


def current_device_spec(device=None) -> DeviceSpec:
    """Spec for a live jax device (default: ``jax.devices()[0]``).
    Unknown accelerators degrade to the nominal CPU row rather than
    None — the accountant always has *a* denominator, flagged
    ``nominal`` when it is not a measured-peak claim."""
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "") or str(device)
    return device_spec(kind) or CPU_SPEC
