"""Run-report rendering: a text table answering "where did the time
go?" from a snapshot directory (or an already-merged cluster view).

``tools/run_report.py`` is the CLI wrapper; the rendering lives here
so tests and notebooks can call it on in-memory payloads.
"""
from __future__ import annotations

from typing import Dict, List

from .aggregate import merge_cluster, read_snapshot_dir

__all__ = ["render_report", "report_from_dir"]


def _bar(frac: float, width: int = 24) -> str:
    n = int(round(max(0.0, min(1.0, frac)) * width))
    return "#" * n + "." * (width - n)


def render_report(cluster: dict, top_n: int = 6,
                  alerts: bool = False) -> str:
    """Text run report from a merged cluster view
    (:func:`~.aggregate.merge_cluster`): goodput breakdown, top span
    categories, per-host step-time skew.  ``alerts=True`` adds the
    active/fired SLO alert table (``tools/run_report.py --alerts``)
    next to the goodput ledger."""
    lines: List[str] = []
    hosts = cluster.get("hosts") or []
    gp = cluster.get("goodput") or {}
    wall = float(gp.get("wall_s") or 0.0)
    lines.append("================ bigdl_tpu run report ================")
    lines.append(f"hosts: {len(hosts)} ({', '.join(hosts)})  "
                 f"incarnation: {cluster.get('incarnation', 0)}")
    lines.append(f"wall clock (host-seconds): {wall:.2f}s   "
                 f"goodput: {100.0 * float(gp.get('productive_fraction') or 0.0):.1f}%   "
                 f"accounted: {100.0 * float(gp.get('accounted_fraction') or 0.0):.1f}%")
    lines.append("")
    lines.append("-- goodput ledger ------------------------------------")
    secs: Dict[str, float] = gp.get("seconds") or {}
    for cat, s in sorted(secs.items(), key=lambda kv: -kv[1]):
        frac = s / wall if wall > 0 else 0.0
        lines.append(f"  {cat:<12} {s:>10.2f}s  {100 * frac:>5.1f}%  "
                     f"|{_bar(frac)}|")
    if alerts:
        lines.extend(_render_alerts(cluster.get("alerts")))
    spans: Dict[str, float] = cluster.get("span_totals") or {}
    if spans:
        lines.append("")
        lines.append(f"-- top span categories (of {len(spans)}) "
                     "-----------------------")
        total = sum(spans.values()) or 1.0
        for cat, s in sorted(spans.items(),
                             key=lambda kv: -kv[1])[:top_n]:
            lines.append(f"  {cat:<12} {s:>10.2f}s  "
                         f"{100 * s / total:>5.1f}%")
    tenants = cluster.get("tenants") or {}
    if tenants:
        lines.append("")
        lines.append("-- per-tenant serving --------------------------------")
        for t, rec in sorted(tenants.items()):
            total = int(rec.get("total") or 0)
            ok = int(rec.get("served_ok") or 0)
            shed = int(rec.get("shed_total") or 0)
            reasons = ", ".join(
                f"{r}={n}" for r, n
                in sorted((rec.get("sheds") or {}).items()))
            lines.append(
                f"  {t:<12} {total:>8} req  ok {ok:>8}  "
                f"shed {shed:>6}" + (f"  ({reasons})" if reasons
                                     else ""))
    skew = cluster.get("per_host_skew") or {}
    if skew:
        lines.append("")
        lines.append("-- per-host step-time skew ---------------------------")
        for host, rec in skew.items():
            lines.append(
                f"  {host:<12} mean step "
                f"{1e3 * float(rec.get('mean_step_s') or 0.0):>8.2f}ms"
                f"   {float(rec.get('skew') or 0.0):>5.2f}x median")
    perf = cluster.get("perf")
    if perf:
        lines.extend(_render_perf(perf))
    lines.append("======================================================")
    return "\n".join(lines)


def _render_alerts(alerts) -> List[str]:
    """The SLO alert section (:func:`~.aggregate.merge_alerts`
    output): cluster verdict, the active-alert table, and recent
    firing/resolved transitions in time order."""
    lines: List[str] = [""]
    lines.append("-- slo alerts ----------------------------------------")
    if not alerts:
        lines.append("  no host published an SLO engine snapshot")
        return lines
    totals = alerts.get("totals") or {}
    lines.append(
        f"  verdict: {alerts.get('verdict', 'ok')}   "
        f"active: {len(alerts.get('active') or ())}   "
        f"fired: {totals.get('firing', 0)}   "
        f"resolved: {totals.get('resolved', 0)}")
    active = alerts.get("active") or []
    if active:
        lines.append(f"  {'rule':<32} {'sev':<7} {'host':<10} value")
        for a in active:
            val = a.get("value")
            val_s = (f"{val:.4g}" if isinstance(val, (int, float))
                     else "n/a")
            lines.append(f"  {a.get('rule', '?'):<32} "
                         f"{a.get('severity', '?'):<7} "
                         f"{a.get('host', '?'):<10} {val_s}")
    recent = alerts.get("recent") or []
    if recent:
        lines.append(f"  recent transitions ({len(recent)}):")
        for a in recent[-10:]:
            lines.append(
                f"    [{a.get('state', '?'):<8}] "
                f"{a.get('rule', '?'):<32} {a.get('reason', '')}")
    return lines


def _human_flops(v: float) -> str:
    for unit, scale in (("PF", 1e15), ("TF", 1e12), ("GF", 1e9),
                        ("MF", 1e6)):
        if abs(v) >= scale:
            return f"{v / scale:.2f} {unit}"
    return f"{v:.0f} F"


def _human_bytes(v: float) -> str:
    for unit, scale in (("GiB", 1024 ** 3), ("MiB", 1024 ** 2),
                        ("KiB", 1024)):
        if abs(v) >= scale:
            return f"{v / scale:.2f} {unit}"
    return f"{v:.0f} B"


def _render_perf(perf: dict) -> List[str]:
    """The XLA cost-model section: cluster MFU next to the goodput
    ledger, and the per-program roofline table."""
    lines: List[str] = [""]
    lines.append("-- performance (XLA cost model) ----------------------")
    dev = perf.get("device") or {}
    mfu = perf.get("cluster_mfu")
    head = "  cluster MFU: " + (f"{100 * mfu:.1f}%" if mfu is not None
                                else "n/a")
    head += f"   total flops: {_human_flops(perf.get('flops_total') or 0.0)}"
    if dev.get("peak_flops_per_sec"):
        head += (f"   peak/chip: "
                 f"{dev['peak_flops_per_sec'] / 1e12:.4g} TFLOP/s "
                 f"({dev.get('kind', '?')})")
    if perf.get("nominal_device"):
        head += "   [nominal peak]"
    lines.append(head)
    hbm = perf.get("hbm_peak_bytes")
    if hbm is not None:
        lines.append(f"  hbm peak: {_human_bytes(hbm)}")
    programs = perf.get("programs") or {}
    if programs:
        lines.append(f"  {'program':<24} {'flops/step':>10} "
                     f"{'bytes/step':>10} {'intensity':>9} "
                     f"{'mfu':>6}  bound")
        for label, prog in sorted(programs.items()):
            ai = prog.get("arithmetic_intensity")
            pmfu = prog.get("mfu")
            lines.append(
                f"  {label:<24} "
                f"{_human_flops(prog.get('flops') or 0.0):>10} "
                f"{_human_bytes(prog.get('bytes_accessed') or 0.0):>10} "
                f"{(f'{ai:.1f}' if ai is not None else 'n/a'):>9} "
                f"{(f'{100 * pmfu:.1f}%' if pmfu is not None else 'n/a'):>6}"
                f"  {prog.get('bound', 'unknown')}-bound")
    return lines


def report_from_dir(directory: str, top_n: int = 6) -> str:
    """Render the report for a snapshot directory (one ``<host>.json``
    per host, as written by ``Telemetry.write_snapshot``)."""
    payloads = read_snapshot_dir(directory)
    if not payloads:
        return f"no telemetry snapshots found under {directory!r}"
    return render_report(merge_cluster(payloads), top_n=top_n)
