"""Convolution family (reference SpatialConvolution.scala:42 et al.).

The reference lowers conv to im2col+gemm with per-sample threads
(SpatialConvolution.scala:199-227, NNPrimitive.scala).  On TPU the
entire family is ``lax.conv_general_dilated`` — XLA tiles it straight
onto the MXU, batched, with bias-add fused.  Layout is NCHW to match
the reference's tensors.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .initialization import ONE_D, OUT_IN_KW_KH, RandomUniform
from .module import TensorModule


def _pair(v):
    return v if isinstance(v, tuple) else (v, v)


def _acc_dtype(x):
    """f32 accumulation for f32 operands; None for low-precision operands
    (the TPU MXU still accumulates f32 internally, and a mismatched
    preferred dtype breaks lax conv transpose rules under vjp)."""
    return jnp.float32 if x.dtype == jnp.float32 else None


class SpatialConvolution(TensorModule):
    """2-D conv, NCHW, group support, optional 'same'-ish explicit pads
    (reference nn/SpatialConvolution.scala:42; im2col path replaced by
    one XLA conv op)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int, stride_w: int = 1,
                 stride_h: int = 1, pad_w: int = 0, pad_h: int = 0,
                 n_group: int = 1, propagate_back: bool = True,
                 w_regularizer=None, b_regularizer=None,
                 with_bias: bool = True):
        super().__init__()
        assert n_input_plane % n_group == 0
        assert n_output_plane % n_group == 0
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.n_group = n_group
        self.propagate_back = propagate_back
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        # conv lowering: None → the bigdl.conv.impl property ("xla"
        # default).  "gemm" = k²-matmul decomposition (ops/conv_gemm) —
        # the MXU-shaped alternative to XLA's native conv lowering.
        self.conv_impl = None
        self.reset()

    def set_conv_impl(self, impl: str):
        self.conv_impl = impl
        return self

    def reset(self):
        shape = (self.n_output_plane, self.n_input_plane // self.n_group,
                 self.kernel_h, self.kernel_w)
        w_init = self._init_methods.get("weight", (RandomUniform(), None))[0]
        self._register_param("weight", w_init.init(shape, OUT_IN_KW_KH))
        if self.with_bias:
            b_init = self._init_methods.get("bias", (RandomUniform(), None))[0]
            self._register_param("bias",
                                 b_init.init((self.n_output_plane,), ONE_D))
        return self

    def _conv(self, x, w):
        # pad_w/pad_h = -1 means 'same' (reference uses -1 for same pad)
        if self.pad_w == -1 or self.pad_h == -1:
            padding = "SAME"
        else:
            padding = [(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)]
        # getattr: checkpoints pickled before this attribute existed
        # restore via __setstate__ without running __init__
        impl = getattr(self, "conv_impl", None)
        if impl is None:
            from ..utils.engine import get_property
            impl = get_property("bigdl.conv.impl", "xla")
        if impl == "gemm" and self.n_group == 1:
            from ..ops.conv_gemm import conv2d_gemm_nchw
            return conv2d_gemm_nchw(
                x, w, stride=(self.stride_h, self.stride_w),
                padding=padding if padding == "SAME"
                else (self.pad_h, self.pad_w))
        if impl == "xla_nhwc" and self.n_group == 1:
            # the layout experiment: same XLA conv, activations flowing
            # NHWC between boundary transposes.  The independent twin
            # (NHWC end-to-end) measured ~14% faster than the NCHW
            # framework on-chip — if XLA cancels the adjacent transpose
            # pairs between layers, this knob recovers the layout share
            # of that gap without changing the module API.
            xs = jnp.transpose(x, (0, 2, 3, 1))
            ws = jnp.transpose(w, (2, 3, 1, 0))  # OIHW -> HWIO
            y = lax.conv_general_dilated(
                xs, ws,
                window_strides=(self.stride_h, self.stride_w),
                padding=padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=_acc_dtype(x))
            return jnp.transpose(y, (0, 3, 1, 2))
        if (impl == "pallas" and self.n_group == 1
                and (self.kernel_w, self.kernel_h) == (3, 3)
                and (self.stride_w, self.stride_h) == (1, 1)
                and (self.pad_w, self.pad_h) == (1, 1)):
            # the hand kernel covers the ResNet workhorse shape; other
            # shapes keep the native lowering
            from ..ops.conv3x3_pallas import conv3x3_s1_same
            y = conv3x3_s1_same(jnp.transpose(x, (0, 2, 3, 1)),
                                jnp.transpose(w, (2, 3, 1, 0)))
            return jnp.transpose(y, (0, 3, 1, 2))
        return lax.conv_general_dilated(
            x, w,
            window_strides=(self.stride_h, self.stride_w),
            padding=padding,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=self.n_group,
            preferred_element_type=_acc_dtype(x))

    def _apply(self, params, buffers, x, training, rng):
        squeeze = False
        if x.ndim == 3:  # no-batch mode
            x = x[None]
            squeeze = True
        # mixed precision: compute in the weight dtype (bf16 weights →
        # bf16 MXU inputs), accumulate f32, emit the weight dtype
        w = params["weight"]
        y = self._conv(x.astype(w.dtype), w).astype(w.dtype)
        if self.with_bias:
            y = y + params["bias"][None, :, None, None]
        if squeeze:
            y = y[0]
        return y, buffers


class SpatialShareConvolution(SpatialConvolution):
    """reference nn/SpatialShareConvolution.scala — im2col-buffer sharing
    variant; under XLA there is no buffer to share, semantics identical."""


class SpatialDilatedConvolution(SpatialConvolution):
    """reference nn/SpatialDilatedConvolution.scala"""

    def __init__(self, n_input_plane, n_output_plane, kw, kh, dw=1, dh=1,
                 pad_w=0, pad_h=0, dilation_w=1, dilation_h=1,
                 w_regularizer=None, b_regularizer=None):
        self.dilation_w, self.dilation_h = dilation_w, dilation_h
        super().__init__(n_input_plane, n_output_plane, kw, kh, dw, dh,
                         pad_w, pad_h, 1, True, w_regularizer, b_regularizer)

    def _conv(self, x, w):
        padding = [(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)]
        return lax.conv_general_dilated(
            x, w,
            window_strides=(self.stride_h, self.stride_w),
            padding=padding,
            rhs_dilation=(self.dilation_h, self.dilation_w),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            preferred_element_type=_acc_dtype(x))


class SpatialFullConvolution(TensorModule):
    """Transposed conv / deconv (reference nn/SpatialFullConvolution.scala),
    with output adjustment adj_w/adj_h."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0, adj_w: int = 0,
                 adj_h: int = 0, n_group: int = 1, no_bias: bool = False,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kw, self.kh, self.dw, self.dh = kw, kh, dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.adj_w, self.adj_h = adj_w, adj_h
        self.n_group = n_group
        self.with_bias = not no_bias
        self.reset()

    def reset(self):
        # reference layout: (in, out/group, kh, kw)
        shape = (self.n_input_plane, self.n_output_plane // self.n_group,
                 self.kh, self.kw)
        w_init = self._init_methods.get("weight", (RandomUniform(), None))[0]
        self._register_param("weight", w_init.init(shape, OUT_IN_KW_KH))
        if getattr(self, "with_bias", True):
            b_init = self._init_methods.get("bias", (RandomUniform(), None))[0]
            self._register_param("bias",
                                 b_init.init((self.n_output_plane,), ONE_D))
        return self

    def _apply(self, params, buffers, x, training, rng):
        squeeze = False
        if x.ndim == 3:
            x = x[None]
            squeeze = True
        w = params["weight"]  # (I, O/g, kh, kw)
        x = x.astype(w.dtype)  # mixed precision: compute in weight dtype
        # Gradient-of-conv formulation: lhs-dilate input by stride.
        pad_h = self.kh - 1 - self.pad_h
        pad_w = self.kw - 1 - self.pad_w
        w_flip = jnp.flip(w, axis=(-1, -2))
        # to OIHW with O=n_output, I=n_input/g : transpose first two dims
        if self.n_group > 1:
            wg = w_flip.reshape(self.n_group, self.n_input_plane // self.n_group,
                                self.n_output_plane // self.n_group, self.kh, self.kw)
            wg = jnp.swapaxes(wg, 1, 2)
            rhs = wg.reshape(self.n_output_plane,
                             self.n_input_plane // self.n_group, self.kh, self.kw)
        else:
            rhs = jnp.swapaxes(w_flip, 0, 1)
        y = lax.conv_general_dilated(
            x, rhs, window_strides=(1, 1),
            padding=[(pad_h, pad_h + self.adj_h), (pad_w, pad_w + self.adj_w)],
            lhs_dilation=(self.dh, self.dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=self.n_group,
            preferred_element_type=_acc_dtype(x))
        if self.with_bias:
            y = y + params["bias"][None, :, None, None]
        if squeeze:
            y = y[0]
        return y, buffers


class SpatialConvolutionMap(TensorModule):
    """Conv with an explicit input→output connection table
    (reference nn/SpatialConvolutionMap.scala).  Implemented as a dense
    conv with a fixed binary mask on the weight."""

    def __init__(self, conn_table, kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        conn = np.asarray(conn_table, dtype=np.int32)  # rows of (in, out), 1-based
        self.conn = conn
        self.n_input_plane = int(conn[:, 0].max())
        self.n_output_plane = int(conn[:, 1].max())
        self.kw, self.kh, self.dw, self.dh = kw, kh, dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        mask = np.zeros((self.n_output_plane, self.n_input_plane, 1, 1), np.float32)
        for i, o in conn:
            mask[o - 1, i - 1, 0, 0] = 1.0
        self._mask = jnp.asarray(mask)
        self.reset()

    def reset(self):
        n_in_per_out = max(1, len(self.conn) // max(self.n_output_plane, 1))
        stdv = 1.0 / math.sqrt(self.kw * self.kh * n_in_per_out)
        init = RandomUniform(-stdv, stdv)
        self._register_param("weight", init.init(
            (self.n_output_plane, self.n_input_plane, self.kh, self.kw)))
        self._register_param("bias", init.init((self.n_output_plane,)))
        return self

    def _apply(self, params, buffers, x, training, rng):
        squeeze = False
        if x.ndim == 3:
            x = x[None]
            squeeze = True
        w = params["weight"] * self._mask.astype(params["weight"].dtype)
        x = x.astype(w.dtype)  # mixed precision: compute in weight dtype
        y = lax.conv_general_dilated(
            x, w, (self.dh, self.dw),
            [(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            preferred_element_type=_acc_dtype(x))
        y = y + params["bias"][None, :, None, None]
        if squeeze:
            y = y[0]
        return y, buffers


class VolumetricConvolution(TensorModule):
    """3-D conv, NCDHW (reference nn/VolumetricConvolution.scala)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int, d_t: int = 1, d_w: int = 1,
                 d_h: int = 1, pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 with_bias: bool = True):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.k = (k_t, k_h, k_w)
        self.d = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.with_bias = with_bias
        self.reset()

    def reset(self):
        shape = (self.n_output_plane, self.n_input_plane) + self.k
        w_init = self._init_methods.get("weight", (RandomUniform(), None))[0]
        self._register_param("weight", w_init.init(shape, OUT_IN_KW_KH))
        if self.with_bias:
            b_init = self._init_methods.get("bias", (RandomUniform(), None))[0]
            self._register_param("bias",
                                 b_init.init((self.n_output_plane,), ONE_D))
        return self

    def _apply(self, params, buffers, x, training, rng):
        squeeze = False
        if x.ndim == 4:
            x = x[None]
            squeeze = True
        x = x.astype(params["weight"].dtype)  # mixed precision
        y = lax.conv_general_dilated(
            x, params["weight"], self.d,
            [(p, p) for p in self.pad],
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
            preferred_element_type=_acc_dtype(x))
        if self.with_bias:
            y = y + params["bias"][None, :, None, None, None]
        if squeeze:
            y = y[0]
        return y, buffers


class TemporalConvolution(TensorModule):
    """1-D conv over (batch, nInputFrame, inputFrameSize)
    (reference nn/TemporalConvolution.scala)."""

    def __init__(self, input_frame_size: int, output_frame_size: int,
                 kernel_w: int, stride_w: int = 1):
        super().__init__()
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w, self.stride_w = kernel_w, stride_w
        self.reset()

    def reset(self):
        stdv = 1.0 / math.sqrt(self.kernel_w * self.input_frame_size)
        init = self._init_methods.get("weight", (RandomUniform(-stdv, stdv), None))[0]
        self._register_param("weight", init.init(
            (self.output_frame_size, self.input_frame_size, self.kernel_w)))
        b_init = self._init_methods.get("bias", (RandomUniform(-stdv, stdv), None))[0]
        self._register_param("bias", b_init.init((self.output_frame_size,)))
        return self

    def _apply(self, params, buffers, x, training, rng):
        squeeze = False
        if x.ndim == 2:
            x = x[None]
            squeeze = True
        # (N, T, C) -> (N, C, T)
        xc = jnp.swapaxes(x, 1, 2).astype(params["weight"].dtype)
        y = lax.conv_general_dilated(
            xc, params["weight"], (self.stride_w,), [(0, 0)],
            dimension_numbers=("NCH", "OIH", "NCH"),
            preferred_element_type=_acc_dtype(xc))
        y = jnp.swapaxes(y, 1, 2) + params["bias"]
        if squeeze:
            y = y[0]
        return y, buffers
