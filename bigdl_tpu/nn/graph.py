"""Graph container (reference nn/Graph.scala:58, utils/DirectedGraph.scala:34).

``Graph`` topo-sorts its DAG once at construction (Graph.scala:180-198)
and replays the sorted node list inside one pure ``apply_fn`` — so an
arbitrary DAG still traces into a single XLA program and backward is the
vjp of the whole graph (no per-node backward scheduling like the
reference's Graph.backward, Graph.scala:64-120).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax

from ..utils.table import Table
from .containers import Identity
from .module import AbstractModule, Container


class ModuleNode:
    """DAG node wrapping a module (reference ``Node[AbstractModule]``)."""

    _counter = [0]

    def __init__(self, element: AbstractModule):
        self.element = element
        self.prev_nodes: List["ModuleNode"] = []
        self.next_nodes: List["ModuleNode"] = []
        ModuleNode._counter[0] += 1
        self.uid = ModuleNode._counter[0]

    def add_edge(self, to: "ModuleNode"):
        self.next_nodes.append(to)
        to.prev_nodes.append(self)
        return self

    def inputs(self, *nodes):
        for n in nodes:
            n.add_edge(self)
        return self

    def __repr__(self):
        return f"Node({self.element.get_name()})"


def Input():
    """Placeholder source node (reference nn/Graph.scala Input)."""
    return ModuleNode(Identity())


def topo_sort(outputs: Sequence[ModuleNode]) -> List[ModuleNode]:
    """DFS post-order topological sort (reference DirectedGraph.topologySort:52)."""
    visited, order, stack = set(), [], []

    def visit(node):
        if node.uid in visited:
            return
        visited.add(node.uid)
        for p in node.prev_nodes:
            visit(p)
        order.append(node)

    for out in outputs:
        visit(out)
    return order


class Graph(Container):
    """DAG of modules with explicit input/output nodes (reference nn/Graph.scala:58).

    Multi-input graphs take a Table input (1-based, matching the order of
    ``inputs``); multi-output graphs return a Table.
    """

    def __init__(self, inputs, outputs):
        if isinstance(inputs, ModuleNode):
            inputs = [inputs]
        if isinstance(outputs, ModuleNode):
            outputs = [outputs]
        self.input_nodes = list(inputs)
        self.output_nodes = list(outputs)
        self.sorted_nodes = topo_sort(self.output_nodes)
        # sanity: every input reachable
        sorted_ids = {n.uid for n in self.sorted_nodes}
        for i in self.input_nodes:
            if i.uid not in sorted_ids:
                raise ValueError("graph input not connected to any output")
        super().__init__(*[n.element for n in self.sorted_nodes])

    def apply_fn(self, params, buffers, inp, training=True, rng=None):
        from .containers import _split_rng

        activities: Dict[int, object] = {}
        n_in = len(self.input_nodes)
        if n_in == 1:
            activities[self.input_nodes[0].uid] = inp
        else:
            for i, node in enumerate(self.input_nodes):
                activities[node.uid] = inp[i + 1]
        rngs = _split_rng(rng, max(len(self.sorted_nodes), 1))
        new_buffers = {}
        for i, node in enumerate(self.sorted_nodes):
            if node.uid in activities:  # input node
                x = activities[node.uid]
            elif len(node.prev_nodes) == 1:
                x = activities[node.prev_nodes[0].uid]
            else:
                x = Table(*[activities[p.uid] for p in node.prev_nodes])
            out, nb = node.element.apply_fn(params[str(i)], buffers[str(i)],
                                            x, training, rngs[i])
            activities[node.uid] = out
            new_buffers[str(i)] = nb
        if len(self.output_nodes) == 1:
            return activities[self.output_nodes[0].uid], new_buffers
        return (Table(*[activities[o.uid] for o in self.output_nodes]),
                new_buffers)


def Model(inputs, outputs) -> Graph:
    """pyspark-parity factory (pyspark/bigdl/nn/layer.py Model)."""
    return Graph(inputs, outputs)
