"""Sharded embedding tables — the recommendation-workload layer.

The BigDL lineage served embedding-heavy recommendation models in
production; their tables are the one parameter class that does not fit
the replicate-everything default: multi-GB row counts (must shard) and
Zipf-skewed access (a batch touches a vanishing fraction of rows, so
dense gradient all-reduce wastes nearly all collective bytes —
Parallax, arxiv 1808.02621).  :class:`ShardedEmbedding` covers both
sides:

* **rows partitioned over a mesh axis** (``axis_name``, usually
  ``"data"`` — the expert-parallel pattern from ``parallel.moe``): the
  module stores the FULL ``[V, D]`` table host-side, the sharding plan
  (``parallel.plan.derive_plan`` via ``spmd.param_specs``) shards the
  leading row dim at trace time, and the lookup becomes an index
  exchange under ``shard_map`` — every shard ``all_gather``s the gang's
  flat indices, gathers the rows it owns, and a ``psum_scatter`` routes
  each requester exactly its rows back.  The wire carries per-lookup
  index+value bytes both ways (the backward rides the exchange's AD
  transpose — row gradients return to their owners pre-summed), never
  the dense table.  Optimizer slots shard with their rows
  (``spmd.slot_specs`` inherits the param specs).

* **sparse gradient transport when replicated** (``sparse_grads =
  True``): a table small enough to replicate still has >99%-zero-row
  gradients under skewed batches; the derived plan stamps its rule
  ``transport="sparse"`` so the compiled step ships
  ``(row_indices, row_values)`` over the data axis instead of the dense
  all-reduce (``parallel.plan`` "Gradient transport").

Unbound (``axis_name=None``) or on a single-device mesh the layer is a
plain gather — the same function, computed locally.  Index convention
follows :class:`~bigdl_tpu.nn.linear.LookupTable`: 1-based floats,
``padding_value`` rows zeroed.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from .initialization import ONE_D, RandomNormal
from .module import TensorModule


class ShardedEmbedding(TensorModule):
    """Embedding whose rows (and their optimizer slots) partition over
    a mesh axis, with sparse gradient transport when replicated.

    ``n_index`` rows x ``n_output`` columns; ``axis_name`` names the
    mesh axis sharding the rows (``None`` = replicated table, sparse
    gradient wire).  When bound, ``n_index`` should divide the axis
    size — a non-dividing mesh (e.g. after an elastic shrink to an odd
    survivor count) degrades to a full replica with a warning from the
    plan, never dropping rows.

    ``staleness`` opts THIS table into bounded-staleness sparse
    updates when it replicates (``derive_plan`` stamps its rule
    ``sync="stale(s)"``, overriding the global ``bigdl.sync.staleness``
    knob): lookups proceed against the local replica while the
    index+row exchange is in flight, peers' updates applying up to
    ``s`` steps late — Parallax's hybrid, per table (docs/
    distributed.md "Synchrony").  Row-SHARDED tables ignore it (each
    row has exactly one copy; the lookup exchange is the forward).
    """

    #: derive_plan stamps this module's rules ``transport="sparse"``
    sparse_grads = True

    #: host-memory backing (``attach_store``) — None = device-resident
    _store = None

    def __init__(self, n_index: int, n_output: int,
                 axis_name: Optional[str] = "data",
                 padding_value: float = 0,
                 staleness: Optional[int] = None):
        super().__init__()
        if n_index < 1 or n_output < 1:
            raise ValueError(
                f"ShardedEmbedding needs positive table dims, got "
                f"({n_index}, {n_output})")
        self.n_index, self.n_output = int(n_index), int(n_output)
        self.axis_name = axis_name
        self.padding_value = padding_value
        # per-module staleness bound (derive_plan's _sparse_param_info
        # reads it); None = follow the bigdl.sync.* knobs
        self.sync_staleness = int(staleness) if staleness else None
        self.reset()

    def reset(self):
        w_init = self._init_methods.get(
            "weight", (RandomNormal(0, 1.0 / max(self.n_output, 1) ** 0.5),
                       None))[0]
        self._register_param(
            "weight", w_init.init((self.n_index, self.n_output), ONE_D))
        return self

    # -- host-memory backing (the parameter-server hybrid) --------------
    def attach_store(self, store) -> "ShardedEmbedding":
        """Back this table with a host-memory
        :class:`~bigdl_tpu.nn.embedding_store.EmbeddingStore` leg.
        The store owns durability and row re-partitioning (sealed-shard
        migration, checkpointed legs, version-retired hot-row cache);
        the module's device-resident ``weight`` becomes a working copy
        refreshed from / flushed to the store at step boundaries —
        tables that dwarf HBM skip the dense copy entirely and serve
        through :class:`~bigdl_tpu.serving.sparse_fetch
        .SparseFetchClient` instead."""
        if (store.n_rows, store.dim) != (self.n_index, self.n_output):
            raise ValueError(
                f"store {store.table!r} is {store.n_rows}x{store.dim}, "
                f"table wants {self.n_index}x{self.n_output}")
        self._store = store
        return self

    def refresh_from_store(self):
        """store → device: re-register ``weight`` from the live table
        (dense materialization — only for tables that fit HBM)."""
        if self._store is None:
            raise ValueError("no store attached (attach_store first)")
        self._register_param("weight",
                             jnp.asarray(self._store.dense()))
        return self

    def flush_to_store(self, rows, grads, lr: float = 1.0):
        """device → store: push one step's sparse row updates
        (``-lr * grads[i]`` into ``rows[i]``) to the rows' OWNING leg —
        the PS-style write the Parallax hybrid pairs with dense
        all-reduce MLPs.  Rows this leg does not own are the caller's
        to route (the store's consistent assignment says where)."""
        import numpy as np

        if self._store is None:
            raise ValueError("no store attached (attach_store first)")
        rows = [int(r) for r in np.asarray(rows).reshape(-1)]
        g = np.asarray(grads, dtype=self._store.dtype)
        g = g.reshape(len(rows), self.n_output)
        mine = [i for i, r in enumerate(rows)
                if self._store.owns_row(r)]
        if mine:
            self._store.apply_updates(
                [rows[i] for i in mine], -float(lr) * g[mine])
        return len(mine)

    def _n_shards(self) -> int:
        """Bound-axis size, or 1 when eager/unbound (the MoEFFN /
        RowParallelLinear detection pattern)."""
        if self.axis_name is None:
            return 1
        try:
            return lax.psum(1, self.axis_name)
        except NameError:
            return 1

    def _apply(self, params, buffers, x, training, rng):
        w = params["weight"]
        idx0 = jnp.clip(x.astype(jnp.int32) - 1, 0, self.n_index - 1)
        n = self._n_shards()
        if n == 1 or w.shape[0] == self.n_index:
            # unbound, single shard, or a plan that degraded the table
            # to a replica (non-dividing mesh): local gather
            out = jnp.take(w, idx0, axis=0)
        else:
            rows = w.shape[0]  # V / n local rows under shard_map
            shape = idx0.shape
            flat = idx0.reshape(-1)
            me = lax.axis_index(self.axis_name)
            # index exchange: every shard sees the gang's lookups...
            all_idx = lax.all_gather(flat, self.axis_name, tiled=True)
            rel = all_idx - me * rows
            mine = (rel >= 0) & (rel < rows)
            contrib = jnp.where(
                mine[:, None],
                jnp.take(w, jnp.clip(rel, 0, rows - 1), axis=0),
                jnp.zeros((), w.dtype))
            # ...and a psum_scatter routes each requester its rows
            # (exactly one owner contributes per lookup).  The AD
            # transpose of this pair returns row gradients to their
            # owners pre-summed — per-lookup index+value wire, never
            # the dense table.
            out = lax.psum_scatter(
                contrib.reshape(n, -1, w.shape[1]),
                self.axis_name, scatter_dimension=0, tiled=False)
            out = out.reshape(shape + (w.shape[1],))
        if self.padding_value != 0:
            mask = (x.astype(jnp.int32) == int(self.padding_value))
            out = jnp.where(mask[..., None], 0.0, out)
        return out, buffers
