"""Activation layers (~29, reference nn/ — SURVEY §2.4 'Activations').

All pure elementwise maps: XLA fuses each into its producer, so unlike
the reference (separate MKL VML calls per op, TensorNumeric.scala:239-334)
these cost zero extra HBM round-trips inside a jitted step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .initialization import ConstInitMethod
from .module import TensorModule


class ReLU(TensorModule):
    """reference nn/ReLU.scala (ip = in-place is meaningless under XLA)"""

    def __init__(self, ip: bool = False):
        super().__init__()

    def _apply(self, params, buffers, x, training, rng):
        return jax.nn.relu(x), buffers


class ReLU6(TensorModule):
    def _apply(self, params, buffers, x, training, rng):
        return jnp.clip(x, 0.0, 6.0), buffers


class LeakyReLU(TensorModule):
    def __init__(self, negval: float = 0.01, inplace: bool = False):
        super().__init__()
        self.negval = negval

    def _apply(self, params, buffers, x, training, rng):
        return jnp.where(x > 0, x, self.negval * x), buffers


class PReLU(TensorModule):
    """Learned negative slope (reference nn/PReLU.scala); n_output_plane=0
    → one shared scalar."""

    def __init__(self, n_output_plane: int = 0):
        super().__init__()
        self.n_output_plane = n_output_plane
        self.reset()

    def reset(self):
        shape = (max(self.n_output_plane, 1),)
        init = self._init_methods.get("weight", (ConstInitMethod(0.25), None))[0]
        self._register_param("weight", init.init(shape))
        return self

    def _apply(self, params, buffers, x, training, rng):
        w = params["weight"]
        if self.n_output_plane > 0:
            # reference PReLU.scala:86 — channel dim (1-based) is
            # (nDim+1)%2+1: axis 1 for batched even-rank (NC, NCHW),
            # axis 0 for unbatched odd-rank (C, CHW)
            ch_axis = (x.ndim + 1) % 2
            shape = [1] * x.ndim
            shape[ch_axis] = self.n_output_plane
            w = w.reshape(shape)
        return jnp.where(x > 0, x, w * x), buffers


class RReLU(TensorModule):
    """Randomized leaky ReLU (reference nn/RReLU.scala): train = slope ~
    U(lower, upper) per element; eval = fixed (lower+upper)/2."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 inplace: bool = False):
        super().__init__()
        self.lower, self.upper = lower, upper

    def _apply(self, params, buffers, x, training, rng):
        if training and rng is not None:
            a = jax.random.uniform(rng, x.shape, x.dtype, self.lower, self.upper)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.where(x >= 0, x, a * x), buffers


class ELU(TensorModule):
    def __init__(self, alpha: float = 1.0, inplace: bool = False):
        super().__init__()
        self.alpha = alpha

    def _apply(self, params, buffers, x, training, rng):
        return jnp.where(x > 0, x, self.alpha * jnp.expm1(x)), buffers


class Tanh(TensorModule):
    def _apply(self, params, buffers, x, training, rng):
        return jnp.tanh(x), buffers


class Sigmoid(TensorModule):
    def _apply(self, params, buffers, x, training, rng):
        return jax.nn.sigmoid(x), buffers


class LogSigmoid(TensorModule):
    def _apply(self, params, buffers, x, training, rng):
        return jax.nn.log_sigmoid(x), buffers


class LogSoftMax(TensorModule):
    """reference nn/LogSoftMax.scala — over last dim for 1-D/2-D input"""

    def _apply(self, params, buffers, x, training, rng):
        return jax.nn.log_softmax(x, axis=-1), buffers


class SoftMax(TensorModule):
    def _apply(self, params, buffers, x, training, rng):
        axis = 1 if x.ndim in (2, 4) else 0 if x.ndim in (1, 3) else -1
        return jax.nn.softmax(x, axis=axis), buffers


class SoftMin(TensorModule):
    def _apply(self, params, buffers, x, training, rng):
        axis = 1 if x.ndim in (2, 4) else 0 if x.ndim in (1, 3) else -1
        return jax.nn.softmax(-x, axis=axis), buffers


class SoftPlus(TensorModule):
    def __init__(self, beta: float = 1.0):
        super().__init__()
        self.beta = beta

    def _apply(self, params, buffers, x, training, rng):
        # threshold at 20 like torch for numerical stability
        bx = self.beta * x
        return jnp.where(bx > 20.0, x, jnp.log1p(jnp.exp(bx)) / self.beta), buffers


class SoftSign(TensorModule):
    def _apply(self, params, buffers, x, training, rng):
        return x / (1.0 + jnp.abs(x)), buffers


class HardTanh(TensorModule):
    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 inplace: bool = False):
        super().__init__()
        assert max_value > min_value
        self.min_value, self.max_value = min_value, max_value

    def _apply(self, params, buffers, x, training, rng):
        return jnp.clip(x, self.min_value, self.max_value), buffers


class HardShrink(TensorModule):
    def __init__(self, lambd: float = 0.5):
        super().__init__()
        self.lambd = lambd

    def _apply(self, params, buffers, x, training, rng):
        return jnp.where(jnp.abs(x) > self.lambd, x, 0.0), buffers


class SoftShrink(TensorModule):
    def __init__(self, lambd: float = 0.5):
        super().__init__()
        self.lambd = lambd

    def _apply(self, params, buffers, x, training, rng):
        return jnp.where(x > self.lambd, x - self.lambd,
                         jnp.where(x < -self.lambd, x + self.lambd, 0.0)), buffers


class TanhShrink(TensorModule):
    def _apply(self, params, buffers, x, training, rng):
        return x - jnp.tanh(x), buffers


class Threshold(TensorModule):
    def __init__(self, th: float = 1e-6, v: float = 0.0, ip: bool = False):
        super().__init__()
        self.th, self.v = th, v

    def _apply(self, params, buffers, x, training, rng):
        return jnp.where(x > self.th, x, self.v), buffers


class Clamp(HardTanh):
    def __init__(self, min_value: float, max_value: float):
        super().__init__(float(min_value), float(max_value))


class Abs(TensorModule):
    def _apply(self, params, buffers, x, training, rng):
        return jnp.abs(x), buffers


class Power(TensorModule):
    """(shift + scale*x)^power (reference nn/Power.scala)"""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0):
        super().__init__()
        self.power, self.scale, self.shift = power, scale, shift

    def _apply(self, params, buffers, x, training, rng):
        return jnp.power(self.shift + self.scale * x, self.power), buffers


class Square(TensorModule):
    def _apply(self, params, buffers, x, training, rng):
        return jnp.square(x), buffers


class Sqrt(TensorModule):
    def _apply(self, params, buffers, x, training, rng):
        return jnp.sqrt(x), buffers


class Log(TensorModule):
    def _apply(self, params, buffers, x, training, rng):
        return jnp.log(x), buffers


class Exp(TensorModule):
    def _apply(self, params, buffers, x, training, rng):
        return jnp.exp(x), buffers


class Mean(TensorModule):
    """Mean over a (1-based) dim (reference nn/Mean.scala)."""

    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 squeeze: bool = True):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims
        self.squeeze = squeeze

    def _axis(self, x):
        d = self.dimension - 1
        if self.n_input_dims > 0 and x.ndim > self.n_input_dims:
            d += 1  # batch mode
        return d

    def _apply(self, params, buffers, x, training, rng):
        return jnp.mean(x, axis=self._axis(x), keepdims=not self.squeeze), buffers


class Sum(TensorModule):
    """Sum over a (1-based) dim (reference nn/Sum.scala:44): negative
    dims count from the end, ``n_input_dims`` marks batch mode (one
    extra leading dim shifts the axis), ``size_average`` divides by the
    reduced extent, ``squeeze`` drops the reduced dim."""

    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 size_average: bool = False, squeeze: bool = True):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims
        self.size_average = size_average
        self.squeeze = squeeze

    def _axis(self, x):
        # the reference resolves a negative dim and THEN applies the
        # batch shift (two sequential ifs, Sum.scala getPositiveDimension)
        # — the combination can run past the rank, and then it raises
        # there too (its require(input.dim() >= dimension))
        d = self.dimension
        if d < 0:
            d = x.ndim + d + 1
        if self.n_input_dims > 0 and x.ndim == self.n_input_dims + 1:
            d += 1
        if not 1 <= d <= x.ndim:
            raise ValueError(
                f"Sum dimension {self.dimension} exceeds input rank {x.ndim}")
        return d - 1

    def _apply(self, params, buffers, x, training, rng):
        axis = self._axis(x)
        y = jnp.sum(x, axis=axis, keepdims=not (self.squeeze and x.ndim > 1))
        if self.size_average:
            y = y / x.shape[axis]
        return y, buffers


class Max(TensorModule):
    def __init__(self, dim: int = 1, num_input_dims: int = -1):
        super().__init__()
        self.dim, self.num_input_dims = dim, num_input_dims

    def _apply(self, params, buffers, x, training, rng):
        d = self.dim - 1
        if self.num_input_dims > 0 and x.ndim > self.num_input_dims:
            d += 1
        return jnp.max(x, axis=d), buffers


class Min(TensorModule):
    def __init__(self, dim: int = 1, num_input_dims: int = -1):
        super().__init__()
        self.dim, self.num_input_dims = dim, num_input_dims

    def _apply(self, params, buffers, x, training, rng):
        d = self.dim - 1
        if self.num_input_dims > 0 and x.ndim > self.num_input_dims:
            d += 1
        return jnp.min(x, axis=d), buffers
