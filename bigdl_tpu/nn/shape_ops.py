"""Shape/structure layers (SURVEY §2.4 'Shape/structure ops').

All are zero-FLOP layout ops — under XLA they compile to metadata
changes or cheap gathers; none of the reference's copy loops survive.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.table import Table
from .module import AbstractModule, TensorModule


class Reshape(TensorModule):
    """reference nn/Reshape.scala — ``batch_mode`` None = auto-detect
    (leading dim preserved when it looks like a batch)."""

    def __init__(self, size: Sequence[int], batch_mode: Optional[bool] = None):
        super().__init__()
        self.size = tuple(size)
        self.batch_mode = batch_mode
        self.n_element = int(np.prod(self.size))

    def _apply(self, params, buffers, x, training, rng):
        # reference Reshape.scala:53-66 — no-batch iff batchMode=Some(false),
        # or unset with an exact element match and a non-1 leading dim
        total = int(np.prod(x.shape))
        if self.batch_mode is False or (
                self.batch_mode is None and total == self.n_element
                and x.shape[0] != 1):
            return x.reshape(self.size), buffers
        return x.reshape((x.shape[0],) + self.size), buffers


class View(TensorModule):
    """reference nn/View.scala — -1 wildcard supported; num_input_dims
    enables batch handling."""

    def __init__(self, *sizes):
        super().__init__()
        if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
            sizes = tuple(sizes[0])
        self.sizes = tuple(sizes)
        self.num_input_dims = 0

    def set_num_input_dims(self, n):
        self.num_input_dims = n
        return self

    def _apply(self, params, buffers, x, training, rng):
        known = int(np.prod([s for s in self.sizes if s != -1]))
        total = int(np.prod(x.shape))
        if -1 in self.sizes or total == known:
            return x.reshape(self.sizes if -1 in self.sizes
                             else ((-1,) + self.sizes if total != known else self.sizes)), buffers
        return x.reshape((-1,) + self.sizes), buffers


class InferReshape(TensorModule):
    """reference nn/InferReshape.scala — 0 keeps the input dim, -1 infers."""

    def __init__(self, size: Sequence[int], batch_mode: bool = False):
        super().__init__()
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def _apply(self, params, buffers, x, training, rng):
        in_shape = x.shape[1:] if self.batch_mode else x.shape
        out = []
        for i, s in enumerate(self.size):
            out.append(in_shape[i] if s == 0 else s)
        if self.batch_mode:
            return x.reshape((x.shape[0],) + tuple(out)), buffers
        return x.reshape(tuple(out)), buffers


class Transpose(TensorModule):
    """Sequence of (1-based) dim swaps (reference nn/Transpose.scala)."""

    def __init__(self, permutations: Sequence):
        super().__init__()
        self.permutations = [tuple(p) for p in permutations]

    def _apply(self, params, buffers, x, training, rng):
        perm = list(range(x.ndim))
        for d1, d2 in self.permutations:
            perm[d1 - 1], perm[d2 - 1] = perm[d2 - 1], perm[d1 - 1]
        return jnp.transpose(x, perm), buffers


class Replicate(TensorModule):
    """Insert + tile a new dim (reference nn/Replicate.scala)."""

    def __init__(self, n_features: int, dim: int = 1, n_dim: int = np.iinfo(np.int32).max):
        super().__init__()
        self.n_features, self.dim, self.n_dim = n_features, dim, n_dim

    def _apply(self, params, buffers, x, training, rng):
        d = self.dim - 1
        if x.ndim > self.n_dim:
            d += 1  # batch mode
        y = jnp.expand_dims(x, d)
        reps = [1] * y.ndim
        reps[d] = self.n_features
        return jnp.tile(y, reps), buffers


class Squeeze(TensorModule):
    def __init__(self, dim: Optional[int] = None, num_input_dims: int = -1):
        super().__init__()
        self.dim, self.num_input_dims = dim, num_input_dims

    def _apply(self, params, buffers, x, training, rng):
        if self.dim is None:
            return jnp.squeeze(x), buffers
        d = self.dim - 1
        if self.num_input_dims > 0 and x.ndim > self.num_input_dims:
            d += 1
        return jnp.squeeze(x, axis=d) if x.shape[d] == 1 else x, buffers


class Unsqueeze(TensorModule):
    def __init__(self, pos: int, num_input_dims: int = -1):
        super().__init__()
        self.pos, self.num_input_dims = pos, num_input_dims

    def _apply(self, params, buffers, x, training, rng):
        d = self.pos - 1
        if self.num_input_dims > 0 and x.ndim > self.num_input_dims:
            d += 1
        return jnp.expand_dims(x, d), buffers


class Select(TensorModule):
    """1-based select along dim; negative counts from the end
    (reference nn/Select.scala)."""

    def __init__(self, dimension: int, index: int):
        super().__init__()
        self.dimension, self.index = dimension, index

    def _apply(self, params, buffers, x, training, rng):
        d = self.dimension - 1 if self.dimension > 0 else x.ndim + self.dimension
        i = self.index - 1 if self.index > 0 else x.shape[d] + self.index
        return jnp.take(x, i, axis=d), buffers


class Narrow(TensorModule):
    """1-based narrow (reference nn/Narrow.scala); negative length keeps
    all but |length|-1 trailing entries."""

    def __init__(self, dimension: int, offset: int, length: int = 1):
        super().__init__()
        self.dimension, self.offset, self.length = dimension, offset, length

    def _apply(self, params, buffers, x, training, rng):
        d = self.dimension - 1 if self.dimension > 0 else x.ndim + self.dimension
        length = self.length
        if length < 0:
            length = x.shape[d] - self.offset + 2 + length
        start = self.offset - 1
        return jax.lax.slice_in_dim(x, start, start + length, axis=d), buffers


class SelectTable(AbstractModule):
    """Pick entry i from a Table (reference nn/SelectTable.scala)."""

    def __init__(self, index: int):
        super().__init__()
        self.index = index

    def _apply(self, params, buffers, inp, training, rng):
        idx = self.index if self.index > 0 else len(inp) + self.index + 1
        return inp[idx], buffers


class NarrowTable(AbstractModule):
    """Slice a Table (reference nn/NarrowTable.scala)."""

    def __init__(self, offset: int, length: int = 1):
        super().__init__()
        self.offset, self.length = offset, length

    def _apply(self, params, buffers, inp, training, rng):
        length = self.length
        if length < 0:
            length = inp.length() - self.offset + 2 + length
        out = Table()
        for i in range(length):
            out[i + 1] = inp[self.offset + i]
        return out, buffers


class FlattenTable(AbstractModule):
    """reference nn/FlattenTable.scala"""

    def _apply(self, params, buffers, inp, training, rng):
        return inp.flatten(), buffers


class SplitTable(AbstractModule):
    """Split a tensor along dim into a Table (reference nn/SplitTable.scala)."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__()
        self.dimension, self.n_input_dims = dimension, n_input_dims

    def _apply(self, params, buffers, x, training, rng):
        d = self.dimension - 1 if self.dimension > 0 else x.ndim + self.dimension
        if self.n_input_dims > 0 and x.ndim > self.n_input_dims:
            d += 1
        out = Table()
        for i in range(x.shape[d]):
            out[i + 1] = jnp.take(x, i, axis=d)
        return out, buffers


class JoinTable(AbstractModule):
    """Concat a Table of tensors along dim (reference nn/JoinTable.scala)."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__()
        self.dimension, self.n_input_dims = dimension, n_input_dims

    def _apply(self, params, buffers, inp, training, rng):
        first = inp[1]
        d = self.dimension - 1
        if self.n_input_dims > 0 and first.ndim > self.n_input_dims:
            d += 1
        return jnp.concatenate([inp[i + 1] for i in range(inp.length())],
                               axis=d), buffers


class Pack(AbstractModule):
    """Stack a Table of tensors along a new dim (reference nn/Pack.scala)."""

    def __init__(self, dimension: int):
        super().__init__()
        self.dimension = dimension

    def _apply(self, params, buffers, inp, training, rng):
        if isinstance(inp, Table):
            arrs = [inp[i + 1] for i in range(inp.length())]
        else:
            arrs = [inp]
        return jnp.stack(arrs, axis=self.dimension - 1), buffers


class Reverse(TensorModule):
    """Reverse along a dim (reference nn/Reverse.scala)."""

    def __init__(self, dimension: int = 1):
        super().__init__()
        self.dimension = dimension

    def _apply(self, params, buffers, x, training, rng):
        return jnp.flip(x, axis=self.dimension - 1), buffers


class Contiguous(TensorModule):
    """No-op under XLA (reference nn/Contiguous.scala)."""

    def _apply(self, params, buffers, x, training, rng):
        return x, buffers


class Index(AbstractModule):
    """Table(src, indices) → index_select (reference nn/Index.scala)."""

    def __init__(self, dimension: int):
        super().__init__()
        self.dimension = dimension

    def _apply(self, params, buffers, inp, training, rng):
        src, idx = inp[1], inp[2]
        return jnp.take(src, idx.astype(jnp.int32) - 1,
                        axis=self.dimension - 1), buffers


class MaskedSelect(AbstractModule):
    """Table(src, mask) → masked flatten (reference nn/MaskedSelect.scala).

    Note: output size is data-dependent; usable eagerly, not under jit.
    The backward is implemented directly (scatter grad_output into the
    mask positions, reference MaskedSelect.scala:51) because the generic
    vjp path cannot trace the data-dependent output shape.
    """

    def _apply(self, params, buffers, inp, training, rng):
        src, mask = np.asarray(inp[1]), np.asarray(inp[2]).astype(bool)
        return jnp.asarray(src[mask]), buffers

    def update_grad_input(self, inp, grad_output):
        from ..utils.table import T

        src, mask = np.asarray(inp[1]), np.asarray(inp[2]).astype(bool)
        g = np.zeros(src.shape, np.asarray(grad_output).dtype)
        g[mask] = np.asarray(grad_output)
        self.grad_input = T(jnp.asarray(g),
                            jnp.zeros(mask.shape, src.dtype))
        return self.grad_input

    def backward(self, inp, grad_output):
        return self.update_grad_input(inp, grad_output)


class Padding(TensorModule):
    """Pad ``pad`` entries (sign = side) along dim (reference nn/Padding.scala)."""

    def __init__(self, dim: int, pad: int, n_input_dim: int,
                 value: float = 0.0, n_index: int = 1):
        super().__init__()
        self.dim, self.pad, self.n_input_dim = dim, pad, n_input_dim
        self.value = value

    def _apply(self, params, buffers, x, training, rng):
        d = self.dim - 1
        if x.ndim > self.n_input_dim:
            d += 1
        widths = [(0, 0)] * x.ndim
        widths[d] = (abs(self.pad), 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(x, widths, constant_values=self.value), buffers


class SpatialZeroPadding(TensorModule):
    """reference nn/SpatialZeroPadding.scala — NCHW zero pad."""

    def __init__(self, pad_left: int, pad_right: int, pad_top: int, pad_bottom: int):
        super().__init__()
        self.pads = (pad_left, pad_right, pad_top, pad_bottom)

    def _apply(self, params, buffers, x, training, rng):
        l, r, t, b = self.pads
        widths = [(0, 0)] * (x.ndim - 2) + [(t, b), (l, r)]
        return jnp.pad(x, widths), buffers


class DotProduct(AbstractModule):
    """Rowwise dot of Table(a, b) (reference nn/DotProduct.scala)."""

    def _apply(self, params, buffers, inp, training, rng):
        a, b = inp[1], inp[2]
        return jnp.sum(a * b, axis=-1), buffers


class CosineDistance(AbstractModule):
    """Rowwise cosine of Table(a, b) (reference nn/CosineDistance.scala)."""

    def _apply(self, params, buffers, inp, training, rng):
        a, b = inp[1], inp[2]
        na = jnp.maximum(jnp.linalg.norm(a, axis=-1), 1e-12)
        nb = jnp.maximum(jnp.linalg.norm(b, axis=-1), 1e-12)
        return jnp.sum(a * b, axis=-1) / (na * nb), buffers


class PairwiseDistance(AbstractModule):
    """Lp distance of Table(a, b) (reference nn/PairwiseDistance.scala)."""

    def __init__(self, norm: int = 2):
        super().__init__()
        self.norm = norm

    def _apply(self, params, buffers, inp, training, rng):
        d = inp[1] - inp[2]
        return jnp.sum(jnp.abs(d) ** self.norm, axis=-1) ** (1.0 / self.norm), buffers


class MixtureTable(AbstractModule):
    """Gater-weighted blend of expert outputs (reference nn/MixtureTable.scala).

    Input: Table(gater (N,K), experts Table of K tensors (N,...)).
    """

    def __init__(self, dim: int = np.iinfo(np.int32).max):
        super().__init__()
        self.dim = dim

    def _apply(self, params, buffers, inp, training, rng):
        gater, experts = inp[1], inp[2]
        if isinstance(experts, Table):
            stacked = jnp.stack([experts[i + 1] for i in range(experts.length())],
                                axis=1)  # (N, K, ...)
        else:
            stacked = experts
        g = gater.reshape(gater.shape + (1,) * (stacked.ndim - gater.ndim))
        return jnp.sum(stacked * g, axis=1), buffers


class Scale(TensorModule):
    """CMul then CAdd (reference nn/Scale.scala)."""

    def __init__(self, size: Sequence[int]):
        super().__init__()
        from .linear import CAdd, CMul

        self.cmul = CMul(size)
        self.cadd = CAdd(size)

    def param_tree(self):
        return {"mul": self.cmul.param_tree(), "add": self.cadd.param_tree()}

    def set_param_tree(self, tree):
        self.cmul.set_param_tree(tree["mul"])
        self.cadd.set_param_tree(tree["add"])

    def gradient_scale_tree(self):
        return {"mul": self.cmul.gradient_scale_tree(),
                "add": self.cadd.gradient_scale_tree()}

    def grad_tree(self):
        return {"mul": self.cmul.grad_tree(), "add": self.cadd.grad_tree()}

    def set_grad_tree(self, tree):
        self.cmul.set_grad_tree(tree["mul"])
        self.cadd.set_grad_tree(tree["add"])

    def _apply(self, params, buffers, x, training, rng):
        y, _ = self.cmul._apply(params["mul"], {}, x, training, rng)
        y, _ = self.cadd._apply(params["add"], {}, y, training, rng)
        return y, buffers


class GradientReversal(TensorModule):
    """Identity forward, negated+scaled gradient (reference
    nn/GradientReversal.scala) — via jax.custom_vjp."""

    def __init__(self, the_lambda: float = 1.0):
        super().__init__()
        self.the_lambda = the_lambda

    def set_lambda(self, lam):
        self.the_lambda = lam
        return self

    def _apply(self, params, buffers, x, training, rng):
        lam = self.the_lambda

        @jax.custom_vjp
        def rev(v):
            return v

        rev.defvjp(lambda v: (v, None), lambda _, g: (-lam * g,))
        return rev(x), buffers
