"""Container modules (reference nn/Sequential.scala:30, Concat.scala,
ConcatTable.scala, ParallelTable.scala, Bottle.scala, MapTable.scala).

Each container's ``apply_fn`` is pure composition of its children's pure
applies — so any container tree traces into one XLA program.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..utils.table import Table
from .module import AbstractModule, Container, TensorModule


def _split_rng(rng, n):
    import jax

    if rng is None:
        return [None] * n
    return list(jax.random.split(rng, n))


class Sequential(Container):
    """Chain children (reference nn/Sequential.scala:30)."""

    def apply_fn(self, params, buffers, inp, training=True, rng=None):
        x = inp
        new_buffers = {}
        rngs = _split_rng(rng, max(len(self.modules), 1))
        for i, m in enumerate(self.modules):
            x, nb = m.apply_fn(params[str(i)], buffers[str(i)], x,
                               training, rngs[i])
            new_buffers[str(i)] = nb
        return x, new_buffers


class Concat(Container):
    """Apply each child to the same input, concatenate outputs along
    ``dimension`` (1-based) (reference nn/Concat.scala)."""

    def __init__(self, dimension: int, *modules):
        super().__init__(*modules)
        self.dimension = dimension

    def apply_fn(self, params, buffers, inp, training=True, rng=None):
        outs, new_buffers = [], {}
        rngs = _split_rng(rng, max(len(self.modules), 1))
        for i, m in enumerate(self.modules):
            o, nb = m.apply_fn(params[str(i)], buffers[str(i)], inp,
                               training, rngs[i])
            outs.append(o)
            new_buffers[str(i)] = nb
        return jnp.concatenate(outs, axis=self.dimension - 1), new_buffers


class ConcatTable(Container):
    """Apply each child to the same input, return a Table of outputs
    (reference nn/ConcatTable.scala)."""

    def apply_fn(self, params, buffers, inp, training=True, rng=None):
        out, new_buffers = Table(), {}
        rngs = _split_rng(rng, max(len(self.modules), 1))
        for i, m in enumerate(self.modules):
            o, nb = m.apply_fn(params[str(i)], buffers[str(i)], inp,
                               training, rngs[i])
            out[i + 1] = o
            new_buffers[str(i)] = nb
        return out, new_buffers


class ParallelTable(Container):
    """i-th child applied to i-th input table entry (reference
    nn/ParallelTable.scala)."""

    def apply_fn(self, params, buffers, inp, training=True, rng=None):
        out, new_buffers = Table(), {}
        rngs = _split_rng(rng, max(len(self.modules), 1))
        for i, m in enumerate(self.modules):
            o, nb = m.apply_fn(params[str(i)], buffers[str(i)], inp[i + 1],
                               training, rngs[i])
            out[i + 1] = o
            new_buffers[str(i)] = nb
        return out, new_buffers


class MapTable(Container):
    """Apply ONE shared child to every input entry (reference
    nn/MapTable.scala) — weight sharing is free: same params subtree."""

    def __init__(self, module: AbstractModule):
        super().__init__(module)

    def apply_fn(self, params, buffers, inp, training=True, rng=None):
        m = self.modules[0]
        out = Table()
        nb = buffers["0"]
        rngs = _split_rng(rng, max(len(inp), 1))
        for j, key in enumerate(sorted(k for k in inp.keys())):
            o, nb = m.apply_fn(params["0"], nb, inp[key], training, rngs[j])
            out[key] = o
        return out, {"0": nb}


class Bottle(Container):
    """Collapse leading dims, apply child, restore (reference nn/Bottle.scala)."""

    def __init__(self, module: AbstractModule, n_input_dim: int = 2,
                 n_output_dim: int = 2):
        super().__init__(module)
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim

    def apply_fn(self, params, buffers, inp, training=True, rng=None):
        in_shape = inp.shape
        if len(in_shape) <= self.n_input_dim:
            return self.modules[0].apply_fn(params["0"], buffers["0"], inp,
                                            training, rng)
        lead = in_shape[:len(in_shape) - self.n_input_dim + 1]
        rest = in_shape[len(in_shape) - self.n_input_dim + 1:]
        squashed = inp.reshape((-1,) + rest)
        out, nb = self.modules[0].apply_fn(params["0"], buffers["0"], squashed,
                                           training, rng)
        out = out.reshape(lead + out.shape[1:])
        return out, {"0": nb}


class Identity(TensorModule):
    """reference nn/Identity.scala"""

    def _apply(self, params, buffers, inp, training, rng):
        return inp, buffers


class Echo(TensorModule):
    """Print shape as activations flow past (reference nn/Echo.scala).
    Uses jax.debug so it works under jit."""

    def _apply(self, params, buffers, inp, training, rng):
        import jax

        jax.debug.print(self.get_name() + " shape: {}", inp.shape)
        return inp, buffers
