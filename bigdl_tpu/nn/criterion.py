"""Criterions (reference nn/abstractnn/AbstractCriterion.scala:49 and the
~28 criterion files, SURVEY §2.4).

Each criterion defines ONE pure ``_loss(input, target) -> scalar``;
``backward`` is ``jax.grad`` of it — no hand-written gradients.  Class
weights / margins etc. are static attributes baked into the trace.

Target index convention follows the reference: class labels are 1-based
floats.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.table import Table
from .module import to_array


class AbstractCriterion:
    # Criterions that accumulate internally in f32 set this True: the
    # mixed-precision drivers then skip the blanket f32 upcast of the
    # model output — at LM vocab sizes that upcast alone materialises
    # a gigabyte-scale [N, V] tensor the fused path exists to avoid.
    accepts_low_precision = False

    def __init__(self):
        self.output = 0.0
        self.grad_input = None
        self.size_average = True

    def _loss(self, inp, target):
        raise NotImplementedError

    def update_output(self, inp, target):
        self.output = float(self._loss(to_array(inp), to_array(target)))
        return self.output

    def forward(self, inp, target):
        return self.update_output(inp, target)

    def update_grad_input(self, inp, target):
        inp, target = to_array(inp), to_array(target)
        self.grad_input = jax.grad(lambda x: self._loss(x, target))(inp)
        return self.grad_input

    def backward(self, inp, target):
        return self.update_grad_input(inp, target)

    def __call__(self, inp, target):
        return self.forward(inp, target)

    def clone_criterion(self):
        import copy

        return copy.deepcopy(self)


def _batch_reduce(losses, size_average):
    return jnp.mean(losses) if size_average else jnp.sum(losses)


class ClassNLLCriterion(AbstractCriterion):
    """NLL over log-probabilities, 1-based integer targets, optional class
    weights (reference nn/ClassNLLCriterion.scala:60)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(to_array(weights))
        self.size_average = size_average

    def _loss(self, inp, target):
        if inp.ndim == 1:
            inp = inp[None]
            target = jnp.reshape(target, (1,))
        t = target.astype(jnp.int32).reshape(-1) - 1
        picked = jnp.take_along_axis(inp, t[:, None], axis=1)[:, 0]
        if self.weights is not None:
            w = jnp.take(self.weights, t)
            total = jnp.sum(w)
            s = -jnp.sum(w * picked)
            return s / total if self.size_average else s
        return -( jnp.mean(picked) if self.size_average else jnp.sum(picked))


class CrossEntropyCriterion(AbstractCriterion):
    """LogSoftMax + ClassNLL fused (reference nn/CrossEntropyCriterion.scala).

    The unweighted path uses ``ops.fused_xent``: logits stay in their
    compute dtype, the log-sum-exp accumulates f32, and the backward
    recomputes the softmax instead of storing it — at LM vocab sizes
    this removes gigabytes of HBM traffic per step."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.nll = ClassNLLCriterion(weights, size_average)
        self.size_average = size_average
        # the fused path accumulates f32 internally; bf16 logits welcome
        self.accepts_low_precision = weights is None

    def _loss(self, inp, target):
        if self.nll.weights is not None:
            return self.nll._loss(jax.nn.log_softmax(inp, axis=-1), target)
        from ..ops.fused_xent import softmax_xent_rows

        if inp.ndim == 1:
            inp = inp[None]
        t = target.astype(jnp.int32).reshape(-1) - 1
        rows = softmax_xent_rows(inp.reshape(-1, inp.shape[-1]), t)
        return jnp.mean(rows) if self.size_average else jnp.sum(rows)


class MSECriterion(AbstractCriterion):
    """reference nn/MSECriterion.scala:32"""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def _loss(self, inp, target):
        se = jnp.square(inp - target)
        return jnp.mean(se) if self.size_average else jnp.sum(se)


class AbsCriterion(AbstractCriterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def _loss(self, inp, target):
        d = jnp.abs(inp - target)
        return jnp.mean(d) if self.size_average else jnp.sum(d)


class BCECriterion(AbstractCriterion):
    """Binary cross entropy with optional per-element weights
    (reference nn/BCECriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(to_array(weights))
        self.size_average = size_average

    def _loss(self, inp, target):
        eps = 1e-12
        l = -(target * jnp.log(inp + eps) + (1 - target) * jnp.log1p(-inp + eps))
        if self.weights is not None:
            l = l * self.weights
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class SmoothL1Criterion(AbstractCriterion):
    """Huber with delta 1 (reference nn/SmoothL1Criterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def _loss(self, inp, target):
        d = jnp.abs(inp - target)
        l = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class SmoothL1CriterionWithWeights(AbstractCriterion):
    """Fast-RCNN bbox loss with inside/outside weights (reference
    nn/SmoothL1CriterionWithWeights.scala).  Input: tensor; target Table
    (target, inside_w, outside_w)."""

    def __init__(self, sigma: float = 1.0, num: int = 0):
        super().__init__()
        self.sigma2 = sigma * sigma
        self.num = num

    def _loss(self, inp, target):
        t, w_in, w_out = target[1], target[2], target[3]
        d = (inp - t) * w_in
        ad = jnp.abs(d)
        l = jnp.where(ad < 1.0 / self.sigma2,
                      0.5 * self.sigma2 * d * d, ad - 0.5 / self.sigma2)
        l = l * w_out
        s = jnp.sum(l)
        return s / self.num if self.num > 0 else s


class MarginCriterion(AbstractCriterion):
    """Hinge: max(0, margin - y*x) (reference nn/MarginCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def _loss(self, inp, target):
        l = jnp.maximum(0.0, self.margin - inp * target)
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class MarginRankingCriterion(AbstractCriterion):
    """Input Table(x1, x2), y=±1 (reference nn/MarginRankingCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def _loss(self, inp, target):
        x1, x2 = inp[1], inp[2]
        y = target[1] if isinstance(target, Table) else target
        l = jnp.maximum(0.0, -y * (x1 - x2) + self.margin)
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class MultiMarginCriterion(AbstractCriterion):
    """Multi-class hinge (reference nn/MultiMarginCriterion.scala)."""

    def __init__(self, p: int = 1, weights=None, margin: float = 1.0,
                 size_average: bool = True):
        super().__init__()
        self.p, self.margin = p, margin
        self.weights = None if weights is None else jnp.asarray(to_array(weights))
        self.size_average = size_average

    def _loss(self, inp, target):
        if inp.ndim == 1:
            inp = inp[None]
            target = jnp.reshape(target, (1,))
        n, k = inp.shape
        t = target.astype(jnp.int32).reshape(-1) - 1
        x_y = jnp.take_along_axis(inp, t[:, None], axis=1)
        margins = jnp.maximum(0.0, self.margin - x_y + inp) ** self.p
        if self.weights is not None:
            margins = margins * jnp.take(self.weights, t)[:, None]
        mask = jax.nn.one_hot(t, k, dtype=inp.dtype)
        per_sample = jnp.sum(margins * (1 - mask), axis=1) / k
        return jnp.mean(per_sample) if self.size_average else jnp.sum(per_sample)


class MultiLabelMarginCriterion(AbstractCriterion):
    """Multi-label hinge; targets are 1-based label lists padded with 0
    (reference nn/MultiLabelMarginCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def _loss(self, inp, target):
        if inp.ndim == 1:
            inp = inp[None]
            target = jnp.reshape(target, (1, -1))
        n, k = inp.shape
        t = target.astype(jnp.int32) - 1  # (n, k), -1 = padding
        valid = (t >= 0).astype(inp.dtype)
        t_safe = jnp.clip(t, 0, k - 1)
        is_target = jnp.zeros((n, k), inp.dtype)
        is_target = jax.vmap(
            lambda row, idx, v: row.at[idx].add(v))(is_target, t_safe, valid)
        is_target = jnp.minimum(is_target, 1.0)
        x_y = jnp.take_along_axis(inp, t_safe, axis=1)  # (n, k)
        # sum over target labels y and non-target j: max(0, 1 - (x_y - x_j))
        diff = 1.0 - (x_y[:, :, None] - inp[:, None, :])  # (n, y, j)
        hinge = jnp.maximum(0.0, diff)
        mask = valid[:, :, None] * (1.0 - is_target)[:, None, :]
        per_sample = jnp.sum(hinge * mask, axis=(1, 2)) / k
        return jnp.mean(per_sample) if self.size_average else jnp.sum(per_sample)


class MultiLabelSoftMarginCriterion(AbstractCriterion):
    """Sigmoid + BCE per label (reference nn/MultiLabelSoftMarginCriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(to_array(weights))
        self.size_average = size_average

    def _loss(self, inp, target):
        l = (jnp.logaddexp(0.0, -inp) * target
             + jnp.logaddexp(0.0, inp) * (1 - target))
        if self.weights is not None:
            l = l * self.weights
        per_sample = jnp.mean(l, axis=-1)
        return jnp.mean(per_sample) if self.size_average else jnp.sum(per_sample)


class HingeEmbeddingCriterion(AbstractCriterion):
    """y=1 → x ; y=-1 → max(0, margin - x) (reference
    nn/HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def _loss(self, inp, target):
        l = jnp.where(target > 0, inp, jnp.maximum(0.0, self.margin - inp))
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class L1HingeEmbeddingCriterion(AbstractCriterion):
    """Pairwise L1 distance hinge over Table(x1, x2)
    (reference nn/L1HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 1.0):
        super().__init__()
        self.margin = margin

    def _loss(self, inp, target):
        d = jnp.sum(jnp.abs(inp[1] - inp[2]))
        y = target if not isinstance(target, Table) else target[1]
        y = jnp.reshape(y, ())
        return jnp.where(y > 0, d, jnp.maximum(0.0, self.margin - d))


class CosineEmbeddingCriterion(AbstractCriterion):
    """reference nn/CosineEmbeddingCriterion.scala"""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def _loss(self, inp, target):
        x1, x2 = inp[1], inp[2]
        if x1.ndim == 1:
            x1, x2 = x1[None], x2[None]
        y = target[1] if isinstance(target, Table) else target
        y = jnp.reshape(y, (-1,))
        cos = (jnp.sum(x1 * x2, -1)
               / jnp.maximum(jnp.linalg.norm(x1, axis=-1)
                             * jnp.linalg.norm(x2, axis=-1), 1e-12))
        l = jnp.where(y > 0, 1.0 - cos, jnp.maximum(0.0, cos - self.margin))
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class CosineDistanceCriterion(AbstractCriterion):
    """1 - cos(input, target) (reference nn/CosineDistanceCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def _loss(self, inp, target):
        if inp.ndim == 1:
            inp, target = inp[None], target[None]
        cos = (jnp.sum(inp * target, -1)
               / jnp.maximum(jnp.linalg.norm(inp, axis=-1)
                             * jnp.linalg.norm(target, axis=-1), 1e-12))
        l = 1.0 - cos
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class DistKLDivCriterion(AbstractCriterion):
    """KL divergence, input = log-probs (reference nn/DistKLDivCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def _loss(self, inp, target):
        l = jnp.where(target > 0, target * (jnp.log(jnp.maximum(target, 1e-12)) - inp), 0.0)
        n = inp.shape[0] if inp.ndim > 1 else 1
        return jnp.sum(l) / n if self.size_average else jnp.sum(l)


class ClassSimplexCriterion(MSECriterion):
    """MSE against simplex-embedded class targets (reference
    nn/ClassSimplexCriterion.scala)."""

    def __init__(self, n_classes: int):
        super().__init__()
        self.n_classes = n_classes
        self.simplex = jnp.asarray(self._build_simplex(n_classes))

    @staticmethod
    def _build_simplex(n_classes):
        """Regular (N-1)-simplex embedding, the reference's ``regsplex``
        (ClassSimplexCriterion.scala:43-62): rows are unit vectors with
        pairwise dot product -1/n, zero-padded to n_classes columns."""
        n = n_classes - 1
        a = np.zeros((n + 1, n), np.float64)
        for k in range(1, n + 1):
            i = k - 1
            if k == 1:
                a[i, i] = 1.0
            else:
                nrm = np.linalg.norm(a[i, :i])
                a[i, i] = np.sqrt(1.0 - nrm * nrm)
            c = (a[i, i] * a[i, i] - 1.0 - 1.0 / n) / a[i, i]
            a[k:, i] = c
        out = np.zeros((n + 1, n_classes), np.float32)
        out[:, :n] = a
        return out

    def _loss(self, inp, target):
        t = target.astype(jnp.int32).reshape(-1) - 1
        goal = jnp.take(self.simplex, t, axis=0)
        return super()._loss(inp, goal)


class DiceCoefficientCriterion(AbstractCriterion):
    """1 - dice overlap (reference nn/DiceCoefficientCriterion.scala)."""

    def __init__(self, size_average: bool = True, epsilon: float = 1.0):
        super().__init__()
        self.epsilon = epsilon
        self.size_average = size_average

    def _loss(self, inp, target):
        if inp.ndim == 1:
            inp, target = inp[None], target[None]
        inter = jnp.sum(inp * target, axis=-1)
        union = jnp.sum(inp, axis=-1) + jnp.sum(target, axis=-1)
        dice = (2.0 * inter + self.epsilon) / (union + self.epsilon)
        l = 1.0 - dice
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class L1Cost(AbstractCriterion):
    """sum(|input|), target ignored (reference nn/L1Cost.scala)."""

    def _loss(self, inp, target):
        return jnp.sum(jnp.abs(inp))


class SoftMarginCriterion(AbstractCriterion):
    """log(1 + exp(-y*x)) (reference nn/SoftMarginCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def _loss(self, inp, target):
        l = jnp.logaddexp(0.0, -inp * target)
        return jnp.mean(l) if self.size_average else jnp.sum(l)


class SoftmaxWithCriterion(AbstractCriterion):
    """Caffe-style fused softmax loss over NCHW with ignore_label
    (reference nn/SoftmaxWithCriterion.scala)."""

    def __init__(self, ignore_label: Optional[int] = None,
                 normalize_mode: str = "VALID"):
        super().__init__()
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode

    def _loss(self, inp, target):
        # inp (N, C, H, W) or (N, C); target 1-based labels.  The
        # reference reads the label storage FLAT (labelData(i*innerNum+j),
        # SoftmaxWithCriterion.scala:64-72), so any target shape with
        # N*H*W elements is legal — notably Caffe's (N, 1, H, W)
        logp = jax.nn.log_softmax(inp, axis=1)
        # clamp the gather index: out-of-range labels (Caffe ignore
        # convention 255, usually >= C) must not poison the gather with
        # NaN fills — the reference skips them before ever indexing
        # (SoftmaxWithCriterion.scala:72-76); the mask below then zeroes
        # the clamped picks.  With no ignore_label configured, an
        # out-of-range label is ALSO masked out of the traced loss
        # (zero contribution, excluded from the VALID count) — and, so a
        # label bug (e.g. accidentally 0-based targets) cannot silently
        # train on nothing, the EAGER path validates and raises; inside
        # jit the values are tracers and only the masking semantics can
        # apply.
        t0 = target.astype(jnp.int32) - 1
        if self.ignore_label is None and not isinstance(t0, jax.core.Tracer):
            import numpy as _np

            bad = _np.asarray((t0 < 0) | (t0 >= inp.shape[1]))
            if bad.any():
                raise ValueError(
                    f"SoftmaxWithCriterion: {int(bad.sum())} target "
                    f"label(s) outside the 1-based range [1, "
                    f"{inp.shape[1]}] and no ignore_label configured "
                    "(labels are 1-based; 0 usually means 0-based "
                    "inputs).  Set ignore_label to skip them "
                    "deliberately.")
        t = jnp.clip(t0, 0, inp.shape[1] - 1)
        if inp.ndim == 2:
            picked = jnp.take_along_axis(logp, t.reshape(-1, 1), axis=1)[:, 0]
        else:
            spatial = inp.shape[2:]
            picked = jnp.take_along_axis(
                logp, t.reshape(inp.shape[0], 1, *spatial), axis=1)[:, 0]
        mask = (t0 >= 0) & (t0 < inp.shape[1])
        if self.ignore_label is not None:
            mask = mask & (target != self.ignore_label)
        mask = mask.astype(inp.dtype).reshape(picked.shape)
        picked = picked * mask
        # VALID normalizes by the masked-in count in every configuration
        # (with all-in-range labels and no ignore_label this is exactly
        # picked.size, the pre-masking behavior)
        count = jnp.maximum(jnp.sum(mask), 1.0)
        if self.normalize_mode == "VALID":
            return -jnp.sum(picked) / count
        if self.normalize_mode == "FULL":
            return -jnp.sum(picked) / picked.size
        if self.normalize_mode == "BATCH_SIZE":
            return -jnp.sum(picked) / inp.shape[0]
        return -jnp.sum(picked)


class TimeDistributedCriterion(AbstractCriterion):
    """Apply a criterion at every timestep (reference
    nn/TimeDistributedCriterion.scala)."""

    def __init__(self, critrn: AbstractCriterion, size_average: bool = False):
        super().__init__()
        self.critrn = critrn
        self.size_average = size_average
        self.accepts_low_precision = critrn.accepts_low_precision

    def _loss(self, inp, target):
        steps = inp.shape[1]
        c = self.critrn
        # Fused path for the classification criterions: sum_t mean_b ==
        # steps * mean_{b,t}, so one flattened (B*T, V) call replaces T
        # traced per-timestep calls — at LM scale the unrolled loop
        # dominates compile AND step time.
        flat_ok = (isinstance(c, (ClassNLLCriterion, CrossEntropyCriterion))
                   and c.size_average and inp.ndim == 3
                   and (c.weights if isinstance(c, ClassNLLCriterion)
                        else c.nll.weights) is None)
        if flat_ok:
            flat = c._loss(inp.reshape(-1, inp.shape[-1]),
                           target.reshape(-1))
            return flat if self.size_average else flat * steps

        def per_t(i):
            return c._loss(inp[:, i], target[:, i])

        total = sum(per_t(i) for i in range(steps))
        return total / steps if self.size_average else total


class ParallelCriterion(AbstractCriterion):
    """Weighted sum of criterions over input/target Tables
    (reference nn/ParallelCriterion.scala)."""

    def __init__(self, repeat_target: bool = False):
        super().__init__()
        self.repeat_target = repeat_target
        self.criterions = []
        self.weights = []

    def add(self, criterion: AbstractCriterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def _loss(self, inp, target):
        total = 0.0
        for i, (c, w) in enumerate(zip(self.criterions, self.weights)):
            t = target if self.repeat_target else target[i + 1]
            total = total + w * c._loss(inp[i + 1], t)
        return total


class MultiCriterion(AbstractCriterion):
    """Sum of criterions on the SAME input/target (reference
    nn/MultiCriterion.scala)."""

    def __init__(self):
        super().__init__()
        self.criterions = []
        self.weights = []

    def add(self, criterion: AbstractCriterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def _loss(self, inp, target):
        return sum(w * c._loss(inp, target)
                   for c, w in zip(self.criterions, self.weights))
