"""Parameter-server-scale embedding store with live row re-partitioning.

PR 15's :class:`~bigdl_tpu.nn.embedding.ShardedEmbedding` proved the
row-sharded table on a mesh, but it holds every shard in device memory
and a membership change degrades non-dividing tables to full replicas.
This module is the Parallax hybrid's other half (arxiv 1808.02621):
**host-memory tables that dwarf HBM** (1e8-row capable — blocks are
materialized lazily, so capacity costs nothing until rows are touched)
with a device-side/serving-side hot-row cache keyed by the
clickstream's Zipf skew, and — the robustness core — **live
shrink/regrow row re-partitioning**:

* **Ownership is consistent, not modular.**  Rows group into fixed
  blocks and each block's owner is chosen by highest-random-weight
  (rendezvous) hashing over the member set: removing one host moves
  exactly the blocks it owned (~1/N of rows) and adding one steals
  ~1/(N+1) — never a full reshuffle.  Every host derives the same
  assignment from the member list alone, so there is no ownership
  directory to keep consistent.

* **Migration is sealed and verified.**  On membership change each
  survivor re-derives ownership and ships the blocks it no longer owns
  as crc32c-sealed shards through the elastic KV transport
  (:class:`~bigdl_tpu.resilience.elastic.KVTransport` — the same
  channel heartbeats and integrity votes ride).  Import verifies every
  shard's checksum before a byte lands: a torn or bit-flipped shard
  raises the typed :class:`MigrationCorrupt` and the importer
  re-requests the block from the owner's **checkpointed leg** (its
  crc-sidecar-verified block file) — a row is never silently
  zero-filled or re-initialized.

* **Versioned reads.**  Each repartition bumps the table version;
  the :class:`HotRowCache` retires every cached row from prior
  versions in O(1), and `read_rows` stamps the version it served so a
  serving-side fetch can prove it never handed out a retired row
  (``bad_rows_served == 0`` under chaos — see
  :mod:`bigdl_tpu.serving.sparse_fetch`).

The deterministic fault injectors driving the chaos tests live in
:mod:`bigdl_tpu.resilience.faults` (``corrupt_migration_shard`` /
``kill_host_mid_repartition``); the ownership function, migration
state machine, and staleness bound are documented in
``docs/embeddings.md``.
"""
from __future__ import annotations

import base64
import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "MigrationCorrupt", "StoreMigrating", "block_owner", "assign_blocks",
    "HotRowCache", "EmbeddingStore", "table_checksum",
]


class MigrationCorrupt(RuntimeError):
    """A migrating row shard failed its crc32c verify-on-import (torn
    write, in-flight bit flip) AND the owner's checkpointed leg could
    not supply a verified replacement.  ``code`` ``"DATA_LOSS"``:
    continuing would train/serve on unknown bytes, so the import stops
    loudly instead of zero-filling."""

    code = "DATA_LOSS"

    def __init__(self, message: str, table: str = "", block: int = -1):
        super().__init__(message)
        self.table = table
        self.block = int(block)


class StoreMigrating(RuntimeError):
    """A read arrived while the store was mid-repartition and the row's
    block is in flight.  Retryable (``code`` ``"UNAVAILABLE"``): the
    serving fetch retries within its deadline budget or sheds typed —
    it never serves a row it cannot verify."""

    code = "UNAVAILABLE"


# ---------------------------------------------------------------------------
# consistent (rendezvous) block ownership
# ---------------------------------------------------------------------------

def _hrw_weight(table: str, block: int, member: str) -> int:
    h = hashlib.blake2b(f"{table}/{block}/{member}".encode(),
                        digest_size=8)
    return int.from_bytes(h.digest(), "big")


def block_owner(table: str, block: int,
                members: Sequence[str]) -> str:
    """Highest-random-weight owner of ``block`` among ``members`` —
    every host computes the same answer from the member list alone,
    and a 1-host delta re-assigns only that host's blocks."""
    if not members:
        raise ValueError(f"block_owner({table!r}, {block}): empty "
                         "member set")
    return max(sorted(members),
               key=lambda m: _hrw_weight(table, block, m))


def assign_blocks(table: str, n_blocks: int,
                  members: Sequence[str]) -> Dict[int, str]:
    """The full block → owner map for ``members`` (deterministic)."""
    ms = sorted(set(members))
    return {b: block_owner(table, b, ms) for b in range(int(n_blocks))}


# ---------------------------------------------------------------------------
# hot-row cache: version-retired, thread-safe
# ---------------------------------------------------------------------------

class HotRowCache:
    """Bounded LRU of hot rows, invalidated **by table version**.

    Every entry is stamped with the version it was read at; a
    repartition bumps the cache's current version, retiring every
    prior entry in O(1) — ``get`` refuses (and evicts) any entry whose
    stamp is not current, and ``put`` refuses a stamp that is already
    retired, so a lookup racing an invalidation can never resurrect a
    stale row.  The staleness bound is therefore **one version**: a
    cached row is served only while the version it was read at is
    still the table's live version (docs/embeddings.md "Cache
    staleness").
    """

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"HotRowCache capacity must be >= 1, got "
                             f"{capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._data: Dict[int, Tuple[int, np.ndarray]] = {}
        self._order: List[int] = []   # LRU order, oldest first
        self._version = 0
        self.hits = 0
        self.misses = 0
        self.stale_evictions = 0
        self.rejected_puts = 0

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def bump_version(self, version: Optional[int] = None) -> int:
        """Retire every entry cached before this call.  Monotonic:
        a stale ``version`` argument never rewinds the cache."""
        with self._lock:
            if version is None:
                self._version += 1
            else:
                self._version = max(self._version, int(version))
            return self._version

    def get(self, row: int) -> Optional[np.ndarray]:
        with self._lock:
            ent = self._data.get(row)
            if ent is None:
                self.misses += 1
                return None
            ver, vec = ent
            if ver != self._version:
                # retired version: evict, never serve
                del self._data[row]
                self._order.remove(row)
                self.stale_evictions += 1
                self.misses += 1
                return None
            self.hits += 1
            self._order.remove(row)
            self._order.append(row)
            return vec

    def put(self, row: int, vec: np.ndarray, version: int) -> bool:
        """Insert ``row`` read at ``version``.  Refused (False) when
        ``version`` is already retired — the lost-invalidation guard:
        a fetch that started before a repartition must not overwrite
        the bump that landed mid-flight."""
        with self._lock:
            if int(version) != self._version:
                self.rejected_puts += 1
                return False
            if row in self._data:
                self._order.remove(row)
            elif len(self._data) >= self.capacity:
                oldest = self._order.pop(0)
                del self._data[oldest]
            self._data[row] = (int(version), vec)
            self._order.append(row)
            return True

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def snapshot(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "version": self._version,
                "size": len(self._data),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (self.hits / total) if total else 0.0,
                "stale_evictions": self.stale_evictions,
                "rejected_puts": self.rejected_puts,
            }


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------

def _crc_fn():
    from ..resilience.checkpoint import _native_crc

    return _native_crc()


class EmbeddingStore:
    """One host's leg of a row-partitioned host-memory embedding table.

    ``n_rows`` × ``dim`` rows group into blocks of ``block_rows``;
    this host materializes only the blocks it owns **and has
    touched** — an owned block reads as its deterministic
    ``(seed, block)`` init until the first update lands, so a 1e8-row
    table costs memory proportional to its hot set, not its
    vocabulary.  All hosts derive the same init, which is what makes
    the chaos e2e's bitwise-equality proof possible at all.

    The migration channel (``kv``) is the elastic KV transport; the
    checkpointed leg (``checkpoint_dir``, a shared filesystem like
    FileKV's) is written by :meth:`checkpoint` with crc32c sidecars
    and is both the corrupt-shard fallback and the dead-owner source.
    """

    #: KV key namespaces (under the elastic transport's flat keyspace)
    _SHARD = "emb/shard/"
    _ACK = "emb/ack/"

    def __init__(self, table: str, n_rows: int, dim: int, host: str,
                 members: Sequence[str], kv=None, *,
                 block_rows: int = 4096, seed: int = 0,
                 checkpoint_dir: Optional[str] = None,
                 dtype=np.float32):
        if n_rows < 1 or dim < 1:
            raise ValueError(f"EmbeddingStore needs positive dims, got "
                             f"({n_rows}, {dim})")
        self.table = str(table)
        self.n_rows = int(n_rows)
        self.dim = int(dim)
        self.host = str(host)
        # a host NOT in ``members`` is a joiner: it owns nothing under
        # the current assignment and acquires its blocks through its
        # first :meth:`repartition` (the regrow path)
        self.members: Tuple[str, ...] = tuple(sorted(set(members)))
        self.kv = kv
        self.block_rows = int(block_rows)
        self.seed = int(seed)
        self.checkpoint_dir = checkpoint_dir
        self.dtype = np.dtype(dtype)
        self.n_blocks = -(-self.n_rows // self.block_rows)
        self.version = 0
        self._lock = threading.RLock()
        self._migrating = False
        #: materialized owned blocks only (lazy capacity)
        self._blocks: Dict[int, np.ndarray] = {}
        #: owned blocks that have received updates since init
        self._touched: set = set()
        self._owners = assign_blocks(self.table, self.n_blocks,
                                     self.members)
        # counters the serving fetch / bench surface
        self.rows_migrated = 0
        self.migration_corrupt_detected = 0
        self.recovered_from_checkpoint = 0
        self.last_migration_s = 0.0

    # -- ownership -------------------------------------------------------
    def owner_of(self, block: int) -> str:
        return self._owners[int(block)]

    def owner_of_row(self, row: int) -> str:
        return self._owners[int(row) // self.block_rows]

    def owned_blocks(self) -> List[int]:
        return [b for b, o in self._owners.items() if o == self.host]

    def owns_row(self, row: int) -> bool:
        return self.owner_of_row(row) == self.host

    def _block_rows_extent(self, block: int) -> int:
        lo = block * self.block_rows
        return min(self.block_rows, self.n_rows - lo)

    # -- block materialization ------------------------------------------
    def _init_block(self, block: int) -> np.ndarray:
        """Deterministic per-(seed, block) init — every host, every
        incarnation, and the fault-free control run derive identical
        bytes, so an untouched block never needs to move at all."""
        rows = self._block_rows_extent(block)
        rng = np.random.RandomState(
            (self.seed * 1_000_003 + block) % (2 ** 31 - 1))
        scale = 1.0 / max(self.dim, 1) ** 0.5
        return (rng.standard_normal((rows, self.dim)) * scale).astype(
            self.dtype)

    def _get_block(self, block: int) -> np.ndarray:
        b = int(block)
        if self._owners[b] != self.host:
            raise KeyError(
                f"{self.table}: block {b} is owned by "
                f"{self._owners[b]!r}, not {self.host!r}")
        arr = self._blocks.get(b)
        if arr is None:
            arr = self._init_block(b)
            self._blocks[b] = arr
        return arr

    # -- reads / writes --------------------------------------------------
    def read_rows(self, rows: Sequence[int]) -> Tuple[np.ndarray, int]:
        """Gather owned ``rows`` → ``([len, dim], version)``.  The
        version stamp is taken under the same lock as the gather, so
        the caller can cache the rows tagged with the exact version
        they were consistent at.  Raises :class:`StoreMigrating` while
        a repartition holds the table."""
        with self._lock:
            if self._migrating:
                raise StoreMigrating(
                    f"{self.table}: repartition in flight on "
                    f"{self.host}")
            out = np.empty((len(rows), self.dim), dtype=self.dtype)
            for i, r in enumerate(rows):
                r = int(r)
                if not 0 <= r < self.n_rows:
                    raise IndexError(f"row {r} outside [0, "
                                     f"{self.n_rows})")
                blk = self._get_block(r // self.block_rows)
                out[i] = blk[r % self.block_rows]
            return out, self.version

    def apply_updates(self, rows: Sequence[int],
                      deltas: np.ndarray) -> None:
        """Add ``deltas[i]`` into owned row ``rows[i]`` (the PS-style
        sparse update the training loop pushes; duplicate rows
        accumulate in order)."""
        deltas = np.asarray(deltas, dtype=self.dtype)
        with self._lock:
            if self._migrating:
                raise StoreMigrating(
                    f"{self.table}: repartition in flight on "
                    f"{self.host}")
            for i, r in enumerate(rows):
                r = int(r)
                b = r // self.block_rows
                blk = self._get_block(b)
                blk[r % self.block_rows] += deltas[i]
                self._touched.add(b)

    def dense(self) -> np.ndarray:
        """The FULL table materialized (owned blocks from this leg,
        peers' untouched blocks from the shared deterministic init) —
        only sensible for tables that fit; the training↔device bridge
        for :meth:`ShardedEmbedding.attach_store`."""
        out = np.empty((self.n_rows, self.dim), dtype=self.dtype)
        with self._lock:
            for b in range(self.n_blocks):
                lo = b * self.block_rows
                n = self._block_rows_extent(b)
                if self._owners[b] == self.host:
                    out[lo:lo + n] = self._get_block(b)
                else:
                    out[lo:lo + n] = self._init_block(b)
        return out

    # -- checkpointed leg ------------------------------------------------
    def _ckpt_path(self, block: int) -> str:
        d = os.path.join(str(self.checkpoint_dir), self.table)
        return os.path.join(d, f"block_{int(block):06d}.npy")

    def checkpoint(self) -> int:
        """Write every touched owned block with a crc32c sidecar
        (atomic tmp+rename, the checkpoint layer's discipline) —
        untouched blocks are reproducible from the deterministic init
        and cost nothing.  Returns blocks written."""
        if self.checkpoint_dir is None:
            raise ValueError(f"{self.table}: no checkpoint_dir "
                             "configured")
        wrote = 0
        with self._lock:
            for b in sorted(self._touched):
                if self._owners[b] != self.host:
                    continue
                self._checkpoint_block(b)
                wrote += 1
        return wrote

    def _checkpoint_block(self, block: int) -> None:
        from ..resilience.checkpoint import stream_crc32c, write_sidecar

        path = self._ckpt_path(block)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.save(f, self._blocks[block])
        os.replace(tmp, path)
        write_sidecar(path, *stream_crc32c(path))

    def _load_checkpointed_block(self, block: int) -> np.ndarray:
        """The owner's checkpointed leg: verified load of one block
        file; a missing file means the block was never touched (the
        deterministic init IS its content); a corrupt file is
        quarantined data loss, raised loudly."""
        from ..resilience.checkpoint import verify_file

        if self.checkpoint_dir is None:
            raise MigrationCorrupt(
                f"{self.table}: block {block} unrecoverable — no "
                "checkpointed leg configured", self.table, block)
        path = self._ckpt_path(block)
        if not os.path.exists(path):
            # never updated before the last checkpoint: init is exact
            return self._init_block(block)
        if verify_file(path) is not True:
            raise MigrationCorrupt(
                f"{self.table}: checkpointed leg for block {block} "
                "failed its crc32c sidecar", self.table, block)
        with open(path, "rb") as f:
            arr = np.load(f)
        return np.ascontiguousarray(arr, dtype=self.dtype)

    # -- sealed shards over the KV transport -----------------------------
    def _seal(self, block: int) -> str:
        """One crc32c-sealed shard: checksum over the raw row bytes,
        payload base64 over the same bytes.  The in-flight corruption
        injector (``faults.corrupt_migration_shard``) flips a payload
        bit AFTER sealing — exactly what a torn write looks like to
        the importer's verify."""
        from ..resilience import faults

        arr = np.ascontiguousarray(self._get_block(block))
        raw = arr.tobytes()
        crc = _crc_fn()(raw, 0)
        data = bytearray(raw)
        flipped = faults.check_migration_fault(
            "corrupt_shard", table=self.table, block=block)
        if flipped:
            data[len(data) // 2] ^= 0x10
        return json.dumps({
            "table": self.table, "block": int(block),
            "rows": int(arr.shape[0]), "dim": int(arr.shape[1]),
            "dtype": self.dtype.name, "crc32c": f"{crc:08x}",
            "data": base64.b64encode(bytes(data)).decode("ascii"),
        })

    def _shard_key(self, version: int, block: int) -> str:
        return f"{self._SHARD}{self.table}/{int(version)}/{int(block)}"

    def _unseal(self, payload: str, block: int) -> np.ndarray:
        rec = json.loads(payload)
        raw = base64.b64decode(rec["data"])
        crc = _crc_fn()(raw, 0)
        if f"{crc:08x}" != rec["crc32c"]:
            self.migration_corrupt_detected += 1
            raise MigrationCorrupt(
                f"{self.table}: shard for block {block} failed "
                f"verify-on-import (got {crc:08x}, sealed "
                f"{rec['crc32c']})", self.table, block)
        return np.frombuffer(raw, dtype=np.dtype(rec["dtype"])).reshape(
            rec["rows"], rec["dim"]).copy()

    # -- the migration state machine -------------------------------------
    def repartition(self, new_members: Sequence[str], *,
                    dead: Sequence[str] = (),
                    fetch_timeout: float = 5.0,
                    poll: float = 0.005,
                    clock=time.monotonic,
                    sleep=time.sleep) -> dict:
        """Live shrink/regrow: derive → export → (maybe die) → import
        → ack → commit.  See docs/embeddings.md for the full state
        machine; the invariants:

        * only blocks whose owner changed move (~1/N of rows for a
          1-host delta — consistent assignment);
        * every imported byte passed a crc32c verify, either on the
          sealed shard or on the owner's checkpointed leg;
        * the version bump (and with it every hot-row cache
          invalidation) happens only after every import verified.
        """
        from ..resilience import faults

        t0 = clock()
        new_ms = tuple(sorted(set(new_members)))
        if self.host not in new_ms:
            raise ValueError(f"{self.host!r} repartitioning itself out "
                             f"of {new_ms}")
        old_owners = self._owners
        new_owners = assign_blocks(self.table, self.n_blocks, new_ms)
        # every member must derive the SAME new version or shard keys
        # miss.  The version is a property of the TRANSITION, not the
        # committer: each ack records its target member set, so a leg
        # adopts the version a peer already committed for this same
        # member set (first committer defines it) and otherwise steps
        # past every ack for other transitions — a joiner constructed
        # at version 0 converges with survivors mid-stream, without an
        # ownership directory.  Adoption is monotonicity-guarded
        # (never below our own next version) so a revisited member set
        # can never rewind the table version.
        new_version = self.version + 1
        if self.kv is not None:
            prefix = f"{self._ACK}{self.table}/"
            same = None
            for key in self.kv.keys(prefix):
                try:
                    acked = int(key[len(prefix):].split("/", 1)[0])
                except ValueError:
                    continue
                try:
                    ms = tuple(sorted(json.loads(
                        self.kv.get(key) or "{}").get("members", ())))
                except (ValueError, AttributeError):
                    ms = ()
                if ms == new_ms:
                    same = acked if same is None else max(same, acked)
                else:
                    new_version = max(new_version, acked + 1)
            if same is not None and same >= self.version + 1:
                new_version = same
        dead = set(dead)

        with self._lock:
            self._migrating = True
        try:
            # -- export: seal every block leaving this host.  Each
            # leaving block is checkpointed FIRST (touched blocks
            # only; untouched ones are reproducible from init), so the
            # checkpointed leg a corrupt-shard re-request falls back
            # to is bitwise-current, not stale ----------------------------
            exported = 0
            for b in range(self.n_blocks):
                if (old_owners[b] == self.host
                        and new_owners[b] != self.host):
                    if (self.checkpoint_dir is not None
                            and b in self._touched):
                        self._checkpoint_block(b)
                    self.kv.put(self._shard_key(new_version, b),
                                self._seal(b))
                    exported += 1

            # between ownership re-derivation and import-ack: the
            # window kill_host_mid_repartition targets — a host dying
            # here has exported nothing durable and acked nothing, so
            # survivors re-derive without it and source its blocks
            # from its checkpointed leg
            faults.check_migration_fault("kill", host=self.host)

            # -- import: every block arriving at this host -------------
            imported = moved_rows = 0
            for b in range(self.n_blocks):
                if (new_owners[b] != self.host
                        or old_owners[b] == self.host):
                    continue
                src = old_owners[b]
                arr = self._import_block(
                    b, src, new_version,
                    src_dead=src in dead or src not in new_ms,
                    fetch_timeout=fetch_timeout, poll=poll,
                    clock=clock, sleep=sleep)
                self._blocks[b] = arr
                self._touched.add(b)
                imported += 1
                moved_rows += arr.shape[0]

            # -- ack, then commit --------------------------------------
            if self.kv is not None:
                self.kv.put(
                    f"{self._ACK}{self.table}/{new_version}/"
                    f"{self.host}",
                    json.dumps({"members": list(new_ms)}))
            with self._lock:
                for b in range(self.n_blocks):
                    if (new_owners[b] != self.host
                            and b in self._blocks):
                        del self._blocks[b]
                        self._touched.discard(b)
                self._owners = new_owners
                self.members = new_ms
                self.version = new_version
                self.rows_migrated += moved_rows
        finally:
            with self._lock:
                self._migrating = False
        self.last_migration_s = clock() - t0
        return {
            "version": new_version,
            "exported_blocks": exported,
            "imported_blocks": imported,
            "moved_rows": moved_rows,
            "recovered_from_checkpoint": self.recovered_from_checkpoint,
            "wall_s": self.last_migration_s,
        }

    def _import_block(self, block: int, src: str, version: int, *,
                      src_dead: bool, fetch_timeout: float,
                      poll: float, clock, sleep) -> np.ndarray:
        """One block's verified import: sealed shard off the KV
        transport first; on corruption (typed
        :class:`MigrationCorrupt`) or a dead/silent source, the
        owner's checkpointed leg."""
        key = self._shard_key(version, block)
        deadline = clock() + float(fetch_timeout)
        payload = None
        if self.kv is not None and not src_dead:
            while True:
                payload = self.kv.get(key)
                if payload is not None or clock() >= deadline:
                    break
                sleep(poll)
        if payload is None:
            # dead or silent old owner: its checkpointed leg is the
            # only verified source left
            self.recovered_from_checkpoint += 1
            return self._load_checkpointed_block(block)
        try:
            return self._unseal(payload, block)
        except MigrationCorrupt:
            # torn/corrupt in flight → re-request from the owner's
            # checkpointed leg (verified); if THAT fails the raise
            # from _load_checkpointed_block propagates — never
            # zero-filled
            self.recovered_from_checkpoint += 1
            return self._load_checkpointed_block(block)

    # -- proof + introspection -------------------------------------------
    def checksum(self) -> str:
        """crc32c over this host's OWNED rows in block order — combine
        legs with :func:`table_checksum` for the whole-table proof."""
        crc_fn = _crc_fn()
        crc = 0
        with self._lock:
            for b in sorted(self.owned_blocks()):
                crc = crc_fn(
                    np.ascontiguousarray(self._get_block(b)).tobytes(),
                    crc)
        return f"{crc:08x}"

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "table": self.table,
                "host": self.host,
                "version": self.version,
                "members": list(self.members),
                "n_rows": self.n_rows,
                "dim": self.dim,
                "block_rows": self.block_rows,
                "owned_blocks": len(self.owned_blocks()),
                "materialized_blocks": len(self._blocks),
                "rows_migrated": self.rows_migrated,
                "migration_corrupt_detected":
                    self.migration_corrupt_detected,
                "recovered_from_checkpoint":
                    self.recovered_from_checkpoint,
                "last_migration_s": self.last_migration_s,
            }


def table_checksum(stores: Sequence[EmbeddingStore]) -> str:
    """The whole table's crc32c across one incarnation's legs, walked
    in block order regardless of which leg owns which block — equal
    strings mean bitwise-equal table contents, which is the proof the
    chaos e2e pins across the membership boundary (checksum_tree's
    discipline applied to the partitioned table)."""
    if not stores:
        raise ValueError("table_checksum of no legs")
    by_host = {s.host: s for s in stores}
    ref = stores[0]
    crc_fn = _crc_fn()
    crc = 0
    for b in range(ref.n_blocks):
        owner = ref.owner_of(b)
        leg = by_host.get(owner)
        if leg is None:
            raise ValueError(f"no leg for owner {owner!r} of block "
                             f"{b}")
        with leg._lock:
            arr = np.ascontiguousarray(leg._get_block(b))
        crc = crc_fn(arr.tobytes(), crc)
    return f"{crc:08x}"
