"""Normalization layers (reference BatchNormalization.scala:50,
SpatialBatchNormalization, SpatialCrossMapLRN, Normalize, L1Penalty,
Spatial{Subtractive,Divisive,Contrastive}Normalization).

Running statistics live in the module's *buffer* pytree and are threaded
functionally through ``apply_fn`` — the TPU answer to the reference's
mutable ``runningMean``/``runningVar`` (BatchNormalization.scala:50,
``copyStatus``).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .initialization import ONE_D, Ones, RandomUniform, Zeros
from .module import TensorModule


class BatchNormalization(TensorModule):
    """BN over (N, D) — feature dim 2 (reference nn/BatchNormalization.scala:50)."""

    _feature_axis = 1  # axis of C in the input

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True):
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.reset()

    def reset(self):
        if self.affine:
            w_init = self._init_methods.get("weight", (RandomUniform(0.0, 1.0), None))[0]
            b_init = self._init_methods.get("bias", (Zeros(), None))[0]
            self._register_param("weight", w_init.init((self.n_output,), ONE_D))
            self._register_param("bias", b_init.init((self.n_output,), ONE_D))
        self._register_buffer("running_mean", jnp.zeros((self.n_output,)))
        self._register_buffer("running_var", jnp.ones((self.n_output,)))
        return self

    def _reduce_axes(self, x):
        return tuple(i for i in range(x.ndim) if i != self._feature_axis)

    def _bshape(self, x):
        shape = [1] * x.ndim
        shape[self._feature_axis] = self.n_output
        return tuple(shape)

    def _apply(self, params, buffers, x, training, rng):
        axes = self._reduce_axes(x)
        bshape = self._bshape(x)
        if training:
            mean = jnp.mean(x, axis=axes)
            var = jnp.mean(jnp.square(x - mean.reshape(bshape)), axis=axes)
            n = int(np.prod([x.shape[i] for i in axes]))
            unbiased = var * n / max(n - 1, 1)
            new_buffers = {
                "running_mean": (1 - self.momentum) * buffers["running_mean"]
                + self.momentum * mean,
                "running_var": (1 - self.momentum) * buffers["running_var"]
                + self.momentum * unbiased,
            }
        else:
            mean, var = buffers["running_mean"], buffers["running_var"]
            new_buffers = buffers
        inv = lax.rsqrt(var + self.eps).reshape(bshape)
        y = (x - mean.reshape(bshape)) * inv
        if self.affine:
            y = y * params["weight"].reshape(bshape) + params["bias"].reshape(bshape)
        return y, new_buffers


class SpatialBatchNormalization(BatchNormalization):
    """BN over NCHW, per-channel (reference nn/SpatialBatchNormalization.scala)."""


class SpatialCrossMapLRN(TensorModule):
    """AlexNet-style local response normalization across channels
    (reference nn/SpatialCrossMapLRN.scala)."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def _apply(self, params, buffers, x, training, rng):
        sq = jnp.square(x)  # (N, C, H, W)
        half = (self.size - 1) // 2
        # sum over channel window via reduce_window on the C axis
        sums = lax.reduce_window(
            sq, 0.0, lax.add, (1, self.size, 1, 1), (1, 1, 1, 1),
            [(0, 0), (half, self.size - 1 - half), (0, 0), (0, 0)])
        denom = jnp.power(self.k + sums * self.alpha / self.size, self.beta)
        return x / denom, buffers


class Normalize(TensorModule):
    """Lp-normalize rows (reference nn/Normalize.scala)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10):
        super().__init__()
        self.p, self.eps = p, eps

    def _apply(self, params, buffers, x, training, rng):
        if self.p == float("inf"):
            norm = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        else:
            norm = jnp.sum(jnp.abs(x) ** self.p, axis=-1, keepdims=True) ** (1.0 / self.p)
        return x / (norm + self.eps), buffers


class L1Penalty(TensorModule):
    """Identity forward that adds an L1 term to the loss gradient
    (reference nn/L1Penalty.scala) — custom_vjp adds sign(x)*scale to grads."""

    def __init__(self, l1weight: float, size_average: bool = False,
                 provide_output: bool = True):
        super().__init__()
        self.l1weight = l1weight
        self.size_average = size_average
        self.loss = 0.0

    def _apply(self, params, buffers, x, training, rng):
        if not training:
            return x, buffers
        l1w, avg = self.l1weight, self.size_average

        @jax.custom_vjp
        def pen(v):
            return v

        def bwd(res, g):
            (v,) = res
            scale = l1w / v.size if avg else l1w
            return (g + scale * jnp.sign(v),)

        pen.defvjp(lambda v: (v, (v,)), bwd)
        return pen(x), buffers


def _gaussian_kernel_2d(kernel):
    k = np.asarray(kernel, np.float32)
    if k.ndim == 1:
        k = np.outer(k, k)
    return k / k.sum()


class SpatialSubtractiveNormalization(TensorModule):
    """Subtract local weighted mean (reference
    nn/SpatialSubtractiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None):
        super().__init__()
        self.n_input_plane = n_input_plane
        if kernel is None:
            kernel = np.ones((9, 9), np.float32)
        self.kernel = _gaussian_kernel_2d(np.asarray(kernel))

    def _local_mean(self, x):
        kh, kw = self.kernel.shape
        # kernel in the INPUT's dtype (lax conv requires matching
        # dtypes; f64 inputs from the gradient checker included)
        k = jnp.asarray(self.kernel, x.dtype)
        w = k.reshape(1, 1, kh, kw)
        w = jnp.tile(w, (1, x.shape[1], 1, 1)) / x.shape[1]
        pad = [(kh // 2, (kh - 1) // 2), (kw // 2, (kw - 1) // 2)]
        mean = lax.conv_general_dilated(
            x, w, (1, 1), pad, dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # edge coefficient correction: convolve a ones image
        ones = jnp.ones_like(x[:1, :1])
        coef = lax.conv_general_dilated(
            ones, k.reshape(1, 1, kh, kw), (1, 1), pad,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return mean / coef

    def _apply(self, params, buffers, x, training, rng):
        squeeze = False
        if x.ndim == 3:
            x = x[None]
            squeeze = True
        y = x - self._local_mean(x)
        if squeeze:
            y = y[0]
        return y, buffers


class SpatialDivisiveNormalization(TensorModule):
    """Divide by local weighted std (reference
    nn/SpatialDivisiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.threshold, self.thresval = threshold, thresval

    def _apply(self, params, buffers, x, training, rng):
        squeeze = False
        if x.ndim == 3:
            x = x[None]
            squeeze = True
        local_sq = self.sub._local_mean(jnp.square(x))
        std = jnp.sqrt(jnp.maximum(local_sq, 0.0))
        mean_std = jnp.mean(std, axis=(1, 2, 3), keepdims=True)
        adj = jnp.maximum(std, mean_std)
        adj = jnp.where(adj < self.threshold, self.thresval, adj)
        y = x / adj
        if squeeze:
            y = y[0]
        return y, buffers


class SpatialContrastiveNormalization(TensorModule):
    """Subtractive then divisive (reference
    nn/SpatialContrastiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel,
                                                threshold, thresval)

    def _apply(self, params, buffers, x, training, rng):
        y, _ = self.sub._apply({}, {}, x, training, rng)
        y, _ = self.div._apply({}, {}, y, training, rng)
        return y, buffers


class LayerNorm(TensorModule):
    """Layer normalization over the last dimension.

    No reference counterpart (the reference predates transformers) —
    required by the TPU rebuild's attention/transformer stack.  Unlike
    BatchNormalization it keeps no running statistics, so it is fully
    shard-oblivious: under sequence/tensor parallelism each device
    normalises its local activations independently.
    """

    def __init__(self, n_output: int, eps: float = 1e-5, affine: bool = True):
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.affine = affine
        self.reset()

    def reset(self):
        if self.affine:
            w_init = self._init_methods.get("weight", (Ones(), None))[0]
            b_init = self._init_methods.get("bias", (Zeros(), None))[0]
            self._register_param("weight", w_init.init((self.n_output,), ONE_D))
            self._register_param("bias", b_init.init((self.n_output,), ONE_D))
        return self

    def _apply(self, params, buffers, x, training, rng):
        if self.affine:
            # Pallas single-pass kernel on TPU, jnp fallback elsewhere
            from ..ops import fused_layer_norm

            return fused_layer_norm(x, params["weight"], params["bias"],
                                    self.eps), buffers
        mean = x.mean(axis=-1, keepdims=True)
        var = ((x - mean) ** 2).mean(axis=-1, keepdims=True)
        return (x - mean) * lax.rsqrt(var + self.eps), buffers


class RMSNorm(TensorModule):
    """Root-mean-square normalization over the last dimension (the
    Llama-family norm): ``x * rsqrt(mean(x²) + eps) * weight`` — no
    mean subtraction, no bias.

    No reference counterpart (the reference predates transformers).
    Matches the HF Llama convention for low-precision inputs: the
    variance is computed in at-LEAST float32 (bf16/f16 upcast; float64
    keeps float64 — the gradient-sweep oracles need the precision),
    and the normalized activations cast back to the input dtype BEFORE
    the weight multiply."""

    def __init__(self, n_output: int, eps: float = 1e-6):
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.reset()

    def reset(self):
        w_init = self._init_methods.get("weight", (Ones(), None))[0]
        self._register_param("weight", w_init.init((self.n_output,),
                                                   ONE_D))
        return self

    def _apply(self, params, buffers, x, training, rng):
        # at-LEAST float32 statistics (bf16 upcasts, f64 oracles keep
        # their precision) — the HF convention for low-precision inputs
        xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        normed = (xf * lax.rsqrt(var + self.eps)).astype(x.dtype)
        return normed * params["weight"].astype(x.dtype), buffers


class ImageNormalize(TensorModule):
    """Device-side image normalization + layout move.

    Pairs with ``MTLabeledImgToBatch(..., device_normalize=True)``: the
    host batch path becomes a pure uint8 memcpy (stack only) and THIS
    module — placed first in the model — does cast → (x-mean)/std →
    NHWC→NCHW on the accelerator, where XLA fuses all of it into the
    stem conv's input read.  The normalize that cost the reference a
    host thread pool (dataset/image/MTLabeledBGRImgToBatch.scala:46)
    costs ~nothing on-device; on a starved host (1 core feeding a
    2000+ img/s chip) this is the difference between infeed-bound and
    compute-bound (docs/PERF.md round-4 infeed rehearsal).

    ``from_layout``: "NHWC" (the memcpy batch layout) transposes to the
    framework's NCHW; "NCHW" normalizes in place.
    """

    def __init__(self, mean, std, from_layout: str = "NHWC"):
        super().__init__()
        if from_layout not in ("NHWC", "NCHW"):
            raise ValueError(f"from_layout {from_layout!r}")
        self.mean = tuple(float(m) for m in np.atleast_1d(mean))
        self.std = tuple(float(s) for s in np.atleast_1d(std))
        self.from_layout = from_layout

    def _apply(self, params, buffers, x, training, rng):
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        # uint8 infeed casts up to f32; float inputs keep their dtype
        # (f64 under the gradient checker must not quantize)
        dt = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.float32
        mean = jnp.asarray(self.mean, dt)
        std = jnp.asarray(self.std, dt)
        x = x.astype(dt)
        if self.from_layout == "NHWC":
            x = (x - mean) / std          # broadcast over trailing C
            x = jnp.transpose(x, (0, 3, 1, 2))
        else:
            x = (x - mean[:, None, None]) / std[:, None, None]
        if squeeze:
            x = x[0]
        return x, buffers
