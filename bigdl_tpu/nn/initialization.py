"""Initialization methods (reference nn/InitializationMethod.scala).

Host-side numpy draws from the seeded MT generator, converted to jax
arrays — init happens once at construction, so it stays off-device.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..utils.rng import RNG


class VariableFormat:
    """Describes which dims are fan-in/fan-out (reference VariableFormat)."""

    def __init__(self, name="Default"):
        self.name = name

    def fans(self, shape):
        if self.name == "ONE_D":
            return shape[0], shape[0]
        if self.name == "IN_OUT":       # (out, in) linear weight
            fan_out, fan_in = shape[0], int(np.prod(shape[1:]))
            return fan_in, fan_out
        if self.name == "OUT_IN":
            fan_in, fan_out = shape[0], int(np.prod(shape[1:]))
            return fan_out, fan_in
        if self.name == "OUT_IN_KW_KH":  # conv weight (out, in, kh, kw)
            receptive = int(np.prod(shape[2:]))
            return shape[1] * receptive, shape[0] * receptive
        if self.name == "IN_OUT_KW_KH":
            receptive = int(np.prod(shape[2:]))
            return shape[0] * receptive, shape[1] * receptive
        n = int(np.prod(shape))
        d0 = shape[0] if shape else 1
        return n // d0 if d0 else 1, d0


ONE_D = VariableFormat("ONE_D")
IN_OUT = VariableFormat("IN_OUT")
OUT_IN = VariableFormat("OUT_IN")
OUT_IN_KW_KH = VariableFormat("OUT_IN_KW_KH")
IN_OUT_KW_KH = VariableFormat("IN_OUT_KW_KH")
DEFAULT_FORMAT = VariableFormat("Default")


class InitializationMethod:
    def init(self, shape, fmt: VariableFormat = DEFAULT_FORMAT):
        raise NotImplementedError


class Zeros(InitializationMethod):
    def init(self, shape, fmt=DEFAULT_FORMAT):
        return jnp.zeros(shape, jnp.float32)


class Ones(InitializationMethod):
    def init(self, shape, fmt=DEFAULT_FORMAT):
        return jnp.ones(shape, jnp.float32)


class ConstInitMethod(InitializationMethod):
    def __init__(self, value):
        self.value = value

    def init(self, shape, fmt=DEFAULT_FORMAT):
        return jnp.full(shape, self.value, jnp.float32)


class RandomUniform(InitializationMethod):
    """U(lower, upper); no-arg variant scales by 1/sqrt(fan_in) like the
    reference's default torch init."""

    def __init__(self, lower=None, upper=None):
        self.lower, self.upper = lower, upper

    def init(self, shape, fmt=DEFAULT_FORMAT):
        if self.lower is None:
            fan_in, _ = fmt.fans(shape)
            stdv = 1.0 / math.sqrt(max(fan_in, 1))
            lo, hi = -stdv, stdv
        else:
            lo, hi = self.lower, self.upper
        return jnp.asarray(RNG().uniform(lo, hi, shape), jnp.float32)


class RandomNormal(InitializationMethod):
    def __init__(self, mean=0.0, stdv=1.0):
        self.mean, self.stdv = mean, stdv

    def init(self, shape, fmt=DEFAULT_FORMAT):
        return jnp.asarray(RNG().normal(self.mean, self.stdv, shape), jnp.float32)


class Xavier(InitializationMethod):
    """Glorot uniform (reference InitializationMethod.scala Xavier)."""

    def init(self, shape, fmt=DEFAULT_FORMAT):
        fan_in, fan_out = fmt.fans(shape)
        stdv = math.sqrt(6.0 / (fan_in + fan_out))
        return jnp.asarray(RNG().uniform(-stdv, stdv, shape), jnp.float32)


class MsraFiller(InitializationMethod):
    """He init (reference MsraFiller)."""

    def __init__(self, variance_norm_average=True):
        self.avg = variance_norm_average

    def init(self, shape, fmt=DEFAULT_FORMAT):
        fan_in, fan_out = fmt.fans(shape)
        n = (fan_in + fan_out) / 2.0 if self.avg else fan_in
        std = math.sqrt(2.0 / max(n, 1))
        return jnp.asarray(RNG().normal(0.0, std, shape), jnp.float32)


class BilinearFiller(InitializationMethod):
    """Bilinear upsampling kernel for deconv (reference BilinearFiller)."""

    def init(self, shape, fmt=DEFAULT_FORMAT):
        assert len(shape) >= 2
        kh, kw = shape[-2], shape[-1]
        f = math.ceil(kw / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        w = np.zeros(shape, np.float32)
        flat = w.reshape(-1, kh * kw)
        for i in range(kh * kw):
            x, y = i % kw, i // kw
            val = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            flat[:, i] = val
        return jnp.asarray(flat.reshape(shape))
