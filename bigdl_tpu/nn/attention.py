"""Attention layers — the TPU rebuild's first-class long-context stack.

The reference has no attention (it predates transformers; SURVEY §5.7),
so these layers have no reference counterpart to cite — they exist
because the TPU framework makes long-context and sequence parallelism
first-class.  The compute lives in ``parallel/ring_attention.py``; these
modules wrap it in the standard layer protocol.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..parallel.ring_attention import (attention, blockwise_attention,
                                       ring_attention, ulysses_attention)
from .initialization import IN_OUT, ONE_D, Xavier, Zeros
from .module import TensorModule

SEQ_STRATEGIES = ("dense", "flash", "block", "ring", "ulysses",
                  "blocksparse")

#: block-sparse mask patterns the layer can build (ops/block_sparse.py)
SPARSE_PATTERNS = ("sliding", "strided")


def rope_rotate(x, pos, theta: float = 10000.0):
    """Rotary position embedding (HF Llama's rotate-half convention)
    over ``x`` [B, H, T, D] at absolute positions ``pos`` [T]."""
    D = x.shape[-1]
    # like RMSNorm: float64 oracles keep their precision, low-precision
    # inputs still get at least float32 tables
    ct = jnp.promote_types(x.dtype, jnp.float32)
    inv = 1.0 / (theta ** (jnp.arange(0, D, 2, dtype=ct) / D))
    ang = pos.astype(ct)[:, None] * inv[None, :]            # [T, D/2]
    cos = jnp.concatenate([jnp.cos(ang), jnp.cos(ang)], -1)  # [T, D]
    sin = jnp.concatenate([jnp.sin(ang), jnp.sin(ang)], -1)
    x1, x2 = x[..., :D // 2], x[..., D // 2:]
    rot = jnp.concatenate([-x2, x1], -1)
    return (x * cos[None, None].astype(x.dtype)
            + rot * sin[None, None].astype(x.dtype))


class MultiHeadAttention(TensorModule):
    """Multi-head self-attention over [batch, seq, embed].

    ``seq_strategy`` picks how the sequence dimension is handled:
      * ``"dense"``  — one [T, T] matmul (short sequences)
      * ``"flash"``  — Pallas online-softmax kernel (ops/flash_attention;
        jnp fallback off-TPU), scores never materialized
      * ``"block"``  — single-device flash-style blockwise attention
      * ``"ring"``   — ring context parallelism; REQUIRES running inside
        shard_map with the sequence sharded over ``seq_axis``
      * ``"ulysses"`` — all-to-all sequence parallelism (same requirement)
      * ``"blocksparse"`` — BLaST block-sparse Pallas kernel
        (ops/block_sparse.py): only the block pairs a static mask
        allows are ever read or multiplied.  The mask is built from
        ``sparse_pattern`` at ``sparse_block`` granularity (default
        ``block_size``): ``"sliding"`` = ``sparse_window`` blocks back
        plus ``sparse_globals`` anchor blocks (Longformer-style);
        ``"strided"`` = own block + every ``sparse_stride``-th block.
        Masks are cached per (T, S); off-TPU the identical math runs
        densely with the mask applied elementwise.
    """

    def __init__(self, embed_dim: int, num_heads: int,
                 causal: bool = False, with_bias: bool = True,
                 seq_strategy: str = "dense", seq_axis: str = "seq",
                 block_size: int = 512,
                 num_kv_heads: "int | None" = None,
                 rope: bool = False, rope_theta: float = 10000.0,
                 sparse_pattern: str = "sliding",
                 sparse_window: int = 2, sparse_globals: int = 1,
                 sparse_stride: int = 4,
                 sparse_block: "int | None" = None):
        super().__init__()
        assert embed_dim % num_heads == 0, "embed_dim % num_heads != 0"
        if seq_strategy not in SEQ_STRATEGIES:
            raise ValueError(f"seq_strategy {seq_strategy!r} not in "
                             f"{SEQ_STRATEGIES}")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.causal = causal
        self.with_bias = with_bias
        self.seq_strategy = seq_strategy
        self.seq_axis = seq_axis
        self.block_size = block_size
        # grouped-query attention: kv projections carry num_kv_heads
        # heads (each shared by num_heads/num_kv_heads query groups)
        self.num_kv_heads = int(num_kv_heads or num_heads)
        if self.num_heads % self.num_kv_heads:
            raise ValueError(
                f"num_heads {num_heads} not divisible by num_kv_heads "
                f"{self.num_kv_heads}")
        if sparse_pattern not in SPARSE_PATTERNS:
            raise ValueError(f"sparse_pattern {sparse_pattern!r} not in "
                             f"{SPARSE_PATTERNS}")
        self.sparse_pattern = sparse_pattern
        self.sparse_window = int(sparse_window)
        self.sparse_globals = int(sparse_globals)
        self.sparse_stride = int(sparse_stride)
        self.sparse_block = sparse_block
        self._sparse_masks = {}   # (T, S) -> BlockMask (static, hashable)
        self.rope = bool(rope)
        self.rope_theta = float(rope_theta)
        if self.rope and seq_strategy in ("ring", "ulysses"):
            # the rotation needs GLOBAL positions, which the module
            # cannot know inside a seq-sharded shard_map region
            raise ValueError(
                "rope composes with dense/flash/block attention; "
                "ring/ulysses sequence parallelism would rotate at "
                "shard-local positions")
        self.reset()

    def reset(self):
        w_init = self._init_methods.get("weight", (Xavier(), None))[0]
        b_init = self._init_methods.get("bias", (Zeros(), None))[0]
        E = self.embed_dim
        kv = self.num_kv_heads * self.head_dim
        for name, rows in (("wq", E), ("wk", kv), ("wv", kv), ("wo", E)):
            self._register_param(name, w_init.init((rows, E), IN_OUT))
        if self.with_bias:
            for name, n in (("bq", E), ("bk", kv), ("bv", kv), ("bo", E)):
                self._register_param(name, b_init.init((n,), ONE_D))
        return self

    def _split(self, x, heads=None):
        B, T, _ = x.shape
        h = heads or self.num_heads
        return x.reshape(B, T, h, self.head_dim).transpose(0, 2, 1, 3)

    def block_mask(self, T, S):
        """The layer's static :class:`~bigdl_tpu.ops.BlockMask` for a
        (T, S) attention — built once per shape and cached (hashable,
        so jit never retraces on reuse).  Public so benches and the
        perf accountant can derive the executed-work correction from
        the EXACT mask the layer runs."""
        key = (int(T), int(S))
        if key not in self._sparse_masks:
            from ..ops.block_sparse import (pick_block_divisor,
                                            sliding_window_mask,
                                            strided_mask)

            target = self.sparse_block or self.block_size
            b = pick_block_divisor(T, S, target)
            nq, nk = T // b, S // b
            if self.sparse_pattern == "strided":
                m = strided_mask(nq, nk, self.sparse_stride,
                                 causal=self.causal, block_q=b,
                                 block_k=b)
            else:
                m = sliding_window_mask(nq, nk, self.sparse_window,
                                        n_global=self.sparse_globals,
                                        causal=self.causal, block_q=b,
                                        block_k=b)
            self._sparse_masks[key] = m
        return self._sparse_masks[key]

    def _attend(self, q, k, v):
        if self.seq_strategy == "blocksparse":
            from ..ops.block_sparse import block_sparse_attention

            return block_sparse_attention(
                q, k, v, self.block_mask(q.shape[2], k.shape[2]),
                causal=self.causal)
        if self.seq_strategy == "ring":
            return ring_attention(q, k, v, axis_name=self.seq_axis,
                                  causal=self.causal)
        if self.seq_strategy == "ulysses":
            return ulysses_attention(q, k, v, axis_name=self.seq_axis,
                                     causal=self.causal,
                                     block_size=self.block_size)
        if self.seq_strategy == "block":
            return blockwise_attention(q, k, v, block_size=self.block_size,
                                       causal=self.causal)
        if self.seq_strategy == "flash":
            from ..ops import flash_attention

            return flash_attention(q, k, v, causal=self.causal)
        return attention(q, k, v, causal=self.causal)

    def _apply(self, params, buffers, x, training, rng):
        def proj(x, w, b):
            y = jnp.dot(x, w.T)
            return y + params[b] if self.with_bias else y

        q = self._split(proj(x, params["wq"], "bq"))
        k = self._split(proj(x, params["wk"], "bk"), self.num_kv_heads)
        v = self._split(proj(x, params["wv"], "bv"), self.num_kv_heads)
        if self.rope:
            pos = jnp.arange(q.shape[2])
            q = rope_rotate(q, pos, self.rope_theta)
            k = rope_rotate(k, pos, self.rope_theta)
        if self.num_kv_heads != self.num_heads:
            group = self.num_heads // self.num_kv_heads
            k = jnp.repeat(k, group, axis=1)
            v = jnp.repeat(v, group, axis=1)
        o = self._attend(q, k, v)
        B, H, T, D = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(B, T, H * D)
        return proj(o, params["wo"], "bo"), buffers
