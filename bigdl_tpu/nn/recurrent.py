"""Recurrent stack (reference nn/Cell.scala:43, Recurrent.scala:32,
RnnCell, LSTM.scala:50, LSTMPeephole, GRU.scala:54, ConvLSTMPeephole,
BiRecurrent, TimeDistributed).

TPU-first redesign: the reference clones the cell per timestep with
shared weight storage (Recurrent.scala:88-125); here the time dimension
is a ``lax.scan`` over ONE cell apply — weight sharing is the scan
carrying the same params, and XLA unrolls/pipelines it.  The reference's
``preTopology`` trick (hoisting the time-independent input projection
out of the per-step loop, Cell.scala:64-75) is preserved: cells expose
``pre_apply`` which runs batched over the whole sequence as one big MXU
matmul before the scan.

Layout: batch-first ``(N, T, F)`` like the reference's batch mode.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.table import Table
from .initialization import ONE_D, RandomUniform
from .module import AbstractModule, TensorModule


class Cell(AbstractModule):
    """Recurrent cell protocol (reference nn/Cell.scala:43).

    Subclasses implement:
      - ``init_hidden(batch_size)`` → hidden pytree
      - ``pre_apply(params, x)``    → time-independent projection of the
        whole (N, T, F) sequence (preTopology); default identity
      - ``cell_apply(params, pre_t, hidden)`` → (out_t, new_hidden)
    """

    def __init__(self):
        super().__init__()

    def init_hidden(self, batch_size: int):
        raise NotImplementedError

    def pre_apply(self, params, x):
        return x

    def cell_apply(self, params, pre_t, hidden):
        raise NotImplementedError

    def _apply(self, params, buffers, inp, training, rng):
        """Single-step eager use: input Table(x_t, hidden) → Table(out, hidden)."""
        x_t, hidden = inp[1], inp[2]
        pre_t = self.pre_apply(params, x_t[:, None, :])[:, 0]
        out, new_hidden = self.cell_apply(params, pre_t, hidden)
        return Table(out, new_hidden), buffers


def _uniform_init(module, name, shape, stdv):
    init = module._init_methods.get(name, (RandomUniform(-stdv, stdv), None))[0]
    module._register_param(name, init.init(shape, ONE_D))


class RnnCell(Cell):
    """Vanilla RNN: h' = act(W x + U h + b) (reference nn/RnnCell.scala)."""

    def __init__(self, input_size: int, hidden_size: int, activation=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation if activation is not None else jnp.tanh
        self.reset()

    def reset(self):
        stdv = 1.0 / math.sqrt(self.hidden_size)
        _uniform_init(self, "i2h", (self.hidden_size, self.input_size), stdv)
        _uniform_init(self, "h2h", (self.hidden_size, self.hidden_size), stdv)
        _uniform_init(self, "bias", (self.hidden_size,), stdv)
        return self

    def init_hidden(self, batch_size):
        return jnp.zeros((batch_size, self.hidden_size))

    def pre_apply(self, params, x):
        # (N, T, F) @ (F, H) — one MXU matmul for the whole sequence
        return jnp.einsum("ntf,hf->nth", x, params["i2h"]) + params["bias"]

    def cell_apply(self, params, pre_t, h):
        act = self.activation
        h_new = act(pre_t + jnp.dot(h, params["h2h"].T))
        return h_new, h_new


class LSTM(Cell):
    """LSTM cell (reference nn/LSTM.scala:50).  Gate order i, f, z(g), o."""

    def __init__(self, input_size: int, hidden_size: int,
                 p: float = 0.0, w_regularizer=None, u_regularizer=None,
                 b_regularizer=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.p = p
        self.reset()

    def reset(self):
        H, F = self.hidden_size, self.input_size
        stdv = 1.0 / math.sqrt(H)
        _uniform_init(self, "i2h", (4 * H, F), stdv)
        _uniform_init(self, "h2h", (4 * H, H), stdv)
        _uniform_init(self, "bias", (4 * H,), stdv)
        return self

    def init_hidden(self, batch_size):
        H = self.hidden_size
        return Table(jnp.zeros((batch_size, H)), jnp.zeros((batch_size, H)))

    def pre_apply(self, params, x):
        return jnp.einsum("ntf,gf->ntg", x, params["i2h"]) + params["bias"]

    def cell_apply(self, params, pre_t, hidden):
        h, c = hidden[1], hidden[2]
        H = self.hidden_size
        gates = pre_t + jnp.dot(h, params["h2h"].T)
        i = jax.nn.sigmoid(gates[:, 0:H])
        f = jax.nn.sigmoid(gates[:, H:2 * H])
        z = jnp.tanh(gates[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(gates[:, 3 * H:4 * H])
        c_new = f * c + i * z
        h_new = o * jnp.tanh(c_new)
        return h_new, Table(h_new, c_new)


class LSTMPeephole(Cell):
    """LSTM with peephole connections (reference nn/LSTMPeephole.scala)."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.reset()

    def reset(self):
        H, F = self.hidden_size, self.input_size
        stdv = 1.0 / math.sqrt(H)
        _uniform_init(self, "i2h", (4 * H, F), stdv)
        _uniform_init(self, "h2h", (4 * H, H), stdv)
        _uniform_init(self, "bias", (4 * H,), stdv)
        _uniform_init(self, "peep_i", (H,), stdv)
        _uniform_init(self, "peep_f", (H,), stdv)
        _uniform_init(self, "peep_o", (H,), stdv)
        return self

    def init_hidden(self, batch_size):
        H = self.hidden_size
        return Table(jnp.zeros((batch_size, H)), jnp.zeros((batch_size, H)))

    def pre_apply(self, params, x):
        return jnp.einsum("ntf,gf->ntg", x, params["i2h"]) + params["bias"]

    def cell_apply(self, params, pre_t, hidden):
        h, c = hidden[1], hidden[2]
        H = self.hidden_size
        gates = pre_t + jnp.dot(h, params["h2h"].T)
        i = jax.nn.sigmoid(gates[:, 0:H] + params["peep_i"] * c)
        f = jax.nn.sigmoid(gates[:, H:2 * H] + params["peep_f"] * c)
        z = jnp.tanh(gates[:, 2 * H:3 * H])
        c_new = f * c + i * z
        o = jax.nn.sigmoid(gates[:, 3 * H:4 * H] + params["peep_o"] * c_new)
        h_new = o * jnp.tanh(c_new)
        return h_new, Table(h_new, c_new)


class GRU(Cell):
    """GRU cell (reference nn/GRU.scala:54).  Gate order r, z, n."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.reset()

    def reset(self):
        H, F = self.hidden_size, self.input_size
        stdv = 1.0 / math.sqrt(H)
        _uniform_init(self, "i2h", (3 * H, F), stdv)
        _uniform_init(self, "h2h", (3 * H, H), stdv)
        _uniform_init(self, "bias", (3 * H,), stdv)
        return self

    def init_hidden(self, batch_size):
        return jnp.zeros((batch_size, self.hidden_size))

    def pre_apply(self, params, x):
        return jnp.einsum("ntf,gf->ntg", x, params["i2h"]) + params["bias"]

    def cell_apply(self, params, pre_t, h):
        H = self.hidden_size
        hh = jnp.dot(h, params["h2h"].T)
        r = jax.nn.sigmoid(pre_t[:, 0:H] + hh[:, 0:H])
        z = jax.nn.sigmoid(pre_t[:, H:2 * H] + hh[:, H:2 * H])
        n = jnp.tanh(pre_t[:, 2 * H:3 * H] + r * hh[:, 2 * H:3 * H])
        h_new = (1 - z) * n + z * h
        return h_new, h_new


class ConvLSTMPeephole(Cell):
    """Convolutional LSTM with peepholes (reference nn/ConvLSTMPeephole.scala).
    State maps are (N, C, H, W); gates via 2-D convs."""

    def __init__(self, input_size: int, output_size: int, kernel_i: int,
                 kernel_c: int, stride: int = 1, with_peephole: bool = True):
        super().__init__()
        self.input_size, self.output_size = input_size, output_size
        self.kernel_i, self.kernel_c = kernel_i, kernel_c
        self.with_peephole = with_peephole
        self._spatial = None  # lazily known from input
        self.reset()

    def reset(self):
        C_in, C_out = self.input_size, self.output_size
        ki, kc = self.kernel_i, self.kernel_c
        stdv = 1.0 / math.sqrt(C_out * kc * kc)
        _uniform_init(self, "wi", (4 * C_out, C_in, ki, ki), stdv)
        _uniform_init(self, "wh", (4 * C_out, C_out, kc, kc), stdv)
        _uniform_init(self, "bias", (4 * C_out,), stdv)
        if self.with_peephole:
            _uniform_init(self, "peep_i", (C_out,), stdv)
            _uniform_init(self, "peep_f", (C_out,), stdv)
            _uniform_init(self, "peep_o", (C_out,), stdv)
        return self

    def init_hidden(self, batch_size, spatial=None):
        spatial = spatial or self._spatial
        h = jnp.zeros((batch_size, self.output_size) + spatial)
        return Table(h, h)

    def _conv(self, x, w):
        from jax import lax

        k = w.shape[-1]
        pad = k // 2
        return lax.conv_general_dilated(
            x, w, (1, 1), [(pad, pad), (pad, pad)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    def cell_apply(self, params, x_t, hidden):
        h, c = hidden[1], hidden[2]
        C = self.output_size
        gates = (self._conv(x_t, params["wi"]) + self._conv(h, params["wh"])
                 + params["bias"][None, :, None, None])
        gi = gates[:, 0:C]
        gf = gates[:, C:2 * C]
        gz = gates[:, 2 * C:3 * C]
        go = gates[:, 3 * C:4 * C]
        if self.with_peephole:
            gi = gi + params["peep_i"][None, :, None, None] * c
            gf = gf + params["peep_f"][None, :, None, None] * c
        i = jax.nn.sigmoid(gi)
        f = jax.nn.sigmoid(gf)
        z = jnp.tanh(gz)
        c_new = f * c + i * z
        if self.with_peephole:
            go = go + params["peep_o"][None, :, None, None] * c_new
        o = jax.nn.sigmoid(go)
        h_new = o * jnp.tanh(c_new)
        return h_new, Table(h_new, c_new)


class Recurrent(AbstractModule):
    """Sequence container: scan the cell over time (reference
    nn/Recurrent.scala:32).  Input (N, T, F) → output (N, T, H)."""

    def __init__(self, cell: Optional[Cell] = None, reverse: bool = False):
        super().__init__()
        self.cell = cell
        self.reverse = reverse

    def add(self, cell: Cell):
        self.cell = cell
        return self

    # param/buffer plumbing delegates to the cell
    def param_tree(self):
        return {"cell": self.cell.param_tree()}

    def set_param_tree(self, tree):
        self.cell.set_param_tree(tree["cell"])

    def grad_tree(self):
        return {"cell": self.cell.grad_tree()}

    def set_grad_tree(self, tree):
        self.cell.set_grad_tree(tree["cell"])

    def buffer_tree(self):
        return {"cell": self.cell.buffer_tree()}

    def set_buffer_tree(self, tree):
        self.cell.set_buffer_tree(tree["cell"])

    def gradient_scale_tree(self):
        return {"cell": self.cell.gradient_scale_tree()}

    def modules_iter(self):
        yield self
        yield from self.cell.modules_iter()

    def reset(self):
        self.cell.reset()
        return self

    def apply_fn(self, params, buffers, x, training=True, rng=None):
        cell, cp = self.cell, params["cell"]
        n = x.shape[0]
        if isinstance(cell, ConvLSTMPeephole):
            cell._spatial = x.shape[3:]
            hidden0 = cell.init_hidden(n, x.shape[3:])
            pre = x
        else:
            hidden0 = cell.init_hidden(n)
            pre = cell.pre_apply(cp, x)
        if self.reverse:
            pre = jnp.flip(pre, axis=1)
        # (N, T, ...) → (T, N, ...) for scan
        pre_t = jnp.moveaxis(pre, 1, 0)

        def step(hidden, p_t):
            out, new_hidden = cell.cell_apply(cp, p_t, hidden)
            return new_hidden, out

        _, outs = jax.lax.scan(step, hidden0, pre_t)
        outs = jnp.moveaxis(outs, 0, 1)
        if self.reverse:
            outs = jnp.flip(outs, axis=1)
        return outs, buffers


class BiRecurrent(AbstractModule):
    """Bidirectional recurrent (reference nn/BiRecurrent.scala): forward +
    reversed scans, merged (default elementwise add, custom merge module
    supported)."""

    def __init__(self, merge: Optional[AbstractModule] = None):
        super().__init__()
        self.fwd: Optional[Recurrent] = None
        self.bwd: Optional[Recurrent] = None
        self.merge = merge

    def add(self, cell: Cell):
        import copy

        self.fwd = Recurrent(cell)
        self.bwd = Recurrent(copy.deepcopy(cell).reset(), reverse=True)
        return self

    def param_tree(self):
        t = {"fwd": self.fwd.param_tree(), "bwd": self.bwd.param_tree()}
        if self.merge is not None:
            t["merge"] = self.merge.param_tree()
        return t

    def set_param_tree(self, tree):
        self.fwd.set_param_tree(tree["fwd"])
        self.bwd.set_param_tree(tree["bwd"])
        if self.merge is not None:
            self.merge.set_param_tree(tree["merge"])

    def gradient_scale_tree(self):
        t = {"fwd": self.fwd.gradient_scale_tree(),
             "bwd": self.bwd.gradient_scale_tree()}
        if self.merge is not None:
            t["merge"] = self.merge.gradient_scale_tree()
        return t

    def grad_tree(self):
        t = {"fwd": self.fwd.grad_tree(), "bwd": self.bwd.grad_tree()}
        if self.merge is not None:
            t["merge"] = self.merge.grad_tree()
        return t

    def set_grad_tree(self, tree):
        self.fwd.set_grad_tree(tree["fwd"])
        self.bwd.set_grad_tree(tree["bwd"])
        if self.merge is not None:
            self.merge.set_grad_tree(tree["merge"])

    def buffer_tree(self):
        return {"fwd": self.fwd.buffer_tree(), "bwd": self.bwd.buffer_tree()}

    def set_buffer_tree(self, tree):
        self.fwd.set_buffer_tree(tree["fwd"])
        self.bwd.set_buffer_tree(tree["bwd"])

    def modules_iter(self):
        yield self
        yield from self.fwd.modules_iter()
        yield from self.bwd.modules_iter()

    def apply_fn(self, params, buffers, x, training=True, rng=None):
        fo, _ = self.fwd.apply_fn(params["fwd"], buffers["fwd"], x, training, rng)
        bo, _ = self.bwd.apply_fn(params["bwd"], buffers["bwd"], x, training, rng)
        if self.merge is None:
            return fo + bo, buffers
        out, _ = self.merge.apply_fn(params["merge"], {}, Table(fo, bo),
                                     training, rng)
        return out, buffers


class TimeDistributed(AbstractModule):
    """Apply a module at every timestep (reference nn/TimeDistributed.scala):
    fold T into the batch dim — one big batched apply, no loop."""

    def __init__(self, module: AbstractModule):
        super().__init__()
        self.module = module

    def param_tree(self):
        return {"m": self.module.param_tree()}

    def set_param_tree(self, tree):
        self.module.set_param_tree(tree["m"])

    def gradient_scale_tree(self):
        return {"m": self.module.gradient_scale_tree()}

    def grad_tree(self):
        return {"m": self.module.grad_tree()}

    def set_grad_tree(self, tree):
        self.module.set_grad_tree(tree["m"])

    def buffer_tree(self):
        return {"m": self.module.buffer_tree()}

    def set_buffer_tree(self, tree):
        self.module.set_buffer_tree(tree["m"])

    def modules_iter(self):
        yield self
        yield from self.module.modules_iter()

    def apply_fn(self, params, buffers, x, training=True, rng=None):
        n, t = x.shape[0], x.shape[1]
        flat = x.reshape((n * t,) + x.shape[2:])
        out, nb = self.module.apply_fn(params["m"], buffers["m"], flat,
                                       training, rng)
        return out.reshape((n, t) + out.shape[1:]), {"m": nb}
