"""Linear-algebra layers (reference: Linear.scala:44, Bilinear, CMul,
CAdd, Mul, Add, MulConstant, AddConstant, MM, MV, Cosine, Euclidean,
LookupTable).

The reference lowers Linear onto MKL gemm with a ones-vector bias trick
(Linear.scala:44); here it's one ``jnp.dot`` on the MXU with the bias
add fused by XLA.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..utils.table import Table
from .initialization import IN_OUT, ONE_D, RandomUniform, Zeros
from .module import TensorModule


class Linear(TensorModule):
    """y = x W^T + b (reference nn/Linear.scala:44)."""

    def __init__(self, input_size: int, output_size: int,
                 with_bias: bool = True, w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.reset()

    def reset(self):
        w_init = self._init_methods.get("weight", (RandomUniform(), None))[0]
        b_init = self._init_methods.get("bias", (RandomUniform(), None))[0]
        self._register_param("weight",
                             w_init.init((self.output_size, self.input_size), IN_OUT))
        if self.with_bias:
            self._register_param("bias",
                                 b_init.init((self.output_size,), ONE_D))
        return self

    def _apply(self, params, buffers, x, training, rng):
        w = params["weight"]
        x = x.astype(w.dtype)
        if jnp.dtype(w.dtype).itemsize < 8:
            # f32/bf16 compute: accumulate f32 on the MXU
            y = jnp.dot(x, w.T,
                        preferred_element_type=jnp.float32).astype(w.dtype)
        else:
            # f64 (gradient-checker precision): never downcast silently
            y = jnp.dot(x, w.T)
        if self.with_bias:
            y = y + params["bias"]
        return y, buffers


class Bilinear(TensorModule):
    """y_k = x1^T W_k x2 + b_k over a Table(x1, x2) (reference nn/Bilinear.scala)."""

    def __init__(self, input_size1: int, input_size2: int, output_size: int,
                 bias_res: bool = True):
        super().__init__()
        self.input_size1, self.input_size2 = input_size1, input_size2
        self.output_size = output_size
        self.bias_res = bias_res
        self.reset()

    def reset(self):
        w_init = self._init_methods.get("weight", (RandomUniform(), None))[0]
        shape = (self.output_size, self.input_size1, self.input_size2)
        self._register_param("weight", w_init.init(shape, ONE_D))
        if self.bias_res:
            b_init = self._init_methods.get("bias", (RandomUniform(), None))[0]
            self._register_param("bias", b_init.init((self.output_size,), ONE_D))
        return self

    def _apply(self, params, buffers, inp, training, rng):
        x1, x2 = inp[1], inp[2]
        # (N, I1) x (K, I1, I2) x (N, I2) -> (N, K)
        y = jnp.einsum("ni,kij,nj->nk", x1, params["weight"], x2)
        if self.bias_res:
            y = y + params["bias"]
        return y, buffers


class CMul(TensorModule):
    """Learned componentwise scale, broadcast by size (reference nn/CMul.scala)."""

    def __init__(self, size):
        super().__init__()
        self.size = tuple(size)
        self.reset()

    def reset(self):
        w_init = self._init_methods.get("weight", (RandomUniform(), None))[0]
        self._register_param("weight", w_init.init(self.size, ONE_D))
        return self

    def _apply(self, params, buffers, x, training, rng):
        w = params["weight"]
        if w.ndim < x.ndim:
            w = w.reshape((1,) * (x.ndim - w.ndim) + w.shape)
        return x * w, buffers


class CAdd(TensorModule):
    """Learned componentwise bias (reference nn/CAdd.scala)."""

    def __init__(self, size):
        super().__init__()
        self.size = tuple(size)
        self.reset()

    def reset(self):
        b_init = self._init_methods.get("bias", (RandomUniform(), None))[0]
        self._register_param("bias", b_init.init(self.size, ONE_D))
        return self

    def _apply(self, params, buffers, x, training, rng):
        b = params["bias"]
        if b.ndim < x.ndim:
            b = b.reshape((1,) * (x.ndim - b.ndim) + b.shape)
        return x + b, buffers


class Mul(TensorModule):
    """Single learned scalar multiplier (reference nn/Mul.scala)."""

    def __init__(self):
        super().__init__()
        self.reset()

    def reset(self):
        self._register_param("weight", RandomUniform().init((1,), ONE_D))
        return self

    def _apply(self, params, buffers, x, training, rng):
        return x * params["weight"][0], buffers


class Add(TensorModule):
    """Learned bias vector added to input (reference nn/Add.scala)."""

    def __init__(self, input_size: int):
        super().__init__()
        self.input_size = input_size
        self.reset()

    def reset(self):
        b_init = self._init_methods.get("bias", (RandomUniform(), None))[0]
        self._register_param("bias", b_init.init((self.input_size,), ONE_D))
        return self

    def _apply(self, params, buffers, x, training, rng):
        return x + params["bias"], buffers


class MulConstant(TensorModule):
    def __init__(self, constant_scalar: float, inplace: bool = False):
        super().__init__()
        self.constant_scalar = constant_scalar

    def _apply(self, params, buffers, x, training, rng):
        return x * self.constant_scalar, buffers


class AddConstant(TensorModule):
    def __init__(self, constant_scalar: float, inplace: bool = False):
        super().__init__()
        self.constant_scalar = constant_scalar

    def _apply(self, params, buffers, x, training, rng):
        return x + self.constant_scalar, buffers


class MM(TensorModule):
    """Matrix multiply of a Table(a, b) (reference nn/MM.scala)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False):
        super().__init__()
        self.trans_a, self.trans_b = trans_a, trans_b

    def _apply(self, params, buffers, inp, training, rng):
        a, b = inp[1], inp[2]
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b), buffers


class MV(TensorModule):
    """Matrix-vector multiply of Table(mat, vec) (reference nn/MV.scala)."""

    def __init__(self, trans: bool = False):
        super().__init__()
        self.trans = trans

    def _apply(self, params, buffers, inp, training, rng):
        m, v = inp[1], inp[2]
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v), buffers


class Cosine(TensorModule):
    """Cosine similarity against learned weight rows (reference nn/Cosine.scala)."""

    def __init__(self, input_size: int, output_size: int):
        super().__init__()
        self.input_size, self.output_size = input_size, output_size
        self.reset()

    def reset(self):
        w_init = self._init_methods.get("weight", (RandomUniform(), None))[0]
        self._register_param("weight",
                             w_init.init((self.output_size, self.input_size), IN_OUT))
        return self

    def _apply(self, params, buffers, x, training, rng):
        w = params["weight"]
        xn = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-12)
        wn = w / (jnp.linalg.norm(w, axis=-1, keepdims=True) + 1e-12)
        return jnp.dot(xn, wn.T), buffers


class Euclidean(TensorModule):
    """Output = ||x - w_j|| per row j (reference nn/Euclidean.scala)."""

    def __init__(self, input_size: int, output_size: int, fast_backward=True):
        super().__init__()
        self.input_size, self.output_size = input_size, output_size
        self.reset()

    def reset(self):
        w_init = self._init_methods.get("weight", (RandomUniform(), None))[0]
        self._register_param("weight",
                             w_init.init((self.input_size, self.output_size), ONE_D))
        return self

    def _apply(self, params, buffers, x, training, rng):
        w = params["weight"]  # (in, out)
        diff = x[..., :, None] - w[None, :, :]
        return jnp.sqrt(jnp.sum(diff * diff, axis=-2) + 1e-12), buffers


class LookupTable(TensorModule):
    """Embedding with optional max-norm renorm (reference nn/LookupTable.scala).

    Indices are 1-based floats (Torch convention); padding_value rows can
    be zeroed.  maxNorm renorm of touched rows is applied functionally.
    """

    def __init__(self, n_index: int, n_output: int, padding_value: float = 0,
                 max_norm: float = float("inf"), norm_type: float = 2.0,
                 should_scale_grad_by_freq: bool = False, w_regularizer=None):
        super().__init__()
        self.n_index, self.n_output = n_index, n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        self.norm_type = norm_type
        self.w_regularizer = w_regularizer
        self.reset()

    def reset(self):
        from .initialization import RandomNormal

        w_init = self._init_methods.get("weight", (RandomNormal(0, 1), None))[0]
        self._register_param("weight",
                             w_init.init((self.n_index, self.n_output), ONE_D))
        return self

    def _apply(self, params, buffers, x, training, rng):
        w = params["weight"]
        if self.max_norm != float("inf"):
            norms = jnp.linalg.norm(w, ord=self.norm_type, axis=1, keepdims=True)
            w = jnp.where(norms > self.max_norm, w * self.max_norm / (norms + 1e-7), w)
        idx = x.astype(jnp.int32) - 1
        out = jnp.take(w, jnp.clip(idx, 0, self.n_index - 1), axis=0)
        if self.padding_value != 0:
            mask = (x.astype(jnp.int32) == int(self.padding_value))
            out = jnp.where(mask[..., None], 0.0, out)
        return out, buffers
