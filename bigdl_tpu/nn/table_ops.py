"""Elementwise table ops (reference nn/CAddTable.scala etc., SURVEY §2.4)."""
from __future__ import annotations

import jax.numpy as jnp

from .module import AbstractModule


class _TableReduce(AbstractModule):
    def _reduce(self, a, b):
        raise NotImplementedError

    def _apply(self, params, buffers, inp, training, rng):
        out = inp[1]
        for i in range(2, inp.length() + 1):
            out = self._reduce(out, inp[i])
        return out, buffers


class CAddTable(_TableReduce):
    def __init__(self, inplace: bool = False):
        super().__init__()

    def _reduce(self, a, b):
        return a + b


class CSubTable(_TableReduce):
    def _reduce(self, a, b):
        return a - b


class CMulTable(_TableReduce):
    def _reduce(self, a, b):
        return a * b


class CDivTable(_TableReduce):
    def _reduce(self, a, b):
        return a / b


class CMaxTable(_TableReduce):
    def _reduce(self, a, b):
        return jnp.maximum(a, b)


class CMinTable(_TableReduce):
    def _reduce(self, a, b):
        return jnp.minimum(a, b)
