"""AbstractModule — the layer protocol, rebuilt TPU-first.

Reference contract (nn/abstractnn/AbstractModule.scala:54): mutable
modules with explicit ``updateOutput`` / ``updateGradInput`` /
``accGradParameters``, ``parameters()`` returning (weights, gradWeights),
``getParameters()`` returning flattened views, containers composing
children, timing counters on forward/backward.

TPU-first redesign (SURVEY §7.1): every module's compute is ONE pure
function

    apply_fn(params, buffers, input, training, rng) -> (output, new_buffers)

where ``params``/``buffers`` are pytrees of jax arrays.  The Torch-style
mutable API (``forward``/``backward``/``zero_grad_parameters``) is a thin
eager shell over this pure core: ``backward`` is derived from ``jax.vjp``
of the pure apply — there are no hand-written backward passes anywhere in
the framework, XLA differentiates and fuses.  Optimizers never call the
eager shell; they trace ``apply_fn`` of the whole model into a single
jitted (and, distributed, shard_mapped) train step.

``Activity`` = jax array | Table | list/tuple of activities (pytree).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.rng import next_jax_key
from ..utils.table import Table
from .initialization import DEFAULT_FORMAT, InitializationMethod

Activity = Any  # jax array | Table | nested list/tuple


def to_array(x):
    """Unwrap Tensor facade / numpy into raw jax arrays at the API boundary."""
    from ..tensor.tensor import Tensor

    if isinstance(x, Tensor):
        return x.data
    if isinstance(x, (list, tuple)):
        return type(x)(to_array(v) for v in x)
    if isinstance(x, Table):
        out = Table()
        for k, v in x.items():
            out[k] = to_array(v)
        return out
    if isinstance(x, (np.ndarray, float, int)):
        return jnp.asarray(x)
    return x


class AbstractModule:
    """Base layer.  Subclasses define ``_build()`` (register params) and
    ``_apply(params, buffers, input, training, rng) -> (output, new_buffers)``.

    Stateless layers only override ``_apply`` and ignore buffers.
    """

    def __init__(self):
        self.params: Dict[str, jax.Array] = {}
        self.grads: Dict[str, jax.Array] = {}
        self.buffers: Dict[str, jax.Array] = {}
        self.output: Activity = None
        self.grad_input: Activity = None
        self.is_training = True
        self.name: Optional[str] = None
        self.forward_time = 0.0
        self.backward_time = 0.0
        self.scale_w = 1.0
        self.scale_b = 1.0
        self._init_methods: Dict[str, Tuple[InitializationMethod, Any]] = {}
        self._last_rng = None
        self._node = None  # lazily-created graph node (see Graph container)

    # ------------------------------------------------------------------
    # functional core
    # ------------------------------------------------------------------
    def _apply(self, params, buffers, inp, training: bool, rng):
        raise NotImplementedError(
            f"{type(self).__name__} must implement _apply")

    def apply_fn(self, params, buffers, inp, training: bool = True, rng=None):
        """The pure forward.  Containers override to route children."""
        return self._apply(params, buffers, inp, training, rng)

    # ------------------------------------------------------------------
    # parameter / buffer pytrees
    # ------------------------------------------------------------------
    def param_tree(self):
        return dict(self.params)

    def set_param_tree(self, tree):
        self.params = dict(tree)

    def grad_tree(self):
        return dict(self.grads)

    def set_grad_tree(self, tree):
        self.grads = dict(tree)

    def buffer_tree(self):
        return dict(self.buffers)

    def set_buffer_tree(self, tree):
        self.buffers = dict(tree)

    def _register_param(self, name: str, value: jax.Array):
        self.params[name] = value
        self.grads[name] = jnp.zeros_like(value)

    def _register_buffer(self, name: str, value: jax.Array):
        self.buffers[name] = value

    # ------------------------------------------------------------------
    # Torch-style eager API (AbstractModule.scala:213-268)
    # ------------------------------------------------------------------
    def update_output(self, inp: Activity) -> Activity:
        inp = to_array(inp)
        if self._last_rng is None:
            self._last_rng = next_jax_key()
        out, new_buf = self.apply_fn(self.param_tree(), self.buffer_tree(),
                                     inp, self.is_training, self._last_rng)
        self.set_buffer_tree(new_buf)
        self.output = out
        return out

    def forward(self, inp: Activity) -> Activity:
        t0 = time.time()
        self._last_rng = next_jax_key()
        out = self.update_output(inp)
        self.forward_time += time.time() - t0
        return out

    def __call__(self, *args):
        """``layer(x)`` → eager forward; ``layer(node)`` / ``layer([n1, n2])``
        → graph wiring (reference ``inputs(...)``, AbstractModule.scala:539)."""
        from .graph import ModuleNode

        if len(args) == 1 and isinstance(args[0], ModuleNode):
            return self.inputs(args[0])
        if (len(args) >= 1 and isinstance(args[0], (list, tuple))
                and args[0] and all(isinstance(a, ModuleNode) for a in args[0])):
            return self.inputs(*args[0])
        if len(args) > 1 and all(isinstance(a, ModuleNode) for a in args):
            return self.inputs(*args)
        if len(args) == 1:
            return self.forward(args[0])
        return self.forward(list(args))

    def inputs(self, *nodes):
        from .graph import ModuleNode

        node = ModuleNode(self)
        for n in nodes:
            n.add_edge(node)
        return node

    def _vjp(self, inp: Activity):
        inp = to_array(inp)
        ptree = self.param_tree()
        btree = self.buffer_tree()
        rng = self._last_rng if self._last_rng is not None else next_jax_key()

        def f(p, x):
            return self.apply_fn(p, btree, x, self.is_training, rng)[0]

        return jax.vjp(f, ptree, inp)

    def update_grad_input(self, inp: Activity, grad_output: Activity) -> Activity:
        _, vjp = self._vjp(inp)
        _, gi = vjp(to_array(grad_output))
        self.grad_input = gi
        return gi

    def acc_grad_parameters(self, inp: Activity, grad_output: Activity):
        _, vjp = self._vjp(inp)
        gp, _ = vjp(to_array(grad_output))
        self._accumulate(gp)

    def backward(self, inp: Activity, grad_output: Activity) -> Activity:
        """One vjp computes both gradInput and parameter gradients —
        mirrors the reference's fused ``backward`` (AbstractModule.scala:231)."""
        t0 = time.time()
        _, vjp = self._vjp(inp)
        gp, gi = vjp(to_array(grad_output))
        self._accumulate(gp)
        self.grad_input = gi
        self.backward_time += time.time() - t0
        return gi

    def _accumulate(self, grad_param_tree):
        cur = self.grad_tree()
        scaled = jax.tree_util.tree_map(
            lambda g, s: g * s if s != 1.0 else g,
            grad_param_tree, self.gradient_scale_tree())
        new = jax.tree_util.tree_map(lambda a, b: a + b, cur, scaled)
        self.set_grad_tree(new)

    def gradient_scale_tree(self):
        """Per-leaf gradient scale factors — the reference's
        setScaleW/setScaleB applied in accGradParameters
        (AbstractModule.scala:70-101).  Same structure as param_tree;
        derived from it path-wise so modules with custom param_tree
        layouts stay consistent."""
        def scale_of(path, _leaf):
            key = str(getattr(path[-1], "key", "")) if path else ""
            return self.scale_b if "bias" in key else self.scale_w

        return jax.tree_util.tree_map_with_path(scale_of, self.param_tree())

    # ------------------------------------------------------------------
    # parameter surface (AbstractModule.scala:284-310)
    # ------------------------------------------------------------------
    def parameters(self) -> Tuple[List[jax.Array], List[jax.Array]]:
        """(weights, gradWeights) as flat lists over the module tree."""
        p_leaves = jax.tree_util.tree_leaves(self.param_tree())
        g_leaves = jax.tree_util.tree_leaves(self.grad_tree())
        return p_leaves, g_leaves

    def get_weights(self) -> List[np.ndarray]:
        """Weights as numpy arrays, in ``parameters()`` order (reference
        pyspark Layer.get_weights, nn/layer.py:308)."""
        return [np.asarray(p) for p in
                jax.tree_util.tree_leaves(self.param_tree())]

    def set_weights(self, weights):
        """Assign weights from a list of arrays in ``parameters()`` order
        (reference pyspark Layer.set_weights, nn/layer.py:263)."""
        tree = self.param_tree()
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if len(weights) != len(leaves):
            raise ValueError(
                f"expected {len(leaves)} weight arrays, got {len(weights)}")
        new_leaves = []
        for cur, w in zip(leaves, weights):
            w = jnp.asarray(w, cur.dtype)
            if w.shape != cur.shape:
                raise ValueError(
                    f"weight shape {w.shape} != expected {cur.shape}")
            new_leaves.append(w)
        self.set_param_tree(jax.tree_util.tree_unflatten(treedef,
                                                         new_leaves))
        return self

    def update_parameters(self, learning_rate: float):
        """Debug-only in-place SGD step from the eager grads (reference
        pyspark Layer.update_parameters, nn/layer.py:201: 'for debug
        only, please use optimizer.optimize() in production')."""
        self.set_param_tree(jax.tree_util.tree_map(
            lambda p, g: p - learning_rate * g,
            self.param_tree(), self.grad_tree()))
        return self

    def test(self, dataset, batch_size: int = 128, v_methods=None):
        """Model-quality benchmark (reference pyspark Layer.test →
        modelTest): ``evaluate(dataset, v_methods, batch_size)`` with the
        pyspark argument order."""
        if not v_methods:
            raise ValueError(
                "test() needs at least one ValidationMethod (e.g. "
                "[Top1Accuracy()]) — an empty list would run the full "
                "eval forward and return no metrics")
        return self.evaluate(dataset, v_methods, batch_size)

    def get_parameters(self) -> Tuple[jax.Array, jax.Array]:
        """Flattened (weight, grad) pair (reference Module.flatten:80).

        On TPU there is no aliased flat storage — this returns 1-D
        concatenations; ``set_flat_parameters`` writes back.
        """
        from jax.flatten_util import ravel_pytree

        flat_w, _ = ravel_pytree(self.param_tree())
        flat_g, _ = ravel_pytree(self.grad_tree())
        if flat_w.size == 0:
            return jnp.zeros((0,)), jnp.zeros((0,))
        return flat_w, flat_g

    def set_flat_parameters(self, flat_w):
        from jax.flatten_util import ravel_pytree

        _, unravel = ravel_pytree(self.param_tree())
        self.set_param_tree(unravel(jnp.asarray(flat_w)))
        return self

    def n_parameters(self) -> int:
        return sum(int(p.size) for p in jax.tree_util.tree_leaves(self.param_tree()))

    def zero_grad_parameters(self):
        self.set_grad_tree(jax.tree_util.tree_map(jnp.zeros_like, self.grad_tree()))
        return self

    # ------------------------------------------------------------------
    # mode / naming / reset (AbstractModule.scala:317-380)
    # ------------------------------------------------------------------
    def training(self):
        self.is_training = True
        return self

    def evaluate(self, *args, **kwargs):
        """No-arg: switch to eval mode.  With a dataset: distributed eval
        (reference AbstractModule.evaluate:571) — routed to Evaluator."""
        if not args:
            self.is_training = False
            return self
        from ..optim.evaluator import Evaluator

        return Evaluator(self).test(*args, **kwargs)

    def set_name(self, name: str):
        self.name = name
        return self

    def get_name(self) -> str:
        return self.name or type(self).__name__

    def set_init_method(self, weight_init: Optional[InitializationMethod] = None,
                        bias_init: Optional[InitializationMethod] = None):
        if weight_init is not None:
            self._init_methods["weight"] = (weight_init, DEFAULT_FORMAT)
        if bias_init is not None:
            self._init_methods["bias"] = (bias_init, DEFAULT_FORMAT)
        self.reset()
        return self

    def set_scale_w(self, w):
        self.scale_w = w
        return self

    def set_scale_b(self, b):
        self.scale_b = b
        return self

    def reset(self):
        """Re-draw parameters (subclasses with params override)."""
        return self

    # ------------------------------------------------------------------
    # traversal / timing (Container.getTimes analogue)
    # ------------------------------------------------------------------
    def modules_iter(self):
        yield self

    def get_times(self):
        return [(m.get_name(), m.forward_time, m.backward_time)
                for m in self.modules_iter()]

    def reset_times(self):
        for m in self.modules_iter():
            m.forward_time = 0.0
            m.backward_time = 0.0
        return self

    def find_module(self, name: str):
        for m in self.modules_iter():
            if m.get_name() == name:
                return m
        return None

    # ------------------------------------------------------------------
    # clone / save / predict
    # ------------------------------------------------------------------
    def clone_module(self) -> "AbstractModule":
        import copy

        return copy.deepcopy(self)

    def save(self, path: str, overwrite: bool = False):
        from ..utils.file_io import save as _save

        _save(self, path, overwrite)
        return self

    def save_torch(self, path: str, overwrite: bool = False):
        """Write this module as a Torch7 ``.t7`` file (reference
        AbstractModule.saveTorch:390 → TorchFile.save)."""
        from ..utils import torch_file

        torch_file.save(self, path, overwrite)
        return self

    def save_caffe(self, prototxt_path: str, model_path: str,
                   use_v2: bool = True, overwrite: bool = False):
        """Write this module as Caffe prototxt+caffemodel (reference
        AbstractModule.saveCaffe, AbstractModule.scala:398)."""
        from ..interop.caffe import CaffePersister

        CaffePersister.persist(prototxt_path, model_path, self,
                               use_v2=use_v2, overwrite=overwrite)
        return self

    def save_tf(self, input_shape, path: str, **kwargs):
        """Write this module as a frozen TF GraphDef (reference
        AbstractModule.saveTF, AbstractModule.scala:405)."""
        from ..interop.tensorflow import TensorflowSaver

        TensorflowSaver.save(self, input_shape, path, **kwargs)
        return self

    def save_weights(self, path: str, overwrite: bool = False):
        from ..utils.file_io import save as _save

        _save(self.param_tree(), path, overwrite)
        return self

    def load_weights(self, path: str):
        from ..utils.file_io import load as _load

        tree = _load(path)
        self.set_param_tree(jax.tree_util.tree_map(jnp.asarray, tree))
        return self

    def predict(self, dataset, batch_size: int = 32, mesh=None):
        """Distributed when given a mesh (reference Predictor.scala:34
        broadcasts + forwards per partition; here a compiled shard_map)."""
        from ..optim.predictor import Predictor

        return Predictor(self, mesh=mesh).predict(dataset, batch_size)

    def predict_class(self, dataset, batch_size: int = 32, mesh=None):
        from ..optim.predictor import Predictor

        return Predictor(self, mesh=mesh).predict_class(dataset, batch_size)

    # -- pickling: jax arrays travel as numpy (checkpoint format seam) ---
    def __getstate__(self):
        state = dict(self.__dict__)
        for key in ("params", "grads", "buffers"):
            state[key] = jax.tree_util.tree_map(
                lambda x: np.asarray(x) if isinstance(x, jax.Array) else x,
                state[key])
        state["output"] = None
        state["grad_input"] = None
        state["_last_rng"] = None
        state["_node"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        for key in ("params", "grads", "buffers"):
            setattr(self, key, jax.tree_util.tree_map(
                lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x,
                getattr(self, key)))

    def __repr__(self):
        return f"{self.get_name()}"


class TensorModule(AbstractModule):
    """Module whose input and output are single tensors (reference
    abstractnn/TensorModule.scala:43)."""


class Container(AbstractModule):
    """Base container (reference nn/Container.scala:40)."""

    def __init__(self, *modules):
        super().__init__()
        self.modules: List[AbstractModule] = list(modules)

    def add(self, module: AbstractModule):
        self.modules.append(module)
        return self

    def __len__(self):
        return len(self.modules)

    def __getitem__(self, i: int) -> AbstractModule:
        return self.modules[i]

    def get(self, i: int) -> AbstractModule:
        """1-based accessor for API parity."""
        return self.modules[i - 1]

    # compose children's pytrees keyed by index
    def param_tree(self):
        return {str(i): m.param_tree() for i, m in enumerate(self.modules)}

    def set_param_tree(self, tree):
        for i, m in enumerate(self.modules):
            m.set_param_tree(tree[str(i)])

    def grad_tree(self):
        return {str(i): m.grad_tree() for i, m in enumerate(self.modules)}

    def set_grad_tree(self, tree):
        for i, m in enumerate(self.modules):
            m.set_grad_tree(tree[str(i)])

    def buffer_tree(self):
        return {str(i): m.buffer_tree() for i, m in enumerate(self.modules)}

    def gradient_scale_tree(self):
        return {str(i): m.gradient_scale_tree()
                for i, m in enumerate(self.modules)}

    def set_buffer_tree(self, tree):
        for i, m in enumerate(self.modules):
            m.set_buffer_tree(tree[str(i)])

    def modules_iter(self):
        yield self
        for m in self.modules:
            yield from m.modules_iter()

    def training(self):
        super().training()
        for m in self.modules:
            m.training()
        return self

    def evaluate(self, *args, **kwargs):
        if args:
            return super().evaluate(*args, **kwargs)
        super().evaluate()
        for m in self.modules:
            m.evaluate()
        return self

    def reset(self):
        for m in self.modules:
            m.reset()
        return self

    def __repr__(self):
        inner = "\n".join(
            "  " + repr(m).replace("\n", "\n  ") for m in self.modules)
        return f"{self.get_name()} {{\n{inner}\n}}"
