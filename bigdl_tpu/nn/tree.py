"""Tree-structured LSTM (reference nn/TreeLSTM.scala:26,
nn/BinaryTreeLSTM.scala:37 — Constituency Tree LSTM).

Tree encoding (reference TensorTree, BinaryTreeLSTM.scala:454-512): a
``(node_number, width)`` tensor per sample; columns ``0..width-2`` hold
1-based child node indices (0 = no child, -1 in column 0 = padding row)
and the LAST column holds ``-1`` for the root or the 1-based leaf index
into the token sequence for leaves.

TPU-first redesign: the reference walks each tree with host recursion,
cloning leaf/composer modules per node with shared weights
(BinaryTreeLSTM.scala:214-276).  Recursion over data-dependent structure
doesn't trace, so here the whole batch of trees is evaluated by a masked
fixed-point iteration: every step computes the composer for ALL nodes as
one batched (B·N, H) matmul and commits only nodes whose children are
both ready.  ``node_number`` iterations guarantee convergence (tree
depth ≤ node count); weight sharing is automatic — one parameter set,
no clones.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.table import Table
from .initialization import ONE_D, RandomUniform
from .module import AbstractModule


class TensorTree:
    """Host-side helper for building/reading the tree tensor
    (reference BinaryTreeLSTM.scala:454-512)."""

    def __init__(self, content: np.ndarray):
        content = np.asarray(content, np.float32)
        assert content.ndim == 2, "TensorTree content must be 2-D"
        self.content = content

    @property
    def node_number(self) -> int:
        return self.content.shape[0]

    def children(self, index: int):
        return self.content[index - 1].astype(np.int64)

    def add_child(self, parent: int, child: int):
        row = self.content[parent - 1]
        for i in range(self.content.shape[1] - 1):
            if row[i] == 0:
                row[i] = child
                return

    def mark_as_root(self, index: int):
        self.content[index - 1, -1] = -1

    def get_root(self) -> int:
        for i in range(self.node_number):
            if int(self.content[i, -1]) == -1:
                return i + 1
        raise RuntimeError("There is no root in the tensor tree")

    def mark_as_leaf(self, index: int, leaf_index: int):
        self.content[index - 1, -1] = leaf_index

    def leaf_index(self, index: int) -> int:
        return int(self.content[index - 1, -1])

    def has_child(self, index: int) -> bool:
        return int(self.content[index - 1, 0]) > 0

    def no_child(self, index: int) -> bool:
        return int(self.content[index - 1, 0]) == 0

    def exists(self, index: int) -> bool:
        return 1 <= index <= self.node_number

    def is_padding(self, index: int) -> bool:
        return int(self.content[index - 1, 0]) == -1


class BinaryTreeLSTM(AbstractModule):
    """Binary (constituency) TreeLSTM (reference BinaryTreeLSTM.scala:37).

    Input: ``Table(embeddings (B, L, input_size), trees (B, N, W))``.
    Output: ``(B, N, hidden_size)`` — the hidden state of every node
    (padding rows zero), matching the reference's ``updateOutput``
    layout (BinaryTreeLSTM.scala:214-259).

    Leaf cell  (createLeafModuleWithGraph, :59-76):
        c = W_c x + b;  h = sigmoid(W_o x + b_o) * tanh(c)   [gate_output]
    Composer  (createComposerWithGraph, :78-110), each gate g:
        g = act(W_l lh + b_l + W_r rh + b_r)
        c = i*u + lf*lc + rf*rc;  h = o * tanh(c)
    """

    def __init__(self, input_size: int, hidden_size: int,
                 gate_output: bool = True):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.gate_output = gate_output
        self.reset()

    def reset(self):
        H, I = self.hidden_size, self.input_size
        n_gates = 5 if self.gate_output else 4  # i, lf, rf, u [, o]

        def uni(name, shape, stdv):
            init = self._init_methods.get(
                name, (RandomUniform(-stdv, stdv), None))[0]
            self._register_param(name, init.init(shape, ONE_D))

        uni("leaf_c_w", (H, I), 1.0 / math.sqrt(I))
        uni("leaf_c_b", (H,), 1.0 / math.sqrt(I))
        if self.gate_output:
            uni("leaf_o_w", (H, I), 1.0 / math.sqrt(I))
            uni("leaf_o_b", (H,), 1.0 / math.sqrt(I))
        stdv = 1.0 / math.sqrt(H)
        uni("comp_l_w", (n_gates * H, H), stdv)
        uni("comp_l_b", (n_gates * H,), stdv)
        uni("comp_r_w", (n_gates * H, H), stdv)
        uni("comp_r_b", (n_gates * H,), stdv)
        return self

    def _apply(self, params, buffers, inp, training, rng):
        x, trees = inp[1], inp[2]
        x = jnp.asarray(x)
        trees = jnp.asarray(trees)
        B, N = trees.shape[0], trees.shape[1]
        H = self.hidden_size

        left = trees[:, :, 0].astype(jnp.int32)    # 1-based; 0 none, -1 pad
        right = trees[:, :, 1].astype(jnp.int32)
        marker = trees[:, :, -1].astype(jnp.int32)  # -1 root / leaf index
        is_leaf = left == 0
        is_pad = left == -1
        is_comp = left > 0

        # --- all leaves at once: one (B, N, I) gather + (B·N, H) matmul
        leaf_pos = jnp.clip(marker - 1, 0, x.shape[1] - 1)
        leaf_in = jnp.take_along_axis(
            x, leaf_pos[:, :, None].astype(jnp.int32), axis=1)  # (B, N, I)
        leaf_c = jnp.einsum("bni,hi->bnh", leaf_in, params["leaf_c_w"]) \
            + params["leaf_c_b"]
        if self.gate_output:
            o = jax.nn.sigmoid(
                jnp.einsum("bni,hi->bnh", leaf_in, params["leaf_o_w"])
                + params["leaf_o_b"])
            leaf_h = o * jnp.tanh(leaf_c)
        else:
            leaf_h = jnp.tanh(leaf_c)

        mask = is_leaf[:, :, None]
        c0 = jnp.where(mask, leaf_c, 0.0)
        h0 = jnp.where(mask, leaf_h, 0.0)
        ready0 = is_leaf | is_pad

        li = jnp.clip(left - 1, 0, N - 1)
        ri = jnp.clip(right - 1, 0, N - 1)

        def gather_nodes(states, idx):
            return jnp.take_along_axis(states, idx[:, :, None], axis=1)

        def body(_, carry):
            c, h, ready = carry
            lc, lh = gather_nodes(c, li), gather_nodes(h, li)
            rc, rh = gather_nodes(c, ri), gather_nodes(h, ri)
            pre = (jnp.einsum("bnh,gh->bng", lh, params["comp_l_w"])
                   + params["comp_l_b"]
                   + jnp.einsum("bnh,gh->bng", rh, params["comp_r_w"])
                   + params["comp_r_b"])
            i_g = jax.nn.sigmoid(pre[..., 0:H])
            lf = jax.nn.sigmoid(pre[..., H:2 * H])
            rf = jax.nn.sigmoid(pre[..., 2 * H:3 * H])
            u = jnp.tanh(pre[..., 3 * H:4 * H])
            cc = i_g * u + lf * lc + rf * rc
            if self.gate_output:
                o_g = jax.nn.sigmoid(pre[..., 4 * H:5 * H])
                hh = o_g * jnp.tanh(cc)
            else:
                hh = jnp.tanh(cc)
            l_ready = jnp.take_along_axis(ready, li, axis=1)
            r_ready = jnp.take_along_axis(ready, ri, axis=1)
            commit = is_comp & l_ready & r_ready & ~ready
            cm = commit[:, :, None]
            return (jnp.where(cm, cc, c), jnp.where(cm, hh, h),
                    ready | commit)

        c, h, _ = jax.lax.fori_loop(0, N, body, (c0, h0, ready0))
        return jnp.where(is_pad[:, :, None], 0.0, h), buffers


class TreeLSTM(BinaryTreeLSTM):
    """Alias base name kept for API parity (reference TreeLSTM.scala:26
    is the abstract parent of BinaryTreeLSTM)."""
