"""Pooling layers → ``lax.reduce_window`` (reference SpatialMaxPooling.scala:43,
SpatialAveragePooling.scala, VolumetricMaxPooling.scala, RoiPooling.scala;
the hand-written NNPrimitive loops disappear into one XLA op)."""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.table import Table
from .module import AbstractModule, TensorModule


def _pool_out(size, k, s, pad, ceil_mode):
    f = math.ceil if ceil_mode else math.floor
    out = int(f((size + 2 * pad - k) / s)) + 1
    if ceil_mode and pad > 0 and (out - 1) * s >= size + pad:
        out -= 1
    return out


def _pool_pads(size, k, s, pad, ceil_mode):
    """Torch-style padding: explicit pad both sides + extra right pad in
    ceil mode so the window count matches.  ``pad=-1`` means TF-style
    SAME (out = ceil(size/stride), asymmetric pad, extra on the right) —
    the TF loader maps SAME pools here."""
    if pad == -1:
        out = -(-size // s)
        total = max((out - 1) * s + k - size, 0)
        return (total // 2, total - total // 2)
    out = _pool_out(size, k, s, pad, ceil_mode)
    needed = (out - 1) * s + k - size - pad
    return (pad, max(needed, pad))


class SpatialMaxPooling(TensorModule):
    """NCHW max pool with ceil/floor modes (reference nn/SpatialMaxPooling.scala:43)."""

    def __init__(self, kw: int, kh: int, dw: Optional[int] = None,
                 dh: Optional[int] = None, pad_w: int = 0, pad_h: int = 0,
                 global_pooling: bool = False):
        super().__init__()
        self.kw, self.kh = kw, kh
        self.dw = dw if dw is not None else kw
        self.dh = dh if dh is not None else kh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.ceil_mode = False
        self.global_pooling = global_pooling

    def ceil(self):
        self.ceil_mode = True
        return self

    def floor(self):
        self.ceil_mode = False
        return self

    def _apply(self, params, buffers, x, training, rng):
        squeeze = False
        if x.ndim == 3:
            x = x[None]
            squeeze = True
        kh, kw = self.kh, self.kw
        if self.global_pooling:
            kh, kw = x.shape[2], x.shape[3]
        ph = _pool_pads(x.shape[2], kh, self.dh, self.pad_h, self.ceil_mode)
        pw = _pool_pads(x.shape[3], kw, self.dw, self.pad_w, self.ceil_mode)
        y = lax.reduce_window(
            x, -jnp.inf, lax.max,
            (1, 1, kh, kw), (1, 1, self.dh, self.dw),
            [(0, 0), (0, 0), ph, pw])
        if squeeze:
            y = y[0]
        return y, buffers


class SpatialAveragePooling(TensorModule):
    """NCHW average pool (reference nn/SpatialAveragePooling.scala).
    ``count_include_pad`` follows the reference default (True)."""

    def __init__(self, kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0, global_pooling: bool = False,
                 ceil_mode: bool = False, count_include_pad: bool = True,
                 divide: bool = True):
        super().__init__()
        self.kw, self.kh, self.dw, self.dh = kw, kh, dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.global_pooling = global_pooling
        self.ceil_mode = ceil_mode
        self.count_include_pad = count_include_pad
        self.divide = divide

    def ceil(self):
        self.ceil_mode = True
        return self

    def _apply(self, params, buffers, x, training, rng):
        squeeze = False
        if x.ndim == 3:
            x = x[None]
            squeeze = True
        kh, kw = self.kh, self.kw
        if self.global_pooling:
            kh, kw = x.shape[2], x.shape[3]
        ph = _pool_pads(x.shape[2], kh, self.dh, self.pad_h, self.ceil_mode)
        pw = _pool_pads(x.shape[3], kw, self.dw, self.pad_w, self.ceil_mode)
        sums = lax.reduce_window(
            x, 0.0, lax.add, (1, 1, kh, kw), (1, 1, self.dh, self.dw),
            [(0, 0), (0, 0), ph, pw])
        if not self.divide:
            y = sums
        elif self.count_include_pad and not (self.pad_h == -1
                                             or self.pad_w == -1):
            y = sums / (kh * kw)
        else:
            # SAME (pad=-1) always divides by the VALID count — TF's
            # AvgPool semantics, which the TF loader relies on.  Counts
            # are identical across batch/channel: reduce a (1,1,H,W)
            # ones plane and broadcast.
            counts = lax.reduce_window(
                jnp.ones((1, 1) + x.shape[2:], x.dtype), 0.0, lax.add,
                (1, 1, kh, kw), (1, 1, self.dh, self.dw),
                [(0, 0), (0, 0), ph, pw])
            y = sums / counts
        if squeeze:
            y = y[0]
        return y, buffers


class VolumetricMaxPooling(TensorModule):
    """NCDHW max pool (reference nn/VolumetricMaxPooling.scala)."""

    def __init__(self, k_t: int, k_w: int, k_h: int, d_t: Optional[int] = None,
                 d_w: Optional[int] = None, d_h: Optional[int] = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        self.k = (k_t, k_h, k_w)
        self.d = (d_t or k_t, d_h or k_h, d_w or k_w)
        self.pad = (pad_t, pad_h, pad_w)

    def _apply(self, params, buffers, x, training, rng):
        squeeze = False
        if x.ndim == 4:
            x = x[None]
            squeeze = True
        y = lax.reduce_window(
            x, -jnp.inf, lax.max,
            (1, 1) + self.k, (1, 1) + self.d,
            [(0, 0), (0, 0)] + [(p, p) for p in self.pad])
        if squeeze:
            y = y[0]
        return y, buffers


class RoiPooling(AbstractModule):
    """ROI max pooling (reference nn/RoiPooling.scala).

    Input: Table(features (N,C,H,W), rois (R,5) rows [batch_idx, x1, y1, x2, y2]).
    Static-shape implementation: each output cell gathers a masked max —
    jit-friendly, R fixed per trace.
    """

    def __init__(self, pooled_w: int, pooled_h: int, spatial_scale: float):
        super().__init__()
        self.pw, self.ph = pooled_w, pooled_h
        self.spatial_scale = spatial_scale

    def _apply(self, params, buffers, inp, training, rng):
        data, rois = inp[1], inp[2]
        N, C, H, W = data.shape

        def one_roi(roi):
            batch = roi[0].astype(jnp.int32) - 1
            x1 = jnp.round(roi[1] * self.spatial_scale).astype(jnp.int32)
            y1 = jnp.round(roi[2] * self.spatial_scale).astype(jnp.int32)
            x2 = jnp.round(roi[3] * self.spatial_scale).astype(jnp.int32)
            y2 = jnp.round(roi[4] * self.spatial_scale).astype(jnp.int32)
            roi_h = jnp.maximum(y2 - y1 + 1, 1)
            roi_w = jnp.maximum(x2 - x1 + 1, 1)
            bin_h = roi_h / self.ph
            bin_w = roi_w / self.pw
            img = data[batch]  # (C, H, W)
            ys = jnp.arange(H)[None, :]
            xs = jnp.arange(W)[None, :]

            def cell(py, px):
                hstart = jnp.floor(py * bin_h).astype(jnp.int32) + y1
                hend = jnp.ceil((py + 1) * bin_h).astype(jnp.int32) + y1
                wstart = jnp.floor(px * bin_w).astype(jnp.int32) + x1
                wend = jnp.ceil((px + 1) * bin_w).astype(jnp.int32) + x1
                hmask = (ys >= jnp.clip(hstart, 0, H)) & (ys < jnp.clip(hend, 0, H))
                wmask = (xs >= jnp.clip(wstart, 0, W)) & (xs < jnp.clip(wend, 0, W))
                mask = (hmask.reshape(1, H, 1) & wmask.reshape(1, 1, W))
                empty = ~jnp.any(mask)
                masked = jnp.where(mask, img, -jnp.inf)
                m = jnp.max(masked, axis=(1, 2))
                return jnp.where(empty, 0.0, m)

            grid = [[cell(py, px) for px in range(self.pw)] for py in range(self.ph)]
            return jnp.stack([jnp.stack(row, -1) for row in grid], -2)  # (C, ph, pw)

        return jax.vmap(one_roi)(rois), buffers
