"""TF-compat ops (reference nn/tf/: Const.scala, Fill.scala, Shape.scala,
SplitAndSelect.scala, StrideSlice.scala — SURVEY §2.4) and Nms
(nn/Nms.scala).

These exist so TensorFlow GraphDefs map onto framework layers
(utils/tf/TensorflowToBigDL pattern table); they are thin jnp ops here.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .module import AbstractModule, TensorModule


class Const(AbstractModule):
    """Emit a constant regardless of input (reference nn/tf/Const.scala)."""

    def __init__(self, value):
        super().__init__()
        self.value = jnp.asarray(value)

    def _apply(self, params, buffers, inp, training, rng):
        return self.value, buffers


class Fill(TensorModule):
    """Input holds the target shape; output is that shape filled with
    ``value`` (reference nn/tf/Fill.scala)."""

    def __init__(self, value):
        super().__init__()
        self.value = value

    def _apply(self, params, buffers, x, training, rng):
        shape = tuple(int(v) for v in np.asarray(x).reshape(-1))
        # output dtype follows the fill value (reference nn/tf/Fill.scala
        # preserves the value's dtype)
        return jnp.full(shape, self.value,
                        jnp.asarray(self.value).dtype), buffers


class Shape(TensorModule):
    """Output the input's shape as a 1-D int32 tensor (reference
    nn/tf/Shape.scala — shapes are integer tensors; consumers needing
    floats convert at the use site)."""

    def _apply(self, params, buffers, x, training, rng):
        return jnp.asarray(x.shape, jnp.int32), buffers


class SplitAndSelect(TensorModule):
    """Split dim into ``num_split`` equal chunks, emit chunk ``index``
    (1-based, reference nn/tf/SplitAndSelect.scala)."""

    def __init__(self, dimension: int, index: int, num_split: int):
        super().__init__()
        self.dimension, self.index, self.num_split = dimension, index, num_split

    def _apply(self, params, buffers, x, training, rng):
        d = (self.dimension - 1 if self.dimension > 0
             else x.ndim + self.dimension)
        size = x.shape[d]
        assert size % self.num_split == 0, (
            f"num_split must evenly divide dim size {size}")
        length = size // self.num_split
        start = (self.index - 1) * length
        return jax.lax.slice_in_dim(x, start, start + length, axis=d), buffers


class StrideSlice(TensorModule):
    """Chained 1-based narrows: specs of (dim, startIdx, endIdx, stride)
    with endIdx exclusive, stride must be 1 (reference nn/tf/StrideSlice.scala)."""

    def __init__(self, slice_specs: Sequence[Tuple[int, int, int, int]]):
        super().__init__()
        assert all(s[3] == 1 for s in slice_specs), "only stride 1 supported"
        self.slice_specs = list(slice_specs)

    def _apply(self, params, buffers, x, training, rng):
        for dim, start, end, _ in self.slice_specs:
            d = dim - 1 if dim > 0 else x.ndim + dim
            x = jax.lax.slice_in_dim(x, start - 1, end - 1, axis=d)
        return x, buffers


class Nms:
    """Greedy non-maximum suppression for detection (reference
    nn/Nms.scala:26): sort by score descending, keep the top box, drop
    boxes whose IoU with it exceeds ``thresh``, repeat.  Box areas use
    the reference's +1 pixel convention ((x2-x1+1)*(y2-y1+1)).

    Host-side helper like the reference (not a Module); the greedy
    data-dependent loop stays on CPU where it belongs — candidate counts
    are tiny post-RPN.
    """

    def nms(self, scores, boxes, thresh: float, indices) -> int:
        scores = np.asarray(scores, np.float32).reshape(-1)
        boxes = np.asarray(boxes, np.float32).reshape(-1, 4)
        n = scores.shape[0]
        if n == 0:
            return 0
        assert len(indices) >= n and boxes.shape[1] == 4
        x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
        areas = (x2 - x1 + 1) * (y2 - y1 + 1)
        order = np.argsort(-scores, kind="stable")
        suppressed = np.zeros(n, bool)
        count = 0
        for i in range(n):
            cur = order[i]
            if suppressed[cur]:
                continue
            indices[count] = cur + 1  # 1-based like the reference
            count += 1
            rest = order[i + 1:]
            rest = rest[~suppressed[rest]]
            if rest.size == 0:
                continue
            w = np.minimum(x2[cur], x2[rest]) - np.maximum(x1[cur], x1[rest]) + 1
            h = np.minimum(y2[cur], y2[rest]) - np.maximum(y1[cur], y1[rest]) + 1
            inter = np.clip(w, 0, None) * np.clip(h, 0, None)
            inter = np.where((w < 0) | (h < 0), 0.0, inter)
            iou = inter / (areas[cur] + areas[rest] - inter)
            suppressed[rest[iou > thresh]] = True
        return count
