"""bigdl_tpu.nn — layer library (reference spark/dl nn/, 151 files).

TPU-first: every layer is a pure ``apply_fn`` over param/buffer pytrees;
the Torch-style mutable API (forward/backward/getParameters) is a shell
(see module.py).
"""
from .module import AbstractModule, Container, TensorModule, to_array
from .initialization import (
    BilinearFiller, ConstInitMethod, InitializationMethod, MsraFiller, Ones,
    RandomNormal, RandomUniform, VariableFormat, Xavier, Zeros,
)
from .containers import (
    Bottle, Concat, ConcatTable, Echo, Identity, MapTable, ParallelTable,
    Sequential,
)
from .graph import Graph, Input, Model, ModuleNode
from .linear import (
    Add, AddConstant, Bilinear, CAdd, CMul, Cosine, Euclidean, Linear,
    LookupTable, MM, MV, Mul, MulConstant,
)
from .embedding import ShardedEmbedding
from .embedding_store import (
    EmbeddingStore, HotRowCache, MigrationCorrupt, StoreMigrating,
    table_checksum,
)
from .activations import (
    Abs, Clamp, ELU, Exp, HardShrink, HardTanh, LeakyReLU, Log, LogSigmoid,
    LogSoftMax, Max, Mean, Min, Power, PReLU, ReLU, ReLU6, RReLU, Sigmoid,
    SoftMax, SoftMin, SoftPlus, SoftShrink, SoftSign, Sqrt, Square, Sum,
    Tanh, TanhShrink, Threshold,
)
from .conv import (
    SpatialConvolution, SpatialConvolutionMap, SpatialDilatedConvolution,
    SpatialFullConvolution, SpatialShareConvolution, TemporalConvolution,
    VolumetricConvolution,
)
from .pooling import (
    RoiPooling, SpatialAveragePooling, SpatialMaxPooling, VolumetricMaxPooling,
)
from .normalization import (
    LayerNorm, RMSNorm,
    BatchNormalization, ImageNormalize, L1Penalty, Normalize,
    SpatialBatchNormalization,
    SpatialContrastiveNormalization, SpatialCrossMapLRN,
    SpatialDivisiveNormalization, SpatialSubtractiveNormalization,
)
from .shape_ops import (
    Contiguous, CosineDistance, DotProduct, FlattenTable, GradientReversal,
    Index, InferReshape, JoinTable, MaskedSelect, MixtureTable, Narrow,
    NarrowTable, Pack, Padding, PairwiseDistance, Replicate, Reshape, Reverse,
    Scale, Select, SelectTable, SpatialZeroPadding, SplitTable, Squeeze,
    Transpose, Unsqueeze, View,
)
from .table_ops import (
    CAddTable, CDivTable, CMaxTable, CMinTable, CMulTable, CSubTable,
)
from .dropout import Dropout
from .criterion import (
    AbsCriterion, AbstractCriterion, BCECriterion, ClassNLLCriterion,
    ClassSimplexCriterion, CosineDistanceCriterion, CosineEmbeddingCriterion,
    CrossEntropyCriterion, DiceCoefficientCriterion, DistKLDivCriterion,
    HingeEmbeddingCriterion, L1Cost, L1HingeEmbeddingCriterion,
    MarginCriterion, MarginRankingCriterion, MSECriterion, MultiCriterion,
    MultiLabelMarginCriterion, MultiLabelSoftMarginCriterion,
    MultiMarginCriterion, ParallelCriterion, SmoothL1Criterion,
    SmoothL1CriterionWithWeights, SoftMarginCriterion, SoftmaxWithCriterion,
    TimeDistributedCriterion,
)
from .attention import MultiHeadAttention
from .recurrent import (
    BiRecurrent, Cell, ConvLSTMPeephole, GRU, LSTM, LSTMPeephole, Recurrent,
    RnnCell, TimeDistributed,
)
from .tree import BinaryTreeLSTM, TensorTree, TreeLSTM
from .tf_ops import Const, Fill, Nms, Shape, SplitAndSelect, StrideSlice
