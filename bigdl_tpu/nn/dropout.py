"""Dropout (reference nn/Dropout.scala:44).

The reference draws bernoulli masks with hand-threaded loops; here the
mask is one ``jax.random.bernoulli`` fused into the step.  The forward
rng is cached by the module shell so eager ``backward`` reuses the same
mask (mirrors the reference caching ``noise``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .module import TensorModule


class Dropout(TensorModule):
    def __init__(self, init_p: float = 0.5, inplace: bool = False,
                 scale: bool = True):
        super().__init__()
        self.p = init_p
        self.scale = scale

    def set_p(self, p: float):
        self.p = p
        return self

    def _apply(self, params, buffers, x, training, rng):
        if not training or self.p <= 0.0:
            return x, buffers
        if rng is None:
            rng = jax.random.PRNGKey(0)
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, x.shape).astype(x.dtype)
        if self.scale:
            mask = mask / keep
        return x * mask, buffers
