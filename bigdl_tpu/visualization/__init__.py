from .crc32c import crc32c, masked_crc32c
from .summary import (
    ElasticSummary, IntegritySummary, ServingSummary, Summary,
    TelemetrySummary, TrainSummary, ValidationSummary, read_scalars,
)
from .writer import EventWriter, FileWriter, RecordWriter
