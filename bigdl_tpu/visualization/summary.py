"""Train/Validation summaries (reference visualization/Summary.scala:32,
TrainSummary.scala:32, ValidationSummary.scala) — scalar + histogram
events, TensorBoard-compatible, with trigger control per tag."""
from __future__ import annotations

import os
import struct
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .proto import (
    decode_fields, encode_event, encode_histogram, encode_summary,
    encode_summary_value,
)
from .writer import FileWriter


def scalar_event(tag: str, value: float, step: int) -> bytes:
    return encode_event(time.time(), step=step, summary=encode_summary(
        [encode_summary_value(tag, simple_value=float(value))]))


def histogram_event(tag: str, values, step: int) -> bytes:
    """Histogram with TF's exponential bucketing (reference Summary.scala:108)."""
    v = np.asarray(values, np.float64).reshape(-1)
    if v.size == 0:
        v = np.zeros(1)
    limits: List[float] = []
    cur = 1e-12
    while cur < 1e20:
        limits.append(cur)
        cur *= 1.1
    limits = sorted(set([-x for x in limits] + [0.0] + limits))
    counts, _ = np.histogram(v, bins=[-np.inf] + limits[1:] + [np.inf])
    histo = encode_histogram(
        float(v.min()), float(v.max()), float(v.size), float(v.sum()),
        float((v * v).sum()), limits, counts.astype(float).tolist())
    return encode_event(time.time(), step=step, summary=encode_summary(
        [encode_summary_value(tag, histo=histo)]))


class Summary:
    def __init__(self, log_dir: str, app_name: str):
        self.log_dir = os.path.join(log_dir, app_name)
        self.writer = FileWriter(self.log_dir)
        self.triggers: Dict[str, object] = {}

    def add_scalar(self, tag: str, value: float, step: int):
        self.writer.add_event(scalar_event(tag, value, step))
        return self

    def add_histogram(self, tag: str, values, step: int):
        self.writer.add_event(histogram_event(tag, values, step))
        return self

    def read_scalar(self, tag: str) -> List[Tuple[int, float]]:
        self.writer.flush()
        return read_scalars(self.log_dir, tag)

    def close(self):
        self.writer.close()


class TrainSummary(Summary):
    """reference TrainSummary.scala:32 — Loss+Throughput every iteration
    by default; LearningRate/Parameters opt-in via set_summary_trigger."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, os.path.join(app_name, "train"))

    def set_summary_trigger(self, name: str, trigger):
        if name not in ("Loss", "Throughput", "LearningRate", "Parameters"):
            raise ValueError(f"unsupported summary tag {name}")
        self.triggers[name] = trigger
        return self


class ValidationSummary(Summary):
    """reference ValidationSummary.scala"""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, os.path.join(app_name, "validation"))


class ServingSummary(Summary):
    """Serving-path metrics stream (``<app>/serving``) — the export
    target of ``serving.metrics.ServingMetrics.to_summary`` (per-
    request p50/p99 latency, queue depth, shed/timeout/trip counts),
    so serving health lands next to the train/validation curves in
    the same tensorboard layout."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, os.path.join(app_name, "serving"))


class ElasticSummary(Summary):
    """Elastic-training metrics stream (``<app>/elastic``) — the export
    target of ``resilience.elastic.ElasticContext``: ``Incarnation``
    (the current membership epoch), ``ClusterSize``, ``Evictions``
    (straggler votes), ``WatchdogTrips`` (hung-collective deadline
    expiries), ``StragglerSkew`` (per-warning step-time skew) and
    ``RecoverySeconds`` (fault detection → first post-recovery step),
    so cluster health lands next to the train/validation curves in the
    same tensorboard layout."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, os.path.join(app_name, "elastic"))


class TelemetrySummary(Summary):
    """Telemetry stream (``<app>/telemetry``) — the export target of
    :meth:`bigdl_tpu.telemetry.Telemetry.to_summary`: the goodput
    ledger (``telemetry/goodput_fraction``, ``telemetry/accounted_
    fraction``, per-category seconds) and headline counters
    (``telemetry/steps_total``, ``telemetry/recovery_windows``), so
    "where did the wall clock go" lands next to the train/validation
    curves in the same tensorboard layout."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, os.path.join(app_name, "telemetry"))


class IntegritySummary(Summary):
    """Integrity/determinism metrics stream (``<app>/integrity``) — the
    export target of the SDC-defense layer
    (``resilience.integrity`` + ``ElasticContext.integrity_vote``):
    ``IntegrityVotes`` (cross-host checksum rounds completed),
    ``IntegrityDisagreements`` (rounds where a minority checksum was
    flagged), ``IntegrityEvictions`` (hosts evicted for silent data
    corruption) and ``FingerprintSteps`` (flight-recorder journal
    length), so corruption evidence lands next to the train/validation
    curves in the same tensorboard layout."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, os.path.join(app_name, "integrity"))


def read_scalars(log_dir: str, tag: str) -> List[Tuple[int, float]]:
    """Read scalar events back (reference tensorboard/FileReader —
    serves the python ``summary_read_scalar`` API)."""
    out = []
    if not os.path.isdir(log_dir):
        return out
    for fname in sorted(os.listdir(log_dir)):
        if "tfevents" not in fname:
            continue
        with open(os.path.join(log_dir, fname), "rb") as f:
            data = f.read()
        pos = 0
        while pos + 12 <= len(data):
            (length,) = struct.unpack("<Q", data[pos:pos + 8])
            pos += 12  # len + len-crc
            record = data[pos:pos + length]
            pos += length + 4  # data + data-crc
            step, summary = 0, None
            for field, wire, val in decode_fields(record):
                if field == 2 and wire == 0:
                    step = val
                elif field == 5 and wire == 2:
                    summary = val
            if summary is None:
                continue
            for field, wire, val in decode_fields(summary):
                if field == 1 and wire == 2:
                    vtag, vval = None, None
                    for f2, w2, v2 in decode_fields(val):
                        if f2 == 1 and w2 == 2:
                            vtag = v2.decode("utf-8")
                        elif f2 == 2 and w2 == 5:
                            vval = v2
                    if vtag == tag and vval is not None:
                        out.append((step, vval))
    return out
