"""Event-file writers (reference visualization/tensorboard/FileWriter.scala:30,
EventWriter.scala:31, RecordWriter.scala): TFRecord framing + async queue."""
from __future__ import annotations

import os
import queue
import struct
import threading
import time

from .crc32c import masked_crc32c
from .proto import encode_event


class RecordWriter:
    """TFRecord framing: len | crc(len) | data | crc(data)
    (reference RecordWriter.scala + Crc32c.java)."""

    def __init__(self, path: str):
        self._f = open(path, "ab")

    def write(self, data: bytes):
        header = struct.pack("<Q", len(data))
        self._f.write(header)
        self._f.write(struct.pack("<I", masked_crc32c(header)))
        self._f.write(data)
        self._f.write(struct.pack("<I", masked_crc32c(data)))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()


class EventWriter:
    """One events file; writes the version header event first
    (reference EventWriter.scala:31)."""

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.bigdl_tpu"
        self.path = os.path.join(log_dir, fname)
        self._rw = RecordWriter(self.path)
        self._rw.write(encode_event(time.time(), file_version="brain.Event:2"))
        self._rw.flush()

    def write_event(self, event: bytes):
        self._rw.write(event)

    def flush(self):
        self._rw.flush()

    def close(self):
        self._rw.flush()
        self._rw.close()


#: queue sentinel: everything enqueued before it is on disk once the
#: drain thread reaches it (FIFO), so close() never races a timeout
#: against in-flight events
_CLOSE = None


class FileWriter:
    """Async queued writer (reference FileWriter.scala:30): producers
    enqueue encoded events, a daemon thread drains to disk.

    ``close()`` drains deterministically: a sentinel is enqueued behind
    every pending event and the drain thread exits when it reaches it —
    a burst of events written immediately before ``close()`` is on disk
    when ``close()`` returns, not dropped by a join timeout."""

    def __init__(self, log_dir: str, flush_secs: float = 2.0):
        self._writer = EventWriter(log_dir)
        self._q: "queue.Queue" = queue.Queue()
        self._closed = False
        self._flush_secs = flush_secs
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def add_event(self, event: bytes):
        if self._closed:
            raise ValueError("FileWriter is closed")
        self._q.put(event)
        return self

    def _run(self):
        last_flush = time.time()
        while True:
            try:
                ev = self._q.get(timeout=0.2)
            except queue.Empty:
                if time.time() - last_flush > self._flush_secs:
                    self._writer.flush()
                    last_flush = time.time()
                continue
            try:
                if ev is _CLOSE:
                    return
                self._writer.write_event(ev)
            finally:
                self._q.task_done()
            if time.time() - last_flush > self._flush_secs:
                self._writer.flush()
                last_flush = time.time()

    def flush(self):
        # join() waits for dequeued-but-unwritten events too (an
        # empty() poll would race the writer thread mid-write)
        self._q.join()
        self._writer.flush()

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._q.put(_CLOSE)
        self._thread.join(timeout=30)
        # belt and braces: if the drain thread died (disk error) or the
        # join timed out, write whatever is still queued on this thread
        # rather than dropping it silently
        while True:
            try:
                ev = self._q.get_nowait()
            except queue.Empty:
                break
            try:
                if ev is not _CLOSE:
                    self._writer.write_event(ev)
            finally:
                self._q.task_done()
        self._writer.close()
