"""Minimal protobuf wire-format encoder for TF Event/Summary messages.

The reference ships ~157k LoC of GENERATED protobuf Java (SURVEY layout
table); the rebuild needs exactly three messages (Event, Summary,
HistogramProto) so they are hand-encoded here — wire-compatible with
TensorBoard, zero codegen.
"""
from __future__ import annotations

import struct
from typing import List, Sequence


def _varint(n: int) -> bytes:
    out = bytearray()
    n &= 0xFFFFFFFFFFFFFFFF
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _double(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def _float(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def _int64(field: int, v: int) -> bytes:
    return _key(field, 0) + _varint(v)


def _packed_doubles(field: int, vals: Sequence[float]) -> bytes:
    payload = b"".join(struct.pack("<d", v) for v in vals)
    return _len_delim(field, payload)


def encode_summary_value(tag: str, simple_value: float = None,
                         histo: bytes = None) -> bytes:
    # Summary.Value: tag=1, simple_value=2, histo=5
    out = _len_delim(1, tag.encode("utf-8"))
    if simple_value is not None:
        out += _float(2, simple_value)
    if histo is not None:
        out += _len_delim(5, histo)
    return out


def encode_histogram(minv: float, maxv: float, num: float, total: float,
                     sum_squares: float, bucket_limits: Sequence[float],
                     buckets: Sequence[float]) -> bytes:
    # HistogramProto: min=1,max=2,num=3,sum=4,sum_squares=5,
    # bucket_limit=6 (packed), bucket=7 (packed)
    return (_double(1, minv) + _double(2, maxv) + _double(3, num)
            + _double(4, total) + _double(5, sum_squares)
            + _packed_doubles(6, bucket_limits) + _packed_doubles(7, buckets))


def encode_summary(values: List[bytes]) -> bytes:
    # Summary: repeated Value value = 1
    return b"".join(_len_delim(1, v) for v in values)


def encode_event(wall_time: float, step: int = None, summary: bytes = None,
                 file_version: str = None) -> bytes:
    # Event: wall_time=1 (double), step=2 (int64), file_version=3, summary=5
    out = _double(1, wall_time)
    if step is not None:
        out += _int64(2, step)
    if file_version is not None:
        out += _len_delim(3, file_version.encode("utf-8"))
    if summary is not None:
        out += _len_delim(5, summary)
    return out


# ---------------------------------------------------------------------------
# decoding (for FileReader — reference visualization/tensorboard/FileReader)
# ---------------------------------------------------------------------------
def _read_varint(buf: bytes, pos: int):
    result, shift = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def decode_fields(buf: bytes):
    """Yield (field_number, wire_type, value) triples."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 1:
            val = struct.unpack("<d", buf[pos:pos + 8])[0]
            pos += 8
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val
