"""CRC32C (Castagnoli) + TFRecord masking (reference java/netty/Crc32c.java).

Pure-python table implementation; fast enough for event-log volume
(SURVEY §2.1 notes native only "if log volume demands").
"""
from __future__ import annotations

_POLY = 0x82F63B78
_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _POLY if _c & 1 else _c >> 1
    _TABLE.append(_c)


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    """TFRecord mask (same constant the reference RecordWriter uses)."""
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF
