"""CRC32C (Castagnoli) + TFRecord masking (reference java/netty/Crc32c.java).

Pure-python table implementation plus a native slicing-by-8 fast path
(native/bigdl_tpu_native.cc, loaded lazily to avoid an import cycle).
"""
from __future__ import annotations

_POLY = 0x82F63B78
_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _POLY if _c & 1 else _c >> 1
    _TABLE.append(_c)


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _native_crc():
    from .. import native

    return native.crc32c if native.available() else crc32c


def masked_crc32c(data: bytes) -> int:
    """TFRecord mask (same constant the reference RecordWriter uses)."""
    crc = _native_crc()(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF
