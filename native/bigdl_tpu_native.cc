// bigdl_tpu native host runtime — the C++ counterpart of the reference's
// native/near-native components (SURVEY §2.1):
//
//  * CRC32C (castagnoli, slicing-by-8) for TFRecord/tensorboard framing
//    (reference java/netty/Crc32c.java)
//  * fp16/bf16 wire codec with compressed-domain accumulate — the
//    FP16CompressedTensor plane (reference
//    parameters/FP16CompressedTensor.scala:26 toFP16/fromFP16/parAdd)
//  * multithreaded image batch assembly: normalize + NHWC->NCHW + stack
//    (reference dataset/image/MTLabeledBGRImgToBatch.scala:46)
//
// Exposed as a flat extern "C" ABI consumed via ctypes — no pybind11
// (not in the image).  All bulk loops are chunked across a std::thread
// pool, mirroring the reference's Engine.default parallel chunks.
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// thread pool (reference utils/ThreadPool.scala:32 invokeAndWait analogue)
// ---------------------------------------------------------------------------
class Pool {
 public:
  explicit Pool(int n) : stop_(false) {
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] {
        for (;;) {
          std::function<void()> job;
          {
            std::unique_lock<std::mutex> lk(m_);
            cv_.wait(lk, [this] { return stop_ || !jobs_.empty(); });
            if (stop_ && jobs_.empty()) return;
            job = std::move(jobs_.front());
            jobs_.pop();
          }
          job();
        }
      });
    }
  }
  ~Pool() {
    {
      std::lock_guard<std::mutex> lk(m_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }
  // run fn(chunk_index) for chunks [0, nchunks) and wait
  void parallel_for(int64_t nchunks, const std::function<void(int64_t)>& fn) {
    if (nchunks <= 1) {
      for (int64_t i = 0; i < nchunks; ++i) fn(i);
      return;
    }
    std::atomic<int64_t> done(0);
    std::mutex dm;
    std::condition_variable dcv;
    for (int64_t i = 0; i < nchunks; ++i) {
      std::function<void()> job = [&, i] {
        fn(i);
        if (done.fetch_add(1) + 1 == nchunks) {
          std::lock_guard<std::mutex> lk(dm);
          dcv.notify_one();
        }
      };
      {
        std::lock_guard<std::mutex> lk(m_);
        jobs_.push(std::move(job));
      }
      cv_.notify_one();
    }
    std::unique_lock<std::mutex> lk(dm);
    dcv.wait(lk, [&] { return done.load() == nchunks; });
  }
  int size() const { return static_cast<int>(workers_.size()); }

 private:
  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> jobs_;
  std::mutex m_;
  std::condition_variable cv_;
  bool stop_;
};

Pool& pool() {
  static Pool p(std::max(2u, std::thread::hardware_concurrency()));
  return p;
}

inline void chunked(int64_t n, int64_t min_chunk,
                    const std::function<void(int64_t, int64_t)>& body) {
  int64_t nthreads = pool().size();
  int64_t chunk = std::max(min_chunk, (n + nthreads - 1) / nthreads);
  int64_t nchunks = (n + chunk - 1) / chunk;
  pool().parallel_for(nchunks, [&](int64_t c) {
    int64_t lo = c * chunk;
    int64_t hi = std::min(n, lo + chunk);
    body(lo, hi);
  });
}

// ---------------------------------------------------------------------------
// CRC32C slicing-by-8
// ---------------------------------------------------------------------------
uint32_t kCrcTable[8][256];
bool init_crc() {
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
    kCrcTable[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = kCrcTable[0][i];
    for (int t = 1; t < 8; ++t) {
      c = kCrcTable[0][c & 0xFF] ^ (c >> 8);
      kCrcTable[t][i] = c;
    }
  }
  return true;
}
const bool crc_inited = init_crc();

}  // namespace

extern "C" {

uint32_t btpu_crc32c(const uint8_t* data, int64_t n, uint32_t crc) {
  crc ^= 0xFFFFFFFFu;
  while (n >= 8) {
    uint32_t lo;
    uint32_t hi;
    std::memcpy(&lo, data, 4);
    std::memcpy(&hi, data + 4, 4);
    lo ^= crc;
    crc = kCrcTable[7][lo & 0xFF] ^ kCrcTable[6][(lo >> 8) & 0xFF] ^
          kCrcTable[5][(lo >> 16) & 0xFF] ^ kCrcTable[4][lo >> 24] ^
          kCrcTable[3][hi & 0xFF] ^ kCrcTable[2][(hi >> 8) & 0xFF] ^
          kCrcTable[1][(hi >> 16) & 0xFF] ^ kCrcTable[0][hi >> 24];
    data += 8;
    n -= 8;
  }
  while (n-- > 0) crc = kCrcTable[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// fp16/bf16 codec (FP16CompressedTensor parity: fp32 -> high-2-bytes
// truncation, i.e. bf16 bit pattern; the reference's "FP16" IS the
// truncated-fp32 format, FP16CompressedTensor.scala:173-199)
// ---------------------------------------------------------------------------
namespace {
inline uint16_t f32_bits_to_bf16(uint32_t bits) {
  if ((bits & 0x7F800000u) == 0x7F800000u && (bits & 0x007FFFFFu)) {
    // NaN: truncate but force a quiet-NaN payload so rounding can't
    // overflow it into ±inf/-0
    return static_cast<uint16_t>((bits >> 16) | 0x0040u);
  }
  // round-to-nearest-even on the truncated mantissa
  uint32_t rounding = 0x7FFFu + ((bits >> 16) & 1u);
  return static_cast<uint16_t>((bits + rounding) >> 16);
}
}  // namespace

void btpu_f32_to_bf16(const float* src, uint16_t* dst, int64_t n) {
  chunked(n, 1 << 15, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      uint32_t bits;
      std::memcpy(&bits, src + i, 4);
      dst[i] = f32_bits_to_bf16(bits);
    }
  });
}

void btpu_bf16_to_f32(const uint16_t* src, float* dst, int64_t n) {
  chunked(n, 1 << 15, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      uint32_t bits = static_cast<uint32_t>(src[i]) << 16;
      std::memcpy(dst + i, &bits, 4);
    }
  });
}

// compressed-domain accumulate: dst[i] += src[i] in bf16 wire format
// (reference FP16CompressedTensor.parAdd:122-152)
void btpu_bf16_add(uint16_t* dst, const uint16_t* src, int64_t n) {
  chunked(n, 1 << 15, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      uint32_t a = static_cast<uint32_t>(dst[i]) << 16;
      uint32_t b = static_cast<uint32_t>(src[i]) << 16;
      float fa;
      float fb;
      std::memcpy(&fa, &a, 4);
      std::memcpy(&fb, &b, 4);
      float s = fa + fb;
      uint32_t bits;
      std::memcpy(&bits, &s, 4);
      dst[i] = f32_bits_to_bf16(bits);
    }
  });
}

// ---------------------------------------------------------------------------
// multithreaded batch assembly (MTLabeledBGRImgToBatch parity):
// n HWC uint8 images -> one NCHW float batch, normalized, one thread per
// image-chunk.
// ---------------------------------------------------------------------------
void btpu_batch_images_u8(const uint8_t* images, int64_t n, int64_t h,
                          int64_t w, int64_t c, const float* mean,
                          const float* stdv, float* out) {
  const int64_t img = h * w * c;
  const int64_t plane = h * w;
  chunked(n, 1, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* src = images + i * img;
      float* dst = out + i * img;
      for (int64_t y = 0; y < h; ++y)
        for (int64_t x = 0; x < w; ++x)
          for (int64_t ch = 0; ch < c; ++ch)
            dst[ch * plane + y * w + x] =
                (static_cast<float>(src[(y * w + x) * c + ch]) - mean[ch]) /
                stdv[ch];
    }
  });
}

// float HWC variant (already-decoded images)
void btpu_batch_images_f32(const float* images, int64_t n, int64_t h,
                           int64_t w, int64_t c, const float* mean,
                           const float* stdv, float* out) {
  const int64_t img = h * w * c;
  const int64_t plane = h * w;
  chunked(n, 1, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const float* src = images + i * img;
      float* dst = out + i * img;
      for (int64_t y = 0; y < h; ++y)
        for (int64_t x = 0; x < w; ++x)
          for (int64_t ch = 0; ch < c; ++ch)
            dst[ch * plane + y * w + x] =
                (src[(y * w + x) * c + ch] - mean[ch]) / stdv[ch];
    }
  });
}

// ---------------------------------------------------------------------------
// Record-file framing scan (the ingest hot loop): walk a TFRecord-framed
// buffer (len | crc(len) | data | crc(data)), verify both masked CRC32Cs,
// and emit (offset, length) pairs for the data payloads.  Returns the
// record count, or -(byte position + 1) at the first corruption.
// ---------------------------------------------------------------------------
namespace {
inline uint32_t masked_crc(const uint8_t* data, int64_t n) {
  uint32_t crc = btpu_crc32c(data, n, 0);
  return (((crc >> 15) | (crc << 17)) + 0xA282EAD8u);
}
inline uint32_t load_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
}  // namespace

int64_t btpu_parse_records(const uint8_t* buf, int64_t n, int64_t* offsets,
                           int64_t* lengths, int64_t max_records,
                           int verify) {
  int64_t pos = 0;
  int64_t count = 0;
  while (pos + 12 <= n && count < max_records) {
    uint64_t len;
    std::memcpy(&len, buf + pos, 8);
    // unsigned check first: a length with high bits set must not wrap
    // negative and slip past the bounds arithmetic below
    if (len > static_cast<uint64_t>(n) ||
        pos + 16 + static_cast<int64_t>(len) > n)
      return -(pos + 1);
    if (verify) {
      if (load_u32(buf + pos + 8) != masked_crc(buf + pos, 8))
        return -(pos + 1);
      if (load_u32(buf + pos + 12 + len) != masked_crc(buf + pos + 12, len))
        return -(pos + 1);
    }
    offsets[count] = pos + 12;
    lengths[count] = static_cast<int64_t>(len);
    ++count;
    pos += 16 + static_cast<int64_t>(len);
  }
  return count;
}

int btpu_num_threads() { return pool().size(); }

}  // extern "C"
